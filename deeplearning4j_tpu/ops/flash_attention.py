"""Fused flash-attention Pallas kernel — forward AND backward.

Why: blockwise attention (ops/attention.py) tops out at ~0.200 est-MFU
at seq 16k (BENCH_baseline.json `attention_longctx_*`): the lax-scan
online softmax round-trips m/l/acc through HBM between small block
matmuls and leaves the MXU idle. The FlashAttention formulation (Dao et
al., 2022) keeps the whole QK^T → online softmax → PV chain for one
query block in VMEM across the entire KV sweep; the backward
(recompute-based, Dao et al. Alg. 4) never materializes the [Tq, Tk]
probability matrix either. A/B numbers live in docs/perf_attention.md;
the dispatch rule that consumes them lives in
ops/attention.py:select_attention_impl.

Layout: the public wrapper takes [batch, time, heads, head_dim] like
dense_attention, folds (batch, heads) into one grid axis, and pads
head_dim to the 128-lane multiple (pad/slice sit OUTSIDE the
custom_vjp, so autodiff handles them). Grid is (batch*heads, q_blocks,
kv_blocks) with KV innermost; m/l/acc live in VMEM scratch and persist
across the KV sweep (TPU grids iterate the last axis innermost).

Positions are passed as int32 ARRAYS, not static python ints: the ring
path (ring_self_attention) offsets KV positions by a TRACED
`axis_index`, so causal masking must compare data, not trace-time
constants. Causal block-skipping still works — `@pl.when` predicates
the whole inner block on `min(kv_pos) <= max(q_pos)`, which on TPU
skips the MXU work for strictly-upper blocks.

The kernel also returns the log-sum-exp per query row (NEG sentinel for
fully-masked rows, matching dense_attention's zero-output convention),
and the custom_vjp accepts a cotangent FOR the lse output: the ring
composition differentiates through the per-hop softmax merge
o = (o1*w1 + o2*w2)/(w1+w2), which reads lse. The lse cotangent folds
into ds = p * (dp - di + g_lse) in the backward kernels.

Autodiff: pallas_call is not differentiable, so `_flash` carries a
custom_vjp (the `lrn` precedent in pallas_kernels.py); forward residuals
are (inputs, o, lse) and the backward runs two more Pallas kernels —
dk/dv with the KV axis as the parallel grid dim, then dq with the Q
axis parallel — both recomputing s and p blockwise from the lse
residual. di = rowsum(o * do) is precomputed outside the kernels.

Gating mirrors lrn: `interpret=True` runs the same kernels on CPU for
tests; the TPU fast path is guarded by flash_attention_supported
(geometry/VMEM) + flash_attention_available (one-time eager compile
probe via pallas_kernels.kernel_probe).
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .pallas_kernels import kernel_probe, pad_axis_to

# Cross-file trace surface (analysis/boundaries.py): decode_attention is
# dispatched inside jitted decode steps (serving/decode.py _step_pure),
# so the JL0xx/JL2xx purity rules must treat it as a traced root here.
__traced__ = ("decode_attention",)

NEG = -1e30  # mask sentinel; matches ops/attention.py (finite: -inf NaNs grads)

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_KV = 128
_LANE = 128          # TPU lane width: head_dim padded to a multiple
_VMEM_BUDGET = 8 * 1024 * 1024  # conservative half of ~16MB/core


def pick_kernel_block(t: int, want: int) -> int:
    """Largest divisor of t that is <= want (t >= 1). Exact tiling keeps
    the kernels free of per-block bounds masking."""
    b = max(1, min(want, t))
    while t % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# Kernels. Shared ref order: positions, mask, tensors. Blocks are
# [1, qb, d] / [1, kb, d] (leading grid axis folded batch*heads);
# q positions are a [tq, 1] column and kv positions a [1, tk] row so the
# causal compare broadcasts to [qb, kb] without an in-kernel transpose.
# ---------------------------------------------------------------------------

def _scores(q_ref, k_ref, qp_ref, kp_ref, km_ref, qs_ref, ks_ref, scale,
            causal, use_mask, use_segs):
    """s = scale * q @ k^T with causal/key/segment masking applied. f32.

    Segment masking reuses the position-array layout: q segments are a
    [qb, 1] column block and kv segments a [1, kb] row block, so the
    equality compare broadcasts to [qb, kb] without a transpose — the
    varlen/packed-batch mask (multiple documents per row; cross-segment
    attention forbidden)."""
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        s = jnp.where(kp_ref[:] <= qp_ref[:], s, NEG)
    if use_mask:
        s = jnp.where(km_ref[:] > 0, s, NEG)
    if use_segs:
        s = jnp.where(qs_ref[0] == ks_ref[0], s, NEG)
    return s


def _skip_when(causal, use_segs, qp_ref, kp_ref, qs_ref, ks_ref, q_block,
               body):
    """Run `body` — under a block-skip predicate when causal and/or
    segment-masked. Causal: the whole KV block is strictly above the
    diagonal iff min(kv_pos) > max(q_pos); positions are traced data, so
    this is a runtime `pl.when`, not a trace-time grid trim (the ring
    path's offsets are traced). Segments: a tile contributes nothing
    when the q tile's segment-id RANGE cannot intersect the kv tile's —
    conservative for arbitrary ids, exact for the packed case (ids
    monotone within a row), and it skips every fully-cross-segment tile
    of a packed batch."""
    from jax.experimental import pallas as pl

    pred = None
    if causal:
        pred = kp_ref[0, 0] <= qp_ref[q_block - 1, 0]
    if use_segs:
        qs, ks = qs_ref[0], ks_ref[0]
        seg_pred = (jnp.min(ks) <= jnp.max(qs)) & \
            (jnp.max(ks) >= jnp.min(qs))
        pred = seg_pred if pred is None else pred & seg_pred
    if pred is not None:
        @pl.when(pred)
        def _():
            body()
    else:
        body()


def _fwd_kernel(qp_ref, kp_ref, km_ref, qs_ref, ks_ref, q_ref, k_ref, v_ref,
                o_ref, lse_ref, m_ref, l_ref, acc_ref, *, scale, causal,
                use_mask, use_segs, nk):
    from jax.experimental import pallas as pl

    j = pl.program_id(2)  # kv block index (innermost)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full(m_ref.shape, NEG, m_ref.dtype)
        l_ref[:] = jnp.zeros(l_ref.shape, l_ref.dtype)
        acc_ref[:] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    def compute():
        s = _scores(q_ref, k_ref, qp_ref, kp_ref, km_ref, qs_ref, ks_ref,
                    scale, causal, use_mask, use_segs)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)
        # Fully-masked so far → m_next == NEG → force p to 0 (exp(0)=1
        # otherwise, counting masked entries into l).
        p = jnp.where(m_next <= NEG / 2, 0.0, jnp.exp(s - m_next))
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_next
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * alpha + pv

    _skip_when(causal, use_segs, qp_ref, kp_ref, qs_ref, ks_ref,
               q_ref.shape[1], compute)

    @pl.when(j == nk - 1)
    def _():
        l, m = l_ref[:], m_ref[:]
        safe = jnp.where(l > 0, l, 1.0)
        # Fully-masked rows: zero output (dense_attention convention) and
        # an lse of NEG so the ring merge treats the hop as weight-0.
        o_ref[0] = (acc_ref[:] * jnp.where(l > 0, 1.0 / safe, 0.0)).astype(
            o_ref.dtype)
        lse_ref[0] = jnp.where(l > 0, m + jnp.log(safe), NEG)


def _recompute_p(q_ref, k_ref, qp_ref, kp_ref, km_ref, qs_ref, ks_ref,
                 lse_ref, scale, causal, use_mask, use_segs):
    """Rebuild the probability block from the lse residual; guard
    fully-masked rows (lse == NEG sentinel) to exact zeros."""
    s = _scores(q_ref, k_ref, qp_ref, kp_ref, km_ref, qs_ref, ks_ref,
                scale, causal, use_mask, use_segs)
    lse = lse_ref[0]  # [qb, 1]
    p = jnp.where(lse <= NEG / 2, 0.0, jnp.exp(s - lse))
    return p


def _bwd_dkv_kernel(qp_ref, kp_ref, km_ref, qs_ref, ks_ref, q_ref, k_ref,
                    v_ref, do_ref, lse_ref, di_ref, gl_ref, dk_ref, dv_ref,
                    dk_acc, dv_acc, *, scale, causal, use_mask, use_segs,
                    nq, acc_dtype):
    from jax.experimental import pallas as pl

    jq = pl.program_id(2)  # q block index (innermost; KV block is parallel)

    @pl.when(jq == 0)
    def _():
        dk_acc[:] = jnp.zeros(dk_acc.shape, dk_acc.dtype)
        dv_acc[:] = jnp.zeros(dv_acc.shape, dv_acc.dtype)

    def compute():
        p = _recompute_p(q_ref, k_ref, qp_ref, kp_ref, km_ref, qs_ref,
                         ks_ref, lse_ref, scale, causal, use_mask,
                         use_segs)
        do = do_ref[0]
        # acc_dtype is the bwd accumulate knob (f32 default; the bf16
        # study in docs/perf_attention.md measures the drift/speed
        # trade): both the running scratch and the per-block matmul
        # accumulate in it.
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dtype).astype(dv_acc.dtype)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        # g_lse folds in here: d lse / d s = p, so the lse cotangent adds
        # p * g_lse — the term the ring's softmax-merge backward needs.
        ds = p * (dp - di_ref[0] + gl_ref[0])
        dk_acc[:] = dk_acc[:] + (jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=acc_dtype) * scale).astype(dk_acc.dtype)

    _skip_when(causal, use_segs, qp_ref, kp_ref, qs_ref, ks_ref,
               q_ref.shape[1], compute)

    @pl.when(jq == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(qp_ref, kp_ref, km_ref, qs_ref, ks_ref, q_ref, k_ref,
                   v_ref, do_ref, lse_ref, di_ref, gl_ref, dq_ref, dq_acc,
                   *, scale, causal, use_mask, use_segs, nk, acc_dtype):
    from jax.experimental import pallas as pl

    jk = pl.program_id(2)  # kv block index (innermost; Q block is parallel)

    @pl.when(jk == 0)
    def _():
        dq_acc[:] = jnp.zeros(dq_acc.shape, dq_acc.dtype)

    def compute():
        p = _recompute_p(q_ref, k_ref, qp_ref, kp_ref, km_ref, qs_ref,
                         ks_ref, lse_ref, scale, causal, use_mask,
                         use_segs)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - di_ref[0] + gl_ref[0])
        dq_acc[:] = dq_acc[:] + (jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype) * scale).astype(dq_acc.dtype)

    _skip_when(causal, use_segs, qp_ref, kp_ref, qs_ref, ks_ref,
               q_ref.shape[1], compute)

    @pl.when(jk == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers over [bh, t, d] arrays.
# ---------------------------------------------------------------------------

def _km_spec(pl, kb, use_mask, kv_axis):
    """key-mask BlockSpec: when no mask the array is a shared [1, tk]
    ones row — every bh grid step maps to row 0."""
    if use_mask:
        return pl.BlockSpec((1, kb), lambda i, j, k:
                            (i, (j, k)[kv_axis - 1]))
    return pl.BlockSpec((1, kb), lambda i, j, k: (0, (j, k)[kv_axis - 1]))


def _seg_specs(pl, qb, kb, use_segs, q_axis, kv_axis):
    """segment-id BlockSpecs: qs is a [bh, tq, 1] column array and ks a
    [bh, 1, tk] row array, so in-kernel qs_ref[0]/ks_ref[0] broadcast to
    [qb, kb] like the position arrays. When segments are off both are
    shared [1, ...] zero arrays and every bh grid step maps to row 0
    (the _km_spec trick)."""
    bh = (lambda i: i) if use_segs else (lambda i: 0)
    qspec = pl.BlockSpec((1, qb, 1),
                         lambda i, j, k: (bh(i), (j, k)[q_axis - 1], 0))
    kspec = pl.BlockSpec((1, 1, kb),
                         lambda i, j, k: (bh(i), 0, (j, k)[kv_axis - 1]))
    return qspec, kspec


def _fwd_call(q3, k3, v3, km, qp, kp, qs, ks, scale, causal, use_mask,
              use_segs, qb, kb, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q3.shape
    tk = k3.shape[1]
    nq, nk = tq // qb, tk // kb
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             use_mask=use_mask, use_segs=use_segs, nk=nk)
    qs_spec, ks_spec = _seg_specs(pl, qb, kb, use_segs, q_axis=1, kv_axis=2)
    return pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((qb, 1), lambda i, j, k: (j, 0)),
            pl.BlockSpec((1, kb), lambda i, j, k: (0, k)),
            _km_spec(pl, kb, use_mask, kv_axis=2),
            qs_spec,
            ks_spec,
            pl.BlockSpec((1, qb, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, k, 0)),
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, k, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, qb, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((1, qb, 1), lambda i, j, k: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((qb, 1), jnp.float32),   # running max m
            pltpu.VMEM((qb, 1), jnp.float32),   # running sum l
            pltpu.VMEM((qb, d), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(qp, kp, km, qs, ks, q3, k3, v3)


def _bwd_calls(q3, k3, v3, km, qp, kp, qs, ks, o, lse, do, dlse,
               scale, causal, use_mask, use_segs, qb, kb, interpret,
               bwd_acc_dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q3.shape
    tk = k3.shape[1]
    nq, nk = tq // qb, tk // kb
    acc_dt = jnp.dtype(bwd_acc_dtype)
    di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1,
                 keepdims=True)               # [bh, tq, 1]
    gl = dlse.astype(jnp.float32)             # lse cotangent [bh, tq, 1]

    # dk/dv: grid (bh, nk, nq) — KV block parallel, Q sweep innermost.
    qrow = lambda i, j, k: (i, k, 0)          # q-indexed rows by inner dim
    dkv_kern = functools.partial(_bwd_dkv_kernel, scale=scale,
                                 causal=causal, use_mask=use_mask,
                                 use_segs=use_segs, nq=nq, acc_dtype=acc_dt)
    qs_dkv, ks_dkv = _seg_specs(pl, qb, kb, use_segs, q_axis=2, kv_axis=1)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((qb, 1), lambda i, j, k: (k, 0)),
            pl.BlockSpec((1, kb), lambda i, j, k: (0, j)),
            _km_spec(pl, kb, use_mask, kv_axis=1),
            qs_dkv,
            ks_dkv,
            pl.BlockSpec((1, qb, d), qrow),                       # q
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, j, 0)),  # k
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, j, 0)),  # v
            pl.BlockSpec((1, qb, d), qrow),                       # do
            pl.BlockSpec((1, qb, 1), qrow),                       # lse
            pl.BlockSpec((1, qb, 1), qrow),                       # di
            pl.BlockSpec((1, qb, 1), qrow),                       # g_lse
        ],
        out_specs=[
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, j, 0)),
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((kb, d), acc_dt),
            pltpu.VMEM((kb, d), acc_dt),
        ],
        interpret=interpret,
    )(qp, kp, km, qs, ks, q3, k3, v3, do, lse, di, gl)

    # dq: grid (bh, nq, nk) — Q block parallel, KV sweep innermost.
    qblk = lambda i, j, k: (i, j, 0)
    dq_kern = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                use_mask=use_mask, use_segs=use_segs,
                                nk=nk, acc_dtype=acc_dt)
    qs_dq, ks_dq = _seg_specs(pl, qb, kb, use_segs, q_axis=1, kv_axis=2)
    dq = pl.pallas_call(
        dq_kern,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((qb, 1), lambda i, j, k: (j, 0)),
            pl.BlockSpec((1, kb), lambda i, j, k: (0, k)),
            _km_spec(pl, kb, use_mask, kv_axis=2),
            qs_dq,
            ks_dq,
            pl.BlockSpec((1, qb, d), qblk),                       # q
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, k, 0)),  # k
            pl.BlockSpec((1, kb, d), lambda i, j, k: (i, k, 0)),  # v
            pl.BlockSpec((1, qb, d), qblk),                       # do
            pl.BlockSpec((1, qb, 1), qblk),                       # lse
            pl.BlockSpec((1, qb, 1), qblk),                       # di
            pl.BlockSpec((1, qb, 1), qblk),                       # g_lse
        ],
        out_specs=pl.BlockSpec((1, qb, d), qblk),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q3.dtype),
        scratch_shapes=[pltpu.VMEM((qb, d), acc_dt)],
        interpret=interpret,
    )(qp, kp, km, qs, ks, q3, k3, v3, do, lse, di, gl)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp core over [bh, t, d].
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(8, 9, 10, 11, 12, 13, 14, 15))
def _flash(q3, k3, v3, km, qp, kp, qs, ks, scale, causal, use_mask,
           use_segs, qb, kb, interpret, bwd_acc_dtype):
    return _fwd_call(q3, k3, v3, km, qp, kp, qs, ks, scale, causal,
                     use_mask, use_segs, qb, kb, interpret)


def _flash_fwd(q3, k3, v3, km, qp, kp, qs, ks, scale, causal, use_mask,
               use_segs, qb, kb, interpret, bwd_acc_dtype):
    o, lse = _fwd_call(q3, k3, v3, km, qp, kp, qs, ks, scale, causal,
                       use_mask, use_segs, qb, kb, interpret)
    return (o, lse), (q3, k3, v3, km, qp, kp, qs, ks, o, lse)


def _flash_bwd(scale, causal, use_mask, use_segs, qb, kb, interpret,
               bwd_acc_dtype, res, cts):
    q3, k3, v3, km, qp, kp, qs, ks, o, lse = res
    do, dlse = cts
    dq, dk, dv = _bwd_calls(q3, k3, v3, km, qp, kp, qs, ks, o, lse, do,
                            dlse, scale, causal, use_mask, use_segs, qb,
                            kb, interpret, bwd_acc_dtype)
    # Mask, int32 positions and int32 segment ids are non-differentiable:
    # zero / float0.
    return (dq, dk, dv, jnp.zeros_like(km),
            np.zeros(qp.shape, jax.dtypes.float0),
            np.zeros(kp.shape, jax.dtypes.float0),
            np.zeros(qs.shape, jax.dtypes.float0),
            np.zeros(ks.shape, jax.dtypes.float0))


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Public API.
# ---------------------------------------------------------------------------

def flash_attention(q, k, v, *, causal: bool = False, key_mask=None,
                    segment_ids=None, kv_segment_ids=None,
                    q_pos=None, kv_pos=None, q_block: int = 0,
                    kv_block: int = 0, interpret: bool = False,
                    with_lse: bool = False,
                    bwd_acc_dtype: str = "float32"):
    """Fused flash attention over [batch, time, heads, head_dim].

    Matches dense_attention semantics (scaling, NEG masking, zero output
    for fully-masked query rows) and is differentiable through the
    custom_vjp backward kernels. `q_pos`/`kv_pos` override the default
    arange positions for causal masking — the ring path passes traced
    global offsets here. `with_lse=True` additionally returns the
    per-row log-sum-exp as [batch, time, heads] f32 (NEG sentinel for
    fully-masked rows); its cotangent is supported.

    `segment_ids` ([batch, t_q] int, or 1-D [t_q] shared across the
    batch) packs multiple sequences into one row: attention is masked
    wherever q and kv segment ids differ, and whole cross-segment tiles
    are skipped on the block-skip path. `kv_segment_ids` defaults to
    `segment_ids` (self-attention); pass it explicitly for
    cross-attention geometries. Combine with `key_mask`/`causal` freely
    — masks compose by conjunction. Causal masking inside a packed row
    stays exact under the default global arange positions: the segment
    equality already removes cross-segment pairs, and within a segment
    global and local position orders agree.

    `bwd_acc_dtype` selects the accumulate dtype of the backward
    kernels' scratch and matmuls ("float32" default; "bfloat16" trades
    grad precision for bandwidth — drift numbers in
    docs/perf_attention.md).
    """
    b, tq, hh, d = q.shape
    tk = k.shape[1]
    qb = q_block or pick_kernel_block(tq, DEFAULT_BLOCK_Q)
    kb = kv_block or pick_kernel_block(tk, DEFAULT_BLOCK_KV)
    if tq % qb or tk % kb:
        raise ValueError(
            f"time ({tq}, {tk}) must divide blocks ({qb}, {kb})")

    def fold(a):  # [b, t, h, d] -> [b*h, t, d], lanes padded
        a3 = a.transpose(0, 2, 1, 3).reshape(b * hh, a.shape[1], d)
        return pad_axis_to(a3, 2, _LANE)

    q3, k3, v3 = fold(q), fold(k), fold(v)
    use_mask = key_mask is not None
    if use_mask:
        km = jnp.broadcast_to(key_mask.astype(jnp.float32)[:, None, :],
                              (b, hh, tk)).reshape(b * hh, tk)
    else:
        km = jnp.ones((1, tk), jnp.float32)
    qp = (jnp.arange(tq, dtype=jnp.int32) if q_pos is None
          else q_pos.astype(jnp.int32)).reshape(tq, 1)
    kp = (jnp.arange(tk, dtype=jnp.int32) if kv_pos is None
          else kv_pos.astype(jnp.int32)).reshape(1, tk)

    use_segs = segment_ids is not None
    if kv_segment_ids is not None and not use_segs:
        raise ValueError("kv_segment_ids requires segment_ids")
    if use_segs:
        def seg_rows(seg, t):  # -> [b*h, t] int32, broadcast over heads
            seg = jnp.asarray(seg, jnp.int32)
            if seg.ndim == 1:
                seg = jnp.broadcast_to(seg[None, :], (b, t))
            return jnp.broadcast_to(seg[:, None, :],
                                    (b, hh, t)).reshape(b * hh, t)
        seg_k = segment_ids if kv_segment_ids is None else kv_segment_ids
        qs = seg_rows(segment_ids, tq).reshape(b * hh, tq, 1)
        ks = seg_rows(seg_k, tk).reshape(b * hh, 1, tk)
    else:
        qs = jnp.zeros((1, tq, 1), jnp.int32)
        ks = jnp.zeros((1, 1, tk), jnp.int32)

    # Softmax scale uses the TRUE head_dim, not the lane-padded one.
    o3, lse3 = _flash(q3, k3, v3, km, qp, kp, qs, ks,
                      1.0 / math.sqrt(d), causal, use_mask, use_segs,
                      qb, kb, interpret, str(bwd_acc_dtype))
    o = o3[:, :, :d].reshape(b, hh, tq, d).transpose(0, 2, 1, 3)
    if not with_lse:
        return o
    lse = lse3.reshape(b, hh, tq).transpose(0, 2, 1)
    return o, lse


def flash_attention_supported(t_q: int, t_k: int, head_dim: int, *,
                              q_block: int = 0, kv_block: int = 0) -> bool:
    """Geometry gate: exact block tiling plus a conservative VMEM bound
    for the worst kernel (dkv: q/k/v/do blocks + 2 [kb, d] f32 scratch +
    the [qb, kb] score block)."""
    if t_q < 1 or t_k < 1 or head_dim < 1:
        return False
    qb = q_block or pick_kernel_block(t_q, DEFAULT_BLOCK_Q)
    kb = kv_block or pick_kernel_block(t_k, DEFAULT_BLOCK_KV)
    if t_q % qb or t_k % kb:
        return False
    dp = head_dim + ((-head_dim) % _LANE)
    est = 4 * ((2 * qb + 4 * kb) * dp + 2 * qb * kb)
    return est <= _VMEM_BUDGET


def _flash_probe():
    x = jnp.ones((1, 2 * DEFAULT_BLOCK_Q, 1, _LANE), jnp.float32)
    o = flash_attention(x, x, x, causal=True)
    o.block_until_ready()


def flash_attention_available() -> bool:
    """One-time eager compile probe (kernel_probe rationale applies: a
    traced first call must not poison the cache)."""
    return kernel_probe("flash_attention", _flash_probe)


def decode_attention(q, k, v, cache_len, *, impl: str = "auto",
                     interpret: bool = False):
    """Single-query-row attention against a growing KV cache.

    The decode-loop variant of `flash_attention`: each batch row holds
    ONE new query token attending to its first `cache_len[i]` cached
    KV positions. Inputs:

      q          [batch, 1, heads, head_dim]  — this step's query
      k, v       [batch, t_kv, heads, head_dim] — bucketed cache view
                 (t_kv is a pow2 bucket; tail rows beyond cache_len are
                 garbage and masked out here)
      cache_len  [batch] int32 — valid prefix length per row, >= 1
                 (the row INCLUDING the current token, already
                 scattered into k/v at position cache_len-1)

    Returns [batch, 1, heads, head_dim].

    `impl="flash"` routes through the flash kernel with q_block=1
    (pick_kernel_block(1, ·) == 1, so the tq=1 row tiles legally);
    `impl="dense"` is the einsum reference; `impl="auto"` picks flash
    when the geometry gate and the one-time probe both pass. No
    backward: decode is inference-only, and the wrapper is jit-friendly
    (cache_len is a traced operand, so one executable serves every
    fill level of a given bucket).
    """
    b, tq, hh, d = q.shape
    if tq != 1:
        raise ValueError(f"decode_attention takes one query row, got {tq}")
    tk = k.shape[1]
    cache_len = jnp.asarray(cache_len, jnp.int32)
    valid = jnp.arange(tk, dtype=jnp.int32)[None, :] < cache_len[:, None]
    if impl not in ("auto", "flash", "dense"):
        raise ValueError(f"unknown decode_attention impl {impl!r}")
    use_flash = impl == "flash" or (
        impl == "auto" and flash_attention_supported(1, tk, d)
        and flash_attention_available())
    if use_flash:
        return flash_attention(q, k, v, key_mask=valid,
                               interpret=interpret)
    # Dense reference arm: f32 accumulate, NEG for masked positions.
    # A fully-masked row cannot occur (cache_len >= 1 by contract).
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / math.sqrt(d)
    scores = jnp.where(valid[:, None, None, :], scores, NEG)
    w = jax.nn.softmax(scores, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)
    return o.astype(q.dtype)
