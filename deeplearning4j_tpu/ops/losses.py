"""Loss functions.

Reference parity: nd4j-api ILossFunction implementations used by DL4J output
layers (`nn/conf/layers/OutputLayer` `lossFunction`; score computed in
BaseOutputLayer via computeScoreArray). Reference set: MSE, L1, L2, MAE, XENT,
MCXENT, NEGATIVELOGLIKELIHOOD, SQUARED_LOSS, HINGE, SQUARED_HINGE,
KL_DIVERGENCE, MEAN_ABSOLUTE_PERCENTAGE_ERROR, MEAN_SQUARED_LOGARITHMIC_ERROR,
POISSON, COSINE_PROXIMITY; per-loss gradient tested by
LossFunctionGradientCheck in the reference test suite.

TPU-native redesign: each loss is a pure function
``score_array(labels, preout, activation, mask) -> per-example score`` and the
backward pass comes from autodiff (no hand-written computeGradient). The
softmax+MCXENT and sigmoid+XENT pairs take the numerically-stable fused path
(log-softmax / logits-BCE) instead of activating then taking logs — the XLA
idiom for what the reference does with explicit clipping.

Shapes: preout/labels are [batch, features] (dense), [batch, time, features]
(RNN; reference layout [batch, features, time] — divergence documented in
nn/layers/recurrent), or [batch, h, w, c] (per-pixel losses, NHWC). The score
array reduces all non-batch axes; masks broadcast against labels.
"""
from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp

from .activations import resolve as resolve_activation

Array = jax.Array


def _reduce_nonbatch(x: Array) -> Array:
    return jnp.sum(x.reshape(x.shape[0], -1), axis=-1)


def _apply_mask(per_elem: Array, mask: Array | None) -> Array:
    if mask is None:
        return per_elem
    # Mask broadcasts from [batch] / [batch, time] / full shape.
    while mask.ndim < per_elem.ndim:
        mask = mask[..., None]
    return per_elem * mask


_EPS = 1e-10


def _mse(labels, out):
    return (out - labels) ** 2


def _l1(labels, out):
    return jnp.abs(out - labels)


def _xent_fused(labels, preout):
    # Binary cross-entropy on logits: stable log(sigmoid) forms.
    return -(
        labels * jax.nn.log_sigmoid(preout)
        + (1.0 - labels) * jax.nn.log_sigmoid(-preout)
    )


def _xent_on_probs(labels, p):
    p = jnp.clip(p, _EPS, 1.0 - _EPS)
    return -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))


def _mcxent_fused(labels, preout):
    return -labels * jax.nn.log_softmax(preout, axis=-1)


def _mcxent_on_probs(labels, p):
    return -labels * jnp.log(jnp.clip(p, _EPS, None))


def _hinge(labels, out):
    # labels in {-1, +1}
    return jnp.maximum(0.0, 1.0 - labels * out)


def _squared_hinge(labels, out):
    return jnp.maximum(0.0, 1.0 - labels * out) ** 2


def _kld(labels, p):
    lab = jnp.clip(labels, _EPS, None)
    p = jnp.clip(p, _EPS, None)
    return labels * (jnp.log(lab) - jnp.log(p))


def _mape(labels, out):
    return 100.0 * jnp.abs((out - labels) / jnp.clip(jnp.abs(labels), _EPS, None))


def _msle(labels, out):
    return (jnp.log1p(jnp.clip(out, -1 + _EPS, None))
            - jnp.log1p(jnp.clip(labels, -1 + _EPS, None))) ** 2


def _poisson(labels, out):
    return out - labels * jnp.log(jnp.clip(out, _EPS, None))


class Loss:
    """A named loss; callable as score_array(labels, preout, activation, mask)."""

    def __init__(self, name: str, elementwise: Callable, fused: dict | None = None,
                 cosine: bool = False):
        self.name = name
        self._elementwise = elementwise
        self._fused = fused or {}
        self._cosine = cosine

    def score_array(self, labels: Array, preout: Array,
                    activation: Union[str, Callable, None] = "identity",
                    mask: Array | None = None) -> Array:
        act_name = activation.lower() if isinstance(activation, str) else None
        if self._cosine:
            act = resolve_activation(activation)
            out = act(preout)
            ln = jnp.linalg.norm(labels.reshape(labels.shape[0], -1), axis=-1)
            on = jnp.linalg.norm(out.reshape(out.shape[0], -1), axis=-1)
            dots = _reduce_nonbatch(_apply_mask(labels * out, mask))
            return -dots / jnp.clip(ln * on, _EPS, None)
        if act_name in self._fused:
            per_elem = self._fused[act_name](labels, preout)
        else:
            act = resolve_activation(activation)
            per_elem = self._elementwise(labels, act(preout))
        return _reduce_nonbatch(_apply_mask(per_elem, mask))

    def score(self, labels, preout, activation="identity", mask=None) -> Array:
        """Mean-over-minibatch score, the quantity MultiLayerNetwork.score()
        reports (reference MultiLayerNetwork.java:1985)."""
        sa = self.score_array(labels, preout, activation, mask)
        if mask is not None and mask.ndim >= 2:
            # Time-series masking: average over present timesteps, matching
            # the reference's masked score normalization.
            denom = jnp.clip(jnp.sum(mask), 1.0)
            return jnp.sum(sa) / denom
        return jnp.mean(sa)


LOSSES: dict[str, Loss] = {}


def _reg(name: str, loss: Loss):
    LOSSES[name] = loss
    return loss


_reg("mse", Loss("mse", _mse))
_reg("squared_loss", Loss("squared_loss", _mse))
_reg("l2", Loss("l2", _mse))
_reg("l1", Loss("l1", _l1))
_reg("mae", Loss("mae", _l1))
_reg("xent", Loss("xent", _xent_on_probs, fused={"sigmoid": _xent_fused}))
_reg("mcxent", Loss("mcxent", _mcxent_on_probs, fused={"softmax": _mcxent_fused}))
_reg("negativeloglikelihood",
     Loss("negativeloglikelihood", _mcxent_on_probs, fused={"softmax": _mcxent_fused}))
_reg("hinge", Loss("hinge", _hinge))
_reg("squared_hinge", Loss("squared_hinge", _squared_hinge))
_reg("kl_divergence", Loss("kl_divergence", _kld))
_reg("mean_absolute_percentage_error", Loss("mape", _mape))
_reg("mape", LOSSES["mean_absolute_percentage_error"])
_reg("mean_squared_logarithmic_error", Loss("msle", _msle))
_reg("msle", LOSSES["mean_squared_logarithmic_error"])
_reg("poisson", Loss("poisson", _poisson))
_reg("cosine_proximity", Loss("cosine_proximity", None, cosine=True))

LossLike = Union[str, Loss]


def resolve(loss: LossLike) -> Loss:
    if isinstance(loss, Loss):
        return loss
    key = loss.lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss {loss!r}. Known: {sorted(LOSSES)}")
    return LOSSES[key]


def register_loss(name: str, loss: Loss) -> None:
    """Custom-loss extension point (reference: custom ILossFunction tests)."""
    LOSSES[name.lower()] = loss
