"""Pallas TPU kernels for hot ops.

Role parity: the reference's deeplearning4j-cuda module hand-writes cuDNN
helpers for ops its default path leaves unfused
(CudnnLocalResponseNormalizationHelper.java etc., SURVEY.md §2.3). On
TPU, XLA fuses most of that inventory automatically; Pallas is the
escape hatch for the residue. LRN is that residue's poster child: the
cross-channel window turns into a reduce_window + pow + divide chain
that XLA executes as several HBM round trips, while one Pallas kernel
keeps the block in VMEM and does squares → shifted-window accumulate →
pow → divide in a single pass on the VPU. Measured on one v5e chip
(AlexNet-shaped [64,27,27,96] fp32, 100-op in-jit chain, 2026-07-30):
633 µs/op Pallas vs 1192 µs/op lax — 1.9× faster.

Autodiff: pallas_call is not differentiable, so `lrn` carries a
custom_vjp whose backward differentiates the plain-lax reference
implementation — the forward takes the fast path, the backward stays
exactly XLA's gradient (parity-tested against autodiff of the lax
version).

The kernel is used when running on TPU (or in interpret mode for CPU
tests); any failure falls back to the lax implementation, mirroring the
reference's "helper != null" optional-acceleration contract
(ConvolutionLayer.java:66-77).
"""
from __future__ import annotations

import functools
import logging
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

log = logging.getLogger(__name__)

_ROW_BLOCK = 256  # flattened pixel rows per grid step (VMEM-friendly)


def lrn_reference(x, k: float, alpha: float, beta: float, n: int):
    """Plain-lax LRN (the pre-Pallas implementation; also the backward)."""
    half = n // 2
    sq = x * x
    window = (1, 1, 1, n)
    pads = ((0, 0), (0, 0), (0, 0), (half, n - 1 - half))
    s = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1), pads)
    return x / jnp.power(k + alpha * s, beta)


def _lrn_kernel(x_ref, o_ref, *, k: float, alpha: float, beta: float,
                n: int):
    """One [rows, C] block: windowed sum of squares via static shifted
    slices (no HBM round trips — everything stays in VMEM). The window
    matches the lax reference's pads (half, n-1-half): channel c sums
    squares over [c-half, c+(n-1-half)]."""
    x = x_ref[:]
    sq = x * x
    up = n // 2          # channels ABOVE c in the window (c-1..c-up)
    down = n - 1 - up    # channels BELOW c (c+1..c+down)
    acc = sq
    for off in range(1, max(up, down) + 1):
        if off <= down:  # channel c sees c+off: shift left, zero-fill
            acc = acc + jnp.concatenate(
                [sq[:, off:], jnp.zeros((sq.shape[0], off), sq.dtype)],
                axis=1)
        if off <= up:    # channel c sees c-off: shift right, zero-fill
            acc = acc + jnp.concatenate(
                [jnp.zeros((sq.shape[0], off), sq.dtype), sq[:, :-off]],
                axis=1)
    o_ref[:] = x / jnp.power(k + alpha * acc, beta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn(x, k: float = 2.0, alpha: float = 1e-4, beta: float = 0.75,
        n: int = 5, interpret: bool = False):
    """LRN over NHWC input with the channel window fused in one Pallas
    pass. Differentiable (custom VJP through the lax reference)."""
    return _lrn_pallas(x, k, alpha, beta, n, interpret)


def _lrn_pallas(x, k, alpha, beta, n, interpret):
    from jax.experimental import pallas as pl

    b, h, w, c = x.shape
    rows = b * h * w
    flat = x.reshape(rows, c)
    # lane-align channels; pad rows to the block multiple
    c_pad = (-c) % 128
    r_pad = (-rows) % _ROW_BLOCK
    if c_pad or r_pad:
        flat = jnp.pad(flat, ((0, r_pad), (0, c_pad)))
    padded_rows, padded_c = flat.shape

    kern = functools.partial(_lrn_kernel, k=float(k), alpha=float(alpha),
                             beta=float(beta), n=int(n))
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        grid=(padded_rows // _ROW_BLOCK,),
        in_specs=[pl.BlockSpec((_ROW_BLOCK, padded_c),
                               lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_ROW_BLOCK, padded_c), lambda i: (i, 0)),
        interpret=interpret,
    )(flat)
    # NB: zero-padding is exact here: padded channels contribute 0 to the
    # window sums of real channels, and padded rows are sliced away.
    return out[:rows, :c].reshape(b, h, w, c)


def _lrn_fwd(x, k, alpha, beta, n, interpret):
    return _lrn_pallas(x, k, alpha, beta, n, interpret), x


def _lrn_bwd(k, alpha, beta, n, interpret, x, g):
    _, vjp = jax.vjp(lambda v: lrn_reference(v, k, alpha, beta, n), x)
    return vjp(g)


lrn.defvjp(_lrn_fwd, _lrn_bwd)


def lrn_supported(x) -> bool:
    """The kernel path is valid for this input. The channel axis lives
    whole in one (row-block, C) VMEM tile: bound C so input+output+shift
    temps stay well under the ~16MB VMEM budget."""
    if x.ndim != 4 or x.shape[-1] < 1:
        return False
    padded_c = x.shape[-1] + ((-x.shape[-1]) % 128)
    return _ROW_BLOCK * padded_c * 4 * 4 <= 8 * 1024 * 1024  # ≤ c=2048 f32


_probe_result = None


def tpu_kernel_available() -> bool:
    """One-time compile probe. try/except around a traced call CANNOT
    catch Pallas lowering failures (they surface at jit-compile time), so
    the optional-helper fallback is decided here, eagerly, once — the
    actual 'helper != null' check."""
    global _probe_result
    if _probe_result is None:
        try:
            x = jnp.ones((1, 1, 1, 8), jnp.float32)
            _lrn_pallas(x, 2.0, 1e-4, 0.75, 5, False).block_until_ready()
            _probe_result = True
        except Exception as e:
            log.info("Pallas LRN kernel unavailable (%s); lax path", e)
            _probe_result = False
    return _probe_result
