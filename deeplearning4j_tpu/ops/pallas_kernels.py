"""Pallas TPU kernels for hot ops.

Role parity: the reference's deeplearning4j-cuda module hand-writes cuDNN
helpers for ops its default path leaves unfused
(CudnnLocalResponseNormalizationHelper.java etc., SURVEY.md §2.3). On
TPU, XLA fuses most of that inventory automatically; Pallas is the
escape hatch for the residue. LRN was the candidate: the cross-channel
window turns into a reduce_window + pow + divide chain, while one
Pallas kernel keeps the block in VMEM and does squares →
shifted-window accumulate → pow → divide in a single pass on the VPU.

ROUND-5 HONESTY NOTE: the standalone-op microbench (633 µs/op Pallas vs
1192 µs/op lax on [64,27,27,96] f32, 2026-07-30) does NOT survive
in-workload reality. After fixing the probe bug that had silently kept
every traced run on the lax path (see tpu_kernel_available), the full
AlexNet A/B measures lax ~2x FASTER end-to-end (bench.py alexnet vs
alexnet_pallaslrn; docs/perf_googlenet.md): the pallas_call is a
fusion barrier, and the 128-lane channel padding doubles HBM bytes for
64-channel LRN layers. The kernels (fwd AND bwd) therefore ship
default-OFF (LocalResponseNormalization.use_pallas=False) as the
optional helper the SPI promises, selectable for channel-heavy
geometries.

Autodiff: pallas_call is not differentiable, so `lrn` carries a
custom_vjp; the backward runs the Pallas backward kernel under the same
gating (else the lax autodiff of the reference implementation) —
parity-tested against autodiff of the lax version.

The kernel path requires TPU (or interpret mode for CPU tests); any
probe failure falls back to the lax implementation, mirroring the
reference's "helper != null" optional-acceleration contract
(ConvolutionLayer.java:66-77).
"""
from __future__ import annotations

import functools
import logging
import os
import time
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

log = logging.getLogger(__name__)

_ROW_BLOCK = 256  # flattened pixel rows per grid step (VMEM-friendly)


# ---------------------------------------------------------------------------
# Shared kernel plumbing (used by LRN here and flash attention in
# ops/flash_attention.py — factor, don't copy a third time).
# ---------------------------------------------------------------------------

def pad_axis_to(a, axis: int, multiple: int):
    """Zero-pad `a` along `axis` up to the next multiple of `multiple`.

    Returns the (possibly identical) array. The caller slices the result
    back; doing the pad OUTSIDE the custom_vjp'd pallas_call means
    autodiff handles the pad/slice pair for free."""
    size = a.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, pad)
    return jnp.pad(a, widths)


_probe_results: Dict[str, bool] = {}


def kernel_probe(name: str, probe: Callable[[], None]) -> bool:
    """One-time compile probe for a Pallas kernel, cached per `name`.

    try/except around a traced call CANNOT catch Pallas lowering failures
    (they surface at jit-compile time), so the optional-helper fallback
    is decided here, eagerly, once — the actual 'helper != null' check.

    The first call usually happens while a layer forward is being TRACED
    (gating runs inside jit), where a bare jnp.ones would produce a
    tracer and the probe would throw and cache False — permanently
    disabling the kernel for the whole process (the round-4 GoogLeNet
    profile caught exactly this: zero Mosaic calls in a "Pallas" run).
    ensure_compile_time_eval makes the probe eager regardless of any
    ambient trace."""
    cached = _probe_results.get(name)
    if cached is not None:
        return cached
    try:
        with jax.ensure_compile_time_eval():
            probe()
        _probe_results[name] = True
    except Exception as e:
        log.info("Pallas %s kernel unavailable (%s); fallback path",
                 name, e)
        _probe_results[name] = False
    return _probe_results[name]


def lrn_reference(x, k: float, alpha: float, beta: float, n: int):
    """Plain-lax LRN (the pre-Pallas implementation; also the backward)."""
    half = n // 2
    sq = x * x
    window = (1, 1, 1, n)
    pads = ((0, 0), (0, 0), (0, 0), (half, n - 1 - half))
    s = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1), pads)
    return x / jnp.power(k + alpha * s, beta)


def _window_sum(a, up: int, down: int):
    """Cross-channel windowed sum over the last axis via static shifted
    slices: out[:, c] = sum(a[:, c-up : c+down+1]) with zero fill.
    jnp.pad (scalar fill), NOT concatenate-with-zeros: materialized zero
    blocks become captured constants when the kernel is traced under
    ensure_compile_time_eval (the probe context), which pallas_call
    rejects."""
    acc = a
    for off in range(1, max(up, down) + 1):
        if off <= down:  # channel c sees c+off: shift left, zero-fill
            acc = acc + jnp.pad(a[:, off:], ((0, 0), (0, off)))
        if off <= up:    # channel c sees c-off: shift right, zero-fill
            acc = acc + jnp.pad(a[:, :-off], ((0, 0), (off, 0)))
    return acc


def _lrn_kernel(x_ref, o_ref, *, k: float, alpha: float, beta: float,
                n: int):
    """One [rows, C] block: windowed sum of squares via static shifted
    slices (no HBM round trips — everything stays in VMEM). The window
    matches the lax reference's pads (half, n-1-half): channel c sums
    squares over [c-half, c+(n-1-half)]."""
    x = x_ref[:]
    up = n // 2          # channels ABOVE c in the window (c-1..c-up)
    down = n - 1 - up    # channels BELOW c (c+1..c+down)
    acc = _window_sum(x * x, up, down)
    o_ref[:] = x / jnp.power(k + alpha * acc, beta)


def _lrn_bwd_kernel(x_ref, g_ref, o_ref, *, k: float, alpha: float,
                    beta: float, n: int):
    """LRN backward in one VMEM pass (the lax autodiff of the reference
    runs this as reduce-window + power + multiply chains over HBM).
    With d_c = k + alpha * sum_{j in N(c)} x_j^2 and y_c = x_c d_c^-b:

      dx_i = g_i d_i^-b - 2 a b x_i * sum_{c in N*(i)} g_c x_c d_c^(-b-1)

    where N*(i) is the TRANSPOSED window: c in N*(i) iff i in N(c) —
    i.e. the (up, down) shifts swap."""
    x = x_ref[:]
    g = g_ref[:]
    up = n // 2
    down = n - 1 - up
    d = k + alpha * _window_sum(x * x, up, down)
    p = jnp.power(d, -beta)
    t = g * x * p / d               # g * x * d^(-beta-1)
    u = _window_sum(t, down, up)    # transposed window
    o_ref[:] = g * p - 2.0 * alpha * beta * x * u


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn(x, k: float = 2.0, alpha: float = 1e-4, beta: float = 0.75,
        n: int = 5, interpret: bool = False):
    """LRN over NHWC input with the channel window fused in one Pallas
    pass. Differentiable (custom VJP through the lax reference)."""
    return _lrn_pallas(x, k, alpha, beta, n, interpret)


def _run_lrn_call(kernel, arrays, k, alpha, beta, n, interpret):
    """Shared pallas_call plumbing for the fwd/bwd LRN kernels: flatten
    NHWC to [rows, C], lane-align channels, pad rows to the block
    multiple, grid over row blocks. Zero-padding is exact: padded
    channels contribute 0 to the window sums of real channels, and
    padded rows are sliced away."""
    from jax.experimental import pallas as pl

    b, h, w, c = arrays[0].shape
    rows = b * h * w
    flats = []
    for a in arrays:
        flat = pad_axis_to(a.reshape(rows, c), 1, 128)
        flats.append(pad_axis_to(flat, 0, _ROW_BLOCK))
    padded_rows, padded_c = flats[0].shape
    kern = functools.partial(kernel, k=float(k), alpha=float(alpha),
                             beta=float(beta), n=int(n))
    spec = pl.BlockSpec((_ROW_BLOCK, padded_c), lambda i: (i, 0))
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct(flats[0].shape, flats[0].dtype),
        grid=(padded_rows // _ROW_BLOCK,),
        in_specs=[spec] * len(flats),
        out_specs=spec,
        interpret=interpret,
    )(*flats)
    return out[:rows, :c].reshape(b, h, w, c)


def _lrn_pallas(x, k, alpha, beta, n, interpret):
    return _run_lrn_call(_lrn_kernel, (x,), k, alpha, beta, n, interpret)


def _lrn_bwd_pallas(x, g, k, alpha, beta, n, interpret):
    return _run_lrn_call(_lrn_bwd_kernel, (x, g), k, alpha, beta, n,
                         interpret)


def _lrn_fwd(x, k, alpha, beta, n, interpret):
    return _lrn_pallas(x, k, alpha, beta, n, interpret), x


def _lrn_bwd(k, alpha, beta, n, interpret, x, g):
    # The backward kernel is gated exactly like the forward (the round-4
    # profile showed the lax backward costing ~4x the Pallas forward it
    # accompanied: reduce-window + power + multiply chains over HBM).
    if interpret or (lrn_supported(x) and jax.default_backend() == "tpu"
                     and tpu_kernel_available()):
        return (_lrn_bwd_pallas(x, g, k, alpha, beta, n, interpret),)
    _, vjp = jax.vjp(lambda v: lrn_reference(v, k, alpha, beta, n), x)
    return vjp(g)


lrn.defvjp(_lrn_fwd, _lrn_bwd)


def lrn_supported(x) -> bool:
    """The kernel path is valid for this input. The channel axis lives
    whole in one (row-block, C) VMEM tile: bound C so input+output+shift
    temps stay well under the ~16MB VMEM budget."""
    if x.ndim != 4 or x.shape[-1] < 1:
        return False
    padded_c = x.shape[-1] + ((-x.shape[-1]) % 128)
    return _ROW_BLOCK * padded_c * 4 * 4 <= 8 * 1024 * 1024  # ≤ c=2048 f32


def _lrn_probe():
    x = jnp.ones((1, 1, 1, 8), jnp.float32)
    _lrn_pallas(x, 2.0, 1e-4, 0.75, 5, False).block_until_ready()


def tpu_kernel_available() -> bool:
    """One-time compile probe for the LRN kernel (see kernel_probe for
    the eager-probe rationale — a traced first call once silently
    disabled the kernel for the whole process)."""
    return kernel_probe("lrn", _lrn_probe)


# ---------------------------------------------------------------------------
# int8 matmul for the quantized serving path (docs/design.md
# "Quantized serving"). Three candidate implementations of the same
# contract — s8[B,K] x s8[N,K] -> s32[B,N], weights transposed so each
# output channel is one contiguous row — and a MEASURED per-backend
# dispatch under the LRN honesty rule: a one-time timed probe at a
# serving-representative shape picks the winner, the losers stay
# standing for the bench.py quant_matmul_ab A/B row.
#
# Why three arms exist at all (CPU rig, 2026-08): XLA's CPU backend has
# no int8 dot emitter — an s8 dot_general materializes an s32 copy of
# the weight operand and runs ~0.2x fp32, and its bf16 dot converts the
# weights back to f32. The native AVX512-VNNI kernel
# (native/quant_gemm.cpp as an XLA typed-FFI custom call; ~105us at
# [8,1024]x[1024,1024] vs ~470us fp32 — the pure_callback bridge it
# replaced cost ~1ms/call in trampoline alone) measures 3-5x FASTER
# than the fp32 matmul at serving shapes. On TPU the Pallas kernel
# feeds the MXU's native int8 path with no host round-trip and the XLA
# arm is the portable fallback. None of that is assumed: whichever arm
# wins the probe on the running backend ships.
# ---------------------------------------------------------------------------

_QUANT_BLOCK_N = 256  # output channels per grid step (VMEM-friendly)

#: force the dispatch (tests / bench A/B arms): native | pallas | xla
QUANT_MATMUL_ENV = "DL4JTPU_QUANT_MATMUL"

_quant_impl: Dict[str, str] = {}  # backend -> winning arm


def _int8_matmul_kernel(x_ref, w_ref, o_ref):
    # Contraction over the shared K axis of x[B,K] and w[N,K]; MXU int8
    # path needs the accumulator type pinned (pallas_guide: always pass
    # preferred_element_type).
    o_ref[:] = lax.dot_general(
        x_ref[:], w_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)


def int8_matmul_pallas(x_q, w_q, interpret: bool = False):
    """Pallas arm: x stays whole in VMEM (serving batches are small),
    grid over output-channel blocks. int8 pads to the (32, 128) minimum
    tile; zero padding is exact for a dot (0-products)."""
    from jax.experimental import pallas as pl

    b, k = x_q.shape
    n = w_q.shape[0]
    xp = pad_axis_to(pad_axis_to(x_q, 0, 32), 1, 128)
    wp = pad_axis_to(pad_axis_to(w_q, 0, _QUANT_BLOCK_N), 1, 128)
    bp, kp = xp.shape
    out = pl.pallas_call(
        _int8_matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((bp, wp.shape[0]), jnp.int32),
        grid=(wp.shape[0] // _QUANT_BLOCK_N,),
        in_specs=[pl.BlockSpec((bp, kp), lambda i: (0, 0)),
                  pl.BlockSpec((_QUANT_BLOCK_N, kp), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bp, _QUANT_BLOCK_N), lambda i: (0, i)),
        interpret=interpret,
    )(xp, wp)
    return out[:b, :n]


def int8_matmul_xla(x_q, w_q):
    """XLA arm: the portable s8 x s8 -> s32 dot_general."""
    return lax.dot_general(x_q, w_q, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.int32)


def int8_matmul_native(x_q, w_q):
    """Native arm: the AVX512-VNNI GEMM as an XLA custom call. The
    typed-FFI handler (native/quant_gemm.cpp, registered once via
    native_quant.ffi_register) hands the kernel raw XLA buffer pointers
    in-process — measured ~1ms/call cheaper than the jax.pure_callback
    bridge, whose python trampoline + marshalling costs an order of
    magnitude more than the GEMM itself at serving shapes. The
    pure_callback bridge stays as the degraded path for a .so built
    without the jaxlib FFI headers; either way the math is exact
    integer, so trace semantics hold."""
    from .. import native_quant
    out_t = jax.ShapeDtypeStruct((x_q.shape[0], w_q.shape[0]), jnp.int32)
    if native_quant.ffi_register():
        from jax.extend import ffi as jffi
        return jffi.ffi_call(native_quant.FFI_TARGET, out_t)(x_q, w_q)
    return jax.pure_callback(native_quant.int8_gemm, out_t,
                             x_q, w_q, vectorized=False)


def _int8_pallas_probe():
    x = jnp.ones((8, 128), jnp.int8)
    w = jnp.ones((8, 128), jnp.int8)
    int8_matmul_pallas(x, w).block_until_ready()


def int8_pallas_available() -> bool:
    return kernel_probe("int8_matmul", _int8_pallas_probe)


def _quant_candidates(backend: str) -> Dict[str, Callable]:
    from .. import native_quant
    cands: Dict[str, Callable] = {"xla": int8_matmul_xla}
    if backend == "cpu" and native_quant.available():
        cands["native"] = int8_matmul_native
    if backend == "tpu" and int8_pallas_available():
        cands["pallas"] = int8_matmul_pallas
    return cands


def _measure_quant_impl(backend: str) -> Tuple[str, Dict[str, float]]:
    """Time every candidate arm eagerly at a serving-representative
    shape and return (winner, per-arm best seconds). Eager (per-op)
    dispatch overhead is tens of µs against ms-scale GEMMs, so the
    ordering matches the jitted steady state; the native arm's
    pure_callback hop is included in its own timing — no arm gets its
    overhead waived."""
    key = jax.random.PRNGKey(0)
    x = jax.random.randint(key, (8, 1024), -127, 128, jnp.int8)
    w = jax.random.randint(key, (1024, 1024), -127, 128, jnp.int8)
    timings: Dict[str, float] = {}
    for name, fn in _quant_candidates(backend).items():
        try:
            jax.block_until_ready(fn(x, w))  # compile/warm
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(x, w))
                best = min(best, time.perf_counter() - t0)
            timings[name] = best
        except Exception as e:  # an arm failing is a fallback, not a crash
            log.info("quant_matmul arm %s unavailable (%s)", name, e)
    winner = min(timings, key=timings.get) if timings else "xla"
    return winner, timings


def select_quant_impl() -> str:
    """The measured per-backend dispatch decision, cached per process.
    Runs eagerly even when first reached during a trace (the
    kernel_probe rationale: a traced probe would poison the cache)."""
    backend = jax.default_backend()
    cached = _quant_impl.get(backend)
    if cached is not None:
        return cached
    forced = os.environ.get(QUANT_MATMUL_ENV, "").strip().lower()
    if forced in ("native", "pallas", "xla"):
        _quant_impl[backend] = forced
        return forced
    with jax.ensure_compile_time_eval():
        winner, timings = _measure_quant_impl(backend)
    _quant_impl[backend] = winner
    log.info("quant_matmul dispatch on %s: %s (%s)", backend, winner,
             {k: f"{v * 1e6:.0f}us" for k, v in timings.items()})
    return winner


def quant_matmul(x_q, w_q):
    """s8[B,K] x s8[N,K] -> s32[B,N] through the measured winner for
    the current backend (see select_quant_impl)."""
    impl = select_quant_impl()
    if impl == "native":
        return int8_matmul_native(x_q, w_q)
    if impl == "pallas":
        return int8_matmul_pallas(x_q, w_q)
    return int8_matmul_xla(x_q, w_q)
