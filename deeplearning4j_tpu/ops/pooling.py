"""Spatial pooling implementations with measured dispatch.

The round-5 GoogLeNet profile (docs/perf_googlenet.md) put 9.5 ms/step —
18% of on-device time — in XLA's select-and-scatter emitter, the VJP it
generates for `lax.reduce_window(max)`, running at 2.1× its byte bound.
S&S is the one HLO in the step with no MXU/VPU-friendly lowering: it
walks windows serially per output element. This module provides the
alternatives and the selector that decides between them, mirroring
`select_attention_impl` (ops/attention.py): static trace-time choice, a
`pooling_impl_selected_total{impl=}` counter in the PR-2 registry, a
one-shot warning when a requested impl is unavailable, and an eager
compile probe (kernel_probe) so a lowering failure can never crash a
traced forward.

Max pool:
  * "sns"  — `lax.reduce_window(max)`; autodiff emits select-and-scatter
    for the backward (XLA's default, the round-5 measured baseline).
  * "mask" — same forward under a custom_vjp whose backward is the
    argmax-equality-mask recompute: per window offset (p,q) compare the
    strided view of x against the broadcast pooled output, divide the
    cotangent by the per-window tie count, and scatter each offset's
    share back with `lax.pad` interior dilation —
    dx = Σ_{(p,q)} dilate(g · (x_pq == out) / ties). Pure
    pad/slice/compare/add (no S&S anywhere in fwd or bwd), so every
    piece is fusible elementwise work.

    Tie semantics differ deliberately: S&S routes the whole cotangent to
    the first maximal element of a window; "mask" splits it equally
    among ties (the mathematically symmetric subgradient; both preserve
    the cotangent sum). Identical whenever window maxima are unique.

Avg pool:
  * "window" — sum reduce_window / count reduce_window, divisor counting
    only in-bounds elements (the layer's historical path; backward is
    the pad+reduce_window transpose of reduce_window-sum).
  * "conv"   — depthwise `conv_general_dilated` with a ones kernel
    (feature_group_count = C) divided by the same in-bounds count; the
    backward is then a transposed conv — an MXU op instead of
    reduce_window. Same count-exclude-pad semantics.

SUM / PNORM stay on reduce_window in the layer (no alternative emitter
worth having: their backwards are already pad+reduce_window / pure
elementwise chains).
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

# Implementation inventory, per pooling family. "auto"/None resolve via
# the measured rule in select_pooling_impl.
MAX_IMPLS = ("sns", "mask")
AVG_IMPLS = ("window", "conv")

Pads2D = Tuple[Tuple[int, int], Tuple[int, int]]


def _window4(window, strides, pads: Pads2D):
    return ((1, window[0], window[1], 1), (1, strides[0], strides[1], 1),
            ((0, 0), pads[0], pads[1], (0, 0)))


def _reduce_max(x: Array, window, strides, pads: Pads2D) -> Array:
    w4, s4, p4 = _window4(window, strides, pads)
    return lax.reduce_window(x, -jnp.inf, lax.max, w4, s4, p4)


def _reduce_sum(x: Array, window, strides, pads: Pads2D) -> Array:
    w4, s4, p4 = _window4(window, strides, pads)
    return lax.reduce_window(x, 0.0, lax.add, w4, s4, p4)


def inbounds_count(x: Array, window, strides, pads: Pads2D) -> Array:
    """Per-output-window count of in-bounds input elements (the
    count-exclude-pad divisor of the reference average pool). Constant
    given static shapes — XLA folds it at compile time."""
    return _reduce_sum(jnp.ones_like(x), window, strides, pads)


# ---------------------------------------------------------------------------
# Mask-backward max pool
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool_mask(x: Array, window, strides, pads: Pads2D) -> Array:
    return _reduce_max(x, window, strides, pads)


def _max_pool_mask_fwd(x, window, strides, pads):
    y = _reduce_max(x, window, strides, pads)
    return y, (x, y)


def _max_pool_mask_bwd(window, strides, pads, res, g):
    x, y = res
    kh, kw = window
    sh, sw = strides
    (pt, pb), (pl, pr) = pads
    B, H, W, C = x.shape
    OH, OW = y.shape[1], y.shape[2]
    # Padded extents must cover the furthest window: offset (kh-1, kw-1)
    # of the last output position, which can exceed H+pt+pb when the
    # high pad is smaller than the window reach (VALID with truncation).
    hp = max(H + pt + pb, (OH - 1) * sh + kh)
    wp = max(W + pl + pr, (OW - 1) * sw + kw)
    # -inf fill: a padding cell can only compare equal to y where the
    # whole window is padding (y == -inf there too); that cotangent share
    # lands in the pad margin and is sliced away below.
    xp = jnp.pad(x, ((0, 0), (pt, hp - H - pt), (pl, wp - W - pl), (0, 0)),
                 constant_values=-jnp.inf)
    # Pass 1 — per-window tie count: for each window offset, the strided
    # view of xp aligned to the output grid equals y exactly where that
    # offset holds a window max (y is a copy of some window element, so
    # equality is exact in every dtype).
    offsets = [(p, q) for p in range(kh) for q in range(kw)]
    eqs = []
    ties = None
    for p, q in offsets:
        xo = lax.slice(xp, (0, p, q, 0),
                       (B, p + (OH - 1) * sh + 1, q + (OW - 1) * sw + 1, C),
                       (1, sh, sw, 1))
        eq = (xo == y)
        eqs.append(eq)
        e = eq.astype(g.dtype)
        ties = e if ties is None else ties + e
    share = g / ties
    # Pass 2 — scatter each offset's share back onto the padded input
    # grid: interior dilation (stride-1 zeros) + low/high edge pads place
    # the output-grid array at exactly the input cells that offset
    # touches. lax.pad is the same primitive the reduce_window-sum
    # transpose lowers to — fusible, no select-and-scatter.
    zero = jnp.zeros((), g.dtype)
    dxp = None
    for (p, q), eq in zip(offsets, eqs):
        contrib = share * eq.astype(g.dtype)
        placed = lax.pad(
            contrib, zero,
            ((0, 0, 0),
             (p, hp - p - (OH - 1) * sh - 1, sh - 1),
             (q, wp - q - (OW - 1) * sw - 1, sw - 1),
             (0, 0, 0)))
        dxp = placed if dxp is None else dxp + placed
    dx = lax.slice(dxp, (0, pt, pl, 0), (B, pt + H, pl + W, C))
    return (dx.astype(x.dtype),)


_max_pool_mask.defvjp(_max_pool_mask_fwd, _max_pool_mask_bwd)


def max_pool(x: Array, window, strides, pads: Pads2D, *,
             impl: str = "sns") -> Array:
    """NHWC max pool with explicit spatial pads ((top,bottom),(left,right)).
    impl: "sns" (XLA select-and-scatter backward) | "mask" (argmax-
    equality-mask backward; see module docstring)."""
    if impl == "sns":
        return _reduce_max(x, window, strides, pads)
    if impl == "mask":
        return _max_pool_mask(x, tuple(window), tuple(strides),
                              (tuple(pads[0]), tuple(pads[1])))
    raise ValueError(f"max_pool impl {impl!r} not in {MAX_IMPLS}")


# ---------------------------------------------------------------------------
# Avg pool
# ---------------------------------------------------------------------------

def avg_pool(x: Array, window, strides, pads: Pads2D, *,
             impl: str = "window") -> Array:
    """NHWC average pool, divisor counting in-bounds elements only.
    impl: "window" (reduce_window sum) | "conv" (depthwise ones-kernel
    conv; backward is a transposed conv)."""
    cnt = inbounds_count(x, window, strides, pads)
    if impl == "window":
        return _reduce_sum(x, window, strides, pads) / cnt
    if impl == "conv":
        kh, kw = window
        c = x.shape[-1]
        ones = jnp.ones((kh, kw, 1, c), x.dtype)
        s = lax.conv_general_dilated(
            x, ones, window_strides=tuple(strides),
            padding=(tuple(pads[0]), tuple(pads[1])),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c)
        return s.astype(x.dtype) / cnt
    raise ValueError(f"avg_pool impl {impl!r} not in {AVG_IMPLS}")


# ---------------------------------------------------------------------------
# Measured dispatch (the select_attention_impl pattern)
# ---------------------------------------------------------------------------

def _count_pooling_impl(impl: str) -> None:
    from ..optimize.metrics import registry
    registry().counter(
        "pooling_impl_selected_total",
        "Pooling implementations chosen at dispatch (trace) time",
    ).labels(impl=impl).inc()


def mask_backward_available() -> bool:
    """One-time eager compile probe for the mask-backward formulation
    (kernel_probe caches per name; ensure_compile_time_eval inside makes
    it safe to first fire under an ambient trace). The formulation is
    portable lax, so this guards against lowering regressions rather
    than hardware support — the same 'helper != null' contract the
    Pallas kernels use."""
    from .pallas_kernels import kernel_probe

    def probe():
        xx = jnp.ones((1, 4, 4, 1), jnp.float32)
        jax.grad(lambda a: _max_pool_mask(
            a, (2, 2), (2, 2), ((0, 0), (0, 0))).sum())(xx)

    return kernel_probe("pool_mask_bwd", probe)


def _warn_unavailable_once(impl: str) -> None:
    if getattr(select_pooling_impl, "_warned_mask", False):
        return
    import logging
    logging.getLogger(__name__).warning(
        "pooling impl %r requested but its compile probe failed on this "
        "backend (%s); falling back per the dispatch rule "
        "(docs/perf_googlenet.md round 6)", impl, jax.default_backend())
    select_pooling_impl._warned_mask = True


def select_pooling_impl(pooling_type: str, window, strides, *,
                        requested: Optional[str] = None) -> str:
    """Pick the implementation for one pooling call, increment
    `pooling_impl_selected_total{impl=}`, and return the choice. Runs at
    TRACE time (static shapes), so the counter counts selections, not
    per-step executions — same contract as select_attention_impl.

    Rule (measured A/B, docs/perf_googlenet.md round 6 + the standing
    `bench.py googlenet_pool_ab` row), per backend like the attention
    rule:

      * max on CPU → "mask": 3.4-4x faster than the S&S expansion at
        GoogLeNet's pool geometries op-level, +5% whole-model
        (85.7 -> 81.5 s/step, b8 bf16, 2026-08-05).
      * max on TPU → "sns": the round-5 profiled baseline; "mask" is
        UNMEASURED on TPU this round (no chip) — the standing bench row
        flips this default if/when it measures a win there.
      * avg → "window" everywhere: the depthwise-conv formulation lost
        its CPU A/B by 270x (XLA:CPU's grouped conv; numbers in the
        round-6 doc) and is untested on TPU.

    The alternatives stay selectable per layer (pooling_impl="mask" /
    "conv"); a requested or auto-chosen "mask" whose compile probe
    fails warns once and falls back to "sns"."""
    if pooling_type == "max":
        impls = MAX_IMPLS
        default = "mask" if jax.default_backend() == "cpu" else "sns"
    elif pooling_type == "avg":
        impls, default = AVG_IMPLS, "window"
    else:
        raise ValueError(f"no impl dispatch for pooling type "
                         f"{pooling_type!r}")
    req = None if requested in (None, "auto") else requested
    if req is not None and req not in impls:
        raise ValueError(f"pooling impl {requested!r} not in "
                         f"{impls + ('auto',)} for {pooling_type} pooling")
    choice = req or default
    if choice == "mask" and not mask_backward_available():
        _warn_unavailable_once("mask")
        choice = "sns"
    _count_pooling_impl(f"{pooling_type}_{choice}")
    return choice


def register_metrics() -> None:
    """Pre-register the pooling dispatch counter family so a scrape
    BEFORE the first trace already exposes every label at 0 (the PR-8/9
    bench --once pattern)."""
    from ..optimize.metrics import registry
    fam = registry().counter(
        "pooling_impl_selected_total",
        "Pooling implementations chosen at dispatch (trace) time")
    for pt, impls in (("max", MAX_IMPLS), ("avg", AVG_IMPLS)):
        for impl in impls:
            fam.labels(impl=f"{pt}_{impl}")
