"""Compile-cost control plane: persistent XLA cache + AOT dispatch.

Every jitted function in the stack recompiles from scratch in every
process — through a tunneled backend that is minutes of wall clock
before the first batch runs. This module attacks that cost on two
fronts:

* **Persistent compilation cache** — wires `jax_compilation_cache_dir`
  (env-overridable, default `~/.cache/deeplearning4j_tpu/xla`) with the
  persistence thresholds dropped to zero so every executable is cached,
  and mirrors jax's cache-hit/miss monitoring events into the
  MetricsRegistry (`compile_cache_hits_total` / `_misses_total`) so warm
  vs cold compiles are visible in `/metrics` and in bench JSON. A warm
  cache turns a minutes-long cold compile into a sub-second
  deserialize.

* **AOT precompile dispatch** — `PrecompiledDispatch` wraps one
  `jax.jit` callable and routes calls whose argument signature matches
  an executable precompiled via `jit.lower(ShapeDtypeStruct...).compile()`
  straight to that executable: no re-trace, no cache lookup, zero XLA
  compilations on the critical path. `MultiLayerNetwork.precompile()` /
  `ComputationGraph.precompile()` build these ahead of the first batch.

Note the counting subtlety this design answers: jax's
`backend_compile_duration` event (what `xla_compilations_total` counts)
wraps `compile_or_get_cached`, so it fires even on a PERSISTENT-cache
hit. Only the AOT dispatch path makes a step truly compile-silent —
which is why `precompile()` stores executables instead of merely
warming the disk cache.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional, Sequence, Tuple

log = logging.getLogger(__name__)

# Resolution order for the cache directory: explicit argument >
# DL4JTPU_COMPILE_CACHE_DIR > JAX_COMPILATION_CACHE_DIR > default.
ENV_CACHE_DIR = "DL4JTPU_COMPILE_CACHE_DIR"
DEFAULT_CACHE_DIR = os.path.join(
    "~", ".cache", "deeplearning4j_tpu", "xla")

_lock = threading.Lock()
_enabled_dir: Optional[str] = None
_listening = False

_HIT_EVENT_SUFFIX = "compilation_cache/cache_hits"
_MISS_EVENT_SUFFIX = "compilation_cache/cache_misses"


def _registry():
    from .metrics import registry
    return registry()


def _hit_counter():
    return _registry().counter(
        "compile_cache_hits_total",
        "Persistent XLA compilation cache hits (jax monitoring)")


def _miss_counter():
    return _registry().counter(
        "compile_cache_misses_total",
        "Persistent XLA compilation cache misses (jax monitoring)")


def _on_event(event: str, **_kw) -> None:
    if event.endswith(_HIT_EVENT_SUFFIX):
        _hit_counter().inc()
    elif event.endswith(_MISS_EVENT_SUFFIX):
        _miss_counter().inc()


def _ensure_listener() -> None:
    global _listening
    if _listening:
        return
    with _lock:
        if _listening:
            return
        try:
            from jax import monitoring
            monitoring.register_event_listener(_on_event)
        except Exception as e:  # pragma: no cover - ancient jax
            log.warning("jax.monitoring unavailable (%s): compile-cache "
                        "hit/miss counters will read 0", e)
            return
        # Touch both families so a scrape sees them at 0 before the
        # first compile, making "no hits yet" distinguishable from
        # "counters never wired".
        _hit_counter()
        _miss_counter()
        _listening = True


def _reset_jax_cache_latch() -> None:
    """jax decides cache-on/off ONCE per process, at the first
    compilation (`compilation_cache.is_cache_used` latches
    `_cache_checked`). Any compile before `enable()` therefore latches
    the cache OFF for the whole process — silently. reset_cache() is
    the supported way to clear the latch; private-ish API, so a move
    across jax versions degrades to a loud warning, not a crash."""
    try:
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except Exception as e:  # pragma: no cover - jax internals moved
        log.warning(
            "could not reset jax's compilation-cache latch (%s): if any "
            "compilation ran before enable(), the persistent cache may "
            "stay OFF for this process", e)


def resolve_cache_dir(cache_dir: Optional[str] = None) -> str:
    d = (cache_dir or os.environ.get(ENV_CACHE_DIR)
         or os.environ.get("JAX_COMPILATION_CACHE_DIR")
         or DEFAULT_CACHE_DIR)
    return os.path.expanduser(d)


def enable(cache_dir: Optional[str] = None) -> str:
    """Turn the persistent compilation cache on; returns the directory.

    Drops jax's persistence thresholds (min compile time / min entry
    size) to zero so even the small jits this framework builds by the
    dozen are persisted — on a tunneled TPU backend EVERY avoided
    compile is round trips saved, and on CPU CI the cache smoke needs
    sub-second compiles cached too."""
    global _enabled_dir
    import jax

    d = resolve_cache_dir(cache_dir)
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    for name, value in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                        ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(name, value)
        except Exception:  # older jax: threshold knob absent — fine
            pass
    _reset_jax_cache_latch()
    _ensure_listener()
    with _lock:
        _enabled_dir = d
    _registry().gauge(
        "compile_cache_enabled",
        "1 when the persistent XLA compilation cache is wired").set(1)
    log.info("persistent XLA compilation cache enabled at %s", d)
    return d


def disable() -> None:
    """Detach the persistent cache (the monitoring listener stays; it
    only counts)."""
    global _enabled_dir
    import jax
    jax.config.update("jax_compilation_cache_dir", None)
    _reset_jax_cache_latch()  # un-latch "cache in use" too
    with _lock:
        _enabled_dir = None
    _registry().gauge(
        "compile_cache_enabled",
        "1 when the persistent XLA compilation cache is wired").set(0)


def status() -> Dict[str, Any]:
    """{enabled, dir, entries, bytes, hits, misses} — entries/bytes from
    a directory scan (cheap: one readdir), hits/misses from the
    registry counters."""
    with _lock:
        d = _enabled_dir
    entries = 0
    size = 0
    if d and os.path.isdir(d):
        try:
            for name in os.listdir(d):
                if name.endswith("-cache"):
                    entries += 1
                    try:
                        size += os.path.getsize(os.path.join(d, name))
                    except OSError:
                        pass
        except OSError:
            pass
    return {
        "enabled": d is not None,
        "dir": d,
        "entries": entries,
        "bytes": size,
        "hits": int(_hit_counter().value()),
        "misses": int(_miss_counter().value()),
    }


# ---------------------------------------------------------------------------
# AOT precompile dispatch
# ---------------------------------------------------------------------------
def _is_tracer(x) -> bool:
    try:
        import jax
        return isinstance(x, jax.core.Tracer)
    except Exception:
        return False


def call_signature(args: Sequence[Any],
                   static_argnums: Tuple[int, ...] = ()) -> Optional[tuple]:
    """Hashable signature of a call: pytree structure + per-leaf
    (shape, dtype, weak_type) + static argument values. Shape metadata
    only — never touches device values. Returns None when any leaf is a
    tracer (a transform is tracing through us: AOT executables cannot
    run under trace) or carries no shape/dtype."""
    import jax
    dynamic = tuple(a for i, a in enumerate(args)
                    if i not in static_argnums)
    statics = tuple(args[i] for i in static_argnums if i < len(args))
    leaves, treedef = jax.tree_util.tree_flatten(dynamic)
    sig = []
    for leaf in leaves:
        if _is_tracer(leaf):
            return None
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return None
        sig.append((tuple(shape), str(dtype),
                    bool(getattr(leaf, "weak_type", False))))
    return (treedef, tuple(sig), statics)


class PrecompiledDispatch:
    """One `jax.jit` callable plus a table of AOT-precompiled
    executables keyed by call signature.

    Calls whose signature matches run the stored executable directly —
    no trace, no lowering, no compile-cache lookup, zero
    `backend_compile` events. Everything else falls through to the jit
    untouched (first call traces+compiles as usual). Donation semantics
    are identical on both paths (the executable was lowered from the
    same jit, donate_argnums included).

    Transform-safe: when a wrapper (ParallelWrapper's vmap,
    SequenceParallelWrapper's re-jit) traces through this object, the
    tracer leaves force the jit path, so an AOT executable can never be
    invoked under trace.
    """

    def __init__(self, jit_fn, label: str,
                 static_argnums: Tuple[int, ...] = ()):
        self._jit = jit_fn
        self.label = label
        self._static_argnums = tuple(static_argnums)
        self._execs: Dict[tuple, Any] = {}
        self._warned_fallback = False

    # -- jax.jit surface the rest of the stack relies on ------------------
    @property
    def jit(self):
        """The wrapped jit — callers that KNOW their inputs carry a
        placement the AOT executables were not lowered for (the
        mesh-sharded DP step) dispatch here directly."""
        return self._jit

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def _cache_size(self) -> int:
        """Executable-cache size of the underlying jit (the
        telemetry.jit_cache_size probe contract). AOT executables live
        beside the jit cache, not in it."""
        probe = getattr(self._jit, "_cache_size", None)
        return int(probe()) if probe is not None else -1

    @property
    def aot_signatures(self) -> int:
        return len(self._execs)

    # -- AOT --------------------------------------------------------------
    def precompile(self, *abstract_args):
        """`jit.lower(...).compile()` on abstract ShapeDtypeStructs (or
        concrete arrays; only shape/dtype are read) and remember the
        executable under the call signature. Idempotent per signature."""
        key = call_signature(abstract_args, self._static_argnums)
        if key is None:
            raise ValueError(
                f"precompile({self.label}): arguments carry no static "
                "shape signature")
        if key in self._execs:
            return self._execs[key]
        compiled = self._jit.lower(*abstract_args).compile()
        self._execs[key] = compiled
        _registry().counter(
            "precompiled_signatures_total",
            "AOT-precompiled (lower+compile) executables built"
            ).labels(fn=self.label).inc()
        return compiled

    # -- dispatch ---------------------------------------------------------
    def __call__(self, *args):
        if self._execs:
            key = call_signature(args, self._static_argnums)
            exe = None if key is None else self._execs.get(key)
            if exe is not None:
                dynamic = tuple(a for i, a in enumerate(args)
                                if i not in self._static_argnums)
                try:
                    out = exe(*dynamic)
                except (TypeError, ValueError) as e:
                    # Layout/sharding drift the signature cannot see
                    # (e.g. an explicitly resharded input). Loud once,
                    # drop the executable, fall back to the jit — which
                    # handles any placement.
                    if not self._warned_fallback:
                        self._warned_fallback = True
                        log.warning(
                            "AOT executable for %s rejected its inputs "
                            "(%s); falling back to jit dispatch for "
                            "this signature", self.label, e)
                    self._execs.pop(key, None)
                    return self._jit(*args)
                _registry().counter(
                    "precompiled_dispatch_hits_total",
                    "Calls served by an AOT-precompiled executable "
                    "(zero compile work)").labels(fn=self.label).inc()
                return out
        return self._jit(*args)


def abstract_like(tree):
    """Pytree of ShapeDtypeStructs mirroring `tree`'s arrays (the
    AOT-argument builder; None leaves pass through)."""
    import jax

    def one(a):
        return jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)

    return jax.tree_util.tree_map(one, tree)
