"""Training listeners.

Reference parity: optimize/api/{IterationListener,TrainingListener}.java SPI
and impls in optimize/listeners/: ScoreIterationListener,
PerformanceListener (samples/sec, batches/sec, ETL time),
CollectScoresIterationListener, EvaluativeListener,
ComposableIterationListener, plus CheckpointListener-style periodic saving.

The contract: networks call `iteration_done(model, iteration)` after every
optimizer step and `on_epoch_end(model, epoch)` per epoch — same hook points
as the reference's Solver loop (StochasticGradientDescent.java:80).
"""
from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

log = logging.getLogger("deeplearning4j_tpu.listeners")


class IterationListener:
    """Base SPI (reference optimize/api/IterationListener.java)."""

    def iteration_done(self, model, iteration: int) -> None:
        pass

    def on_epoch_end(self, model, epoch: int) -> None:
        pass


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (reference ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10, printer=None):
        self.n = max(1, int(print_iterations))
        self._printer = printer or (lambda msg: log.info("%s", msg))

    def iteration_done(self, model, iteration):
        if iteration % self.n == 0:
            self._printer(
                f"Score at iteration {iteration} is "
                # deliberate rate-limited fence: printing IS the read
                f"{float(model.score_value):.6f}")  # jaxlint: disable=JL101


class PerformanceListener(IterationListener):
    """Throughput reporting (reference PerformanceListener: samples/sec,
    batches/sec, iteration wall time). NB: fetches the score each report,
    which fences the async dispatch queue — frequency matters on TPU.

    Beyond the reference: the ETL stall splits into host-wait vs
    h2d-wait when the device prefetcher is active, and each report
    carries the XLA compilations observed since the previous one — a
    nonzero count at steady state is the recompile-per-shape bug
    pad-to-bucket exists to kill (docs/perf_data_pipeline.md). The
    compile count and ETL numbers come FROM the metrics registry /
    model, never recomputed here, and every report writes throughput +
    score back INTO the registry (docs/observability.md) so a /metrics
    scrape and this log line can never disagree.

    `fence=False` skips the score fetch: timings are then DISPATCH-SIDE
    only (jax async dispatch returns before the device finishes — the
    TPU caveat above), but the listener adds zero synchronization."""

    def __init__(self, frequency: int = 10, report_samples: bool = True,
                 printer=None, fence: bool = True):
        self.frequency = max(1, int(frequency))
        self.report_samples = report_samples
        self.fence = bool(fence)
        self._printer = printer or (lambda msg: log.info("%s", msg))
        self._last_time: Optional[float] = None
        self._last_iter: Optional[int] = None
        self._last_batch_size: Optional[int] = None
        self._last_compiles: Optional[int] = None
        self.last_compile_delta: int = 0

    def set_batch_size(self, n: int):
        self._last_batch_size = int(n)

    def iteration_done(self, model, iteration):
        if iteration % self.frequency != 0:
            return
        from .metrics import registry
        from .telemetry import compilation_count
        reg = registry()
        if self.fence:
            # fence: measure real device time, and publish the score
            # (the registry's train_score only updates on fenced reads
            # — nothing else may sync the dispatch queue)
            reg.gauge("train_score",
                      "Loss at the last fenced report").set(
                          float(model.score_value))  # jaxlint: disable=JL101
        compiles = compilation_count()
        now = time.perf_counter()
        if self._last_time is not None and iteration > self._last_iter:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            msg = (f"iteration {iteration}: {iters / dt:.2f} batches/sec, "
                   f"{dt / iters * 1000:.1f} ms/iter")
            reg.gauge("train_batches_per_sec",
                      "Throughput at the last report").set(iters / dt)
            reg.gauge("train_ms_per_iter",
                      "Wall ms per optimizer step at the last report"
                      ).set(dt / iters * 1000)
            if self.report_samples and self._last_batch_size:
                sps = iters * self._last_batch_size / dt
                msg += f", {sps:.1f} samples/sec"
                reg.gauge("train_samples_per_sec",
                          "Example throughput at the last report"
                          ).set(sps)
            etl = getattr(model, "last_etl_ms", None)
            if etl is not None:
                msg += f", etl {etl:.2f} ms"
                host = getattr(model, "last_etl_host_ms", None)
                h2d = getattr(model, "last_etl_h2d_ms", None)
                if host is not None and h2d is not None:
                    msg += f" (host {host:.2f} ms, h2d {h2d:.2f} ms)"
            self.last_compile_delta = compiles - self._last_compiles \
                if self._last_compiles is not None else 0
            if self.last_compile_delta:
                msg += f", {self.last_compile_delta} xla compilations"
            if not self.fence:
                msg += " [dispatch-side]"
            self._printer(msg)
        self._last_time = now
        self._last_iter = iteration
        self._last_compiles = compiles


class ParamAndGradientIterationListener(IterationListener):
    """Per-iteration parameter/update magnitude logging (reference
    optimize/listeners/ParamAndGradientIterationListener.java:30:
    mean / min / max / mean-abs of every parameter tensor and its
    gradient, tab-delimited to console and/or file).

    TPU-native divergence, on record: gradients are consumed inside the
    fused jitted train step (autodiff -> updater -> donated buffers), so
    the observable per-iteration signal is the applied UPDATE
    (param_new - param_old = -lr-scaled gradient) — same debugging role
    (exploding/vanishing detection), one subtraction instead of a second
    backward pass. Columns: <param>.{p,u}.{mean,absmean,min,max}."""

    def __init__(self, frequency: int = 1, print_header: bool = True,
                 print_mean: bool = True, print_min_max: bool = True,
                 print_mean_abs: bool = True,
                 output_to_console: bool = False,
                 file_path: Optional[str] = None, delimiter: str = "\t",
                 printer: Optional[Callable[[str], None]] = None):
        self.frequency = max(1, int(frequency))
        self.print_header = print_header
        self.print_mean = print_mean
        self.print_min_max = print_min_max
        self.print_mean_abs = print_mean_abs
        self.output_to_console = output_to_console
        self.file_path = file_path
        self.delimiter = delimiter
        self.printer = printer
        self._prev = None
        self._wrote_header = False

    @staticmethod
    def _named_params(model):
        import numpy as np
        tree = model.params_tree
        items = tree.items() if isinstance(tree, dict) else enumerate(tree)
        for lname, pdict in items:
            for pname, arr in pdict.items():
                yield f"{lname}_{pname}", np.asarray(arr)

    def _stats(self, name, arr):
        import numpy as np
        out = []
        if self.print_mean:
            out.append((f"{name}.mean", float(arr.mean())))
        if self.print_mean_abs:
            out.append((f"{name}.absmean", float(np.abs(arr).mean())))
        if self.print_min_max:
            out.append((f"{name}.min", float(arr.min())))
            out.append((f"{name}.max", float(arr.max())))
        return out

    def _emit(self, line: str):
        if self.printer is not None:
            self.printer(line)
        elif self.output_to_console:
            print(line)
        if self.file_path:
            try:
                with open(self.file_path, "a") as f:
                    f.write(line + "\n")
            except OSError as e:  # reference caps write-failure logging
                log.warning("ParamAndGradient write failed: %s", e)
                self.file_path = None

    def iteration_done(self, model, iteration):
        import numpy as np
        report = iteration % self.frequency == 0
        # A device->host param snapshot costs a full transfer + sync, so
        # take one ONLY when this iteration reports or the NEXT one will
        # (it needs a previous snapshot for the update columns).
        if not report and (iteration + 1) % self.frequency != 0:
            self._prev = None
            return
        current = list(self._named_params(model))
        prev, self._prev = self._prev, {n: a for n, a in current}
        if not report:
            return
        cols = [("iteration", float(iteration)),
                ("score", float(model.score_value))]
        for name, arr in current:
            cols.extend(self._stats(name + ".p", arr))
            # first iteration has no previous params: update = 0, keeping
            # every row the same width as the header
            upd = arr - prev[name] if prev is not None and name in prev \
                else np.zeros_like(arr)
            cols.extend(self._stats(name + ".u", upd))
        if self.print_header and not self._wrote_header:
            self._emit(self.delimiter.join(n for n, _ in cols))
            self._wrote_header = True
        self._emit(self.delimiter.join(repr(v) for _, v in cols))


class CollectScoresIterationListener(IterationListener):
    """Accumulate (iteration, score) pairs (reference
    CollectScoresIterationListener).

    The callback stores the raw device scalar: a ``float()`` here would
    fence the async dispatch queue on every collected iteration, serially
    stalling the step pipeline (jaxlint JL101). Conversion to host floats
    happens lazily on the first read of :attr:`scores` — one fence for
    the whole batch of pending values, normally after fit returns.
    """

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, int(frequency))
        self._raw = []
        self._scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration):
        if iteration % self.frequency == 0:
            self._raw.append((iteration, model.score_value))

    @property
    def scores(self) -> List[Tuple[int, float]]:
        if self._raw:
            pending, self._raw = self._raw, []
            self._scores.extend((i, float(s)) for i, s in pending)
        return self._scores


class EvaluativeListener(IterationListener):
    """Periodic evaluation against a held-out set (reference
    EvaluativeListener; invocation per N iterations or per epoch)."""

    def __init__(self, data, labels=None, frequency: int = 0,
                 each_epoch: bool = True, callback=None):
        self.data = data
        self.labels = labels
        self.frequency = int(frequency)
        self.each_epoch = each_epoch
        self.callback = callback
        self.evaluations = []

    def _evaluate(self, model):
        ev = model.evaluate(self.data, self.labels)
        self.evaluations.append(ev)
        if self.callback is not None:
            self.callback(model, ev)
        else:
            log.info("Evaluation: accuracy=%.4f f1=%.4f", ev.accuracy(),
                     ev.f1())

    def iteration_done(self, model, iteration):
        if self.frequency > 0 and iteration % self.frequency == 0:
            self._evaluate(model)

    def on_epoch_end(self, model, epoch):
        if self.each_epoch:
            self._evaluate(model)


class ComposableIterationListener(IterationListener):
    """Fan-out to several listeners (reference
    ComposableIterationListener)."""

    def __init__(self, *listeners: IterationListener):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration):
        for l in self.listeners:
            l.iteration_done(model, iteration)

    def on_epoch_end(self, model, epoch):
        for l in self.listeners:
            l.on_epoch_end(model, epoch)


class CheckpointListener(IterationListener):
    """Periodic checkpointing (reference CheckpointListener semantics:
    every N iterations or every N epochs, keep last K).

    Two modes: the classic `directory` mode writes bare
    ``checkpoint_{tag}.zip`` files (now atomic via save_model's
    tmp+rename path) with simple keep-last pruning; passing ``manager=``
    (a resilience.CheckpointManager) instead delegates cadence, manifest,
    checksums, and retention to the manager — the crash-safe/resumable
    format (docs/robustness.md). With a manager, the every_n/keep_last
    args are ignored (the manager carries its own). Note the listener
    counts iteration_done events as "batches"; under truncated BPTT that
    over-counts windows — resume through fit(checkpoint=) counts true
    batches."""

    def __init__(self, directory: Optional[str] = None,
                 every_n_iterations: int = 0,
                 every_n_epochs: int = 0, keep_last: int = 3,
                 manager=None):
        import os
        if (directory is None) == (manager is None):
            raise ValueError("pass exactly one of directory= or manager=")
        self.manager = manager
        self.dir = directory if manager is None else manager.directory
        if manager is None:
            os.makedirs(directory, exist_ok=True)
        self.every_n_iterations = int(every_n_iterations)
        self.every_n_epochs = int(every_n_epochs)
        self.keep_last = int(keep_last)
        self.saved: List[str] = []
        self._batches_into_epoch = 0

    def _save(self, model, tag: str):
        import os
        from ..utils.model_serializer import save_model
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        save_model(model, path)
        self.saved.append(path)
        while len(self.saved) > self.keep_last:
            old = self.saved.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass

    def iteration_done(self, model, iteration):
        if self.manager is not None:
            self._batches_into_epoch += 1
            self.manager.on_batch(model, self._batches_into_epoch)
            return
        if self.every_n_iterations > 0 and \
                iteration % self.every_n_iterations == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model, epoch):
        if self.manager is not None:
            self._batches_into_epoch = 0
            self.manager.on_epoch(model)
            return
        if self.every_n_epochs > 0 and epoch % self.every_n_epochs == 0:
            self._save(model, f"epoch_{epoch}")
