"""Process-global metrics registry: the single source of truth for
training telemetry.

The reference routes every number through BaseStatsListener → StatsStorage
and renders it in the Play UI; here the scattered fragments (compile
counts in telemetry.py, throughput strings in PerformanceListener, RSS
snapshots in ui/stats.py) fold into ONE thread-safe registry of labeled
Counter / Gauge / Histogram families, exported two ways:

* `registry().prometheus_text()` — Prometheus text exposition format
  (`GET /metrics` on the UI server scrapes this).
* `registry().snapshot()` — a flat {name{labels}: value} dict, embedded
  in bench.py's BENCH JSON so a timed-out run still leaves telemetry
  behind.

Device visibility: a runtime collector samples
`jax.local_devices()[i].memory_stats()` at scrape time into per-device
`device_bytes_in_use` / `device_peak_bytes_in_use` gauges (0 on
backends that expose no stats, e.g. CPU), plus host RSS with the
platform-correct `ru_maxrss` units (KiB on Linux, BYTES on Darwin —
the 1024× bug this helper exists to kill).

Overhead: a counter bump is a dict lookup + lock; sampling (devices,
jit caches) happens only at scrape/snapshot time, never in the step
loop. Nothing here fences the device.
"""
from __future__ import annotations

import collections
import resource
import sys
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "host_rss_bytes", "device_memory_stats", "record_train_step",
    "register_jit_probe",
]

# Invalid label/metric characters are the caller's problem — names here
# are all code-authored. Prometheus escaping rules for label VALUES are
# applied on export (backslash, quote, newline).
_LABEL_ESCAPES = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def _escape_label(v: str) -> str:
    return "".join(_LABEL_ESCAPES.get(c, c) for c in str(v))


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_str(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


class _Family:
    """One named metric family; children keyed by their label set.
    Unlabeled use (`family.inc()`) operates on the empty-label child."""

    kind = "untyped"

    def __init__(self, name: str, help: str, lock: threading.RLock):
        self.name = name
        self.help = help
        self._lock = lock
        self._children: Dict[Tuple[Tuple[str, str], ...], "_Family"] = {}
        self._value = 0.0

    def labels(self, **labels) -> "_Family":
        key = _label_key(labels)
        if not key:
            return self
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self.help, self._lock)
                self._children[key] = child
            return child

    def touch(self, **labels) -> "_Family":
        """Materialize the labeled child at its zero value without
        changing it — pre-registration, so a snapshot can distinguish
        'this label set never fired' (exported 0) from 'this code path
        never ran' (absent)."""
        return self.labels(**labels)

    # ---- iteration over (label_key, child) incl. the bare child --------
    def _cells(self):
        with self._lock:
            items = list(self._children.items())
        out = []
        if not items or self._touched():
            out.append(((), self))
        out.extend(items)
        return out

    def _touched(self) -> bool:
        return not self._children  # bare families always export

    def items(self) -> List[Tuple[Dict[str, str], "_Family"]]:
        """[(labels_dict, child)] snapshot including the bare child when
        it exports — the scrape-side iteration surface the gateway's
        percentile collector and the SLO monitor walk."""
        return [(dict(key), child) for key, child in self._cells()]

    def value(self, **labels) -> float:
        child = self.labels(**labels)
        with self._lock:
            return child._value

    def total(self, **labels) -> float:
        """Sum of this family's value across every label set (the
        label-blind aggregate bench extras and health summaries want:
        e.g. breaker transitions regardless of target state).
        Histograms aggregate their observation counts. A label filter
        (`total(outcome="canary_rejected")`) sums only the children
        whose label set carries every given pair — the bare child never
        matches a non-empty filter."""
        want = {(k, str(v)) for k, v in labels.items()}
        with self._lock:
            cells = [((), self)] + list(self._children.items())
            tot = 0.0
            for key, c in cells:
                if want and not want.issubset(set(key)):
                    continue
                tot += c._n if isinstance(self, Histogram) else c._value
            return float(tot)


class Counter(_Family):
    """Monotonic counter (Prometheus counter semantics)."""

    kind = "counter"

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount


class Gauge(_Family):
    """Set-anytime value (scores, queue depths, memory bytes)."""

    kind = "gauge"

    def __init__(self, name, help, lock):
        super().__init__(name, help, lock)
        self._set = False

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._set = True

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self._set = True

    def _touched(self) -> bool:
        return self._set or not self._children


DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 10000.0)


class Histogram(_Family):
    """Cumulative-bucket histogram (Prometheus histogram exposition:
    `_bucket{le=...}`, `_sum`, `_count`) plus a bounded ring of recent
    (timestamp, value) observations for *windowed* quantiles — the
    cumulative buckets answer "over the process lifetime", the ring
    answers "over the last N seconds" (what an SLO verdict needs)."""

    kind = "histogram"

    # Ring capacity per child: at 2048 the window math matches the
    # recent-latency deques it replaced; beyond it the OLDEST
    # observations drop first, so a saturated ring under-reports the
    # window span, never the recency.
    RING = 2048

    def __init__(self, name, help, lock,
                 buckets: Iterable[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self._sum = 0.0
        self._n = 0
        self._ring: "collections.deque" = collections.deque(maxlen=self.RING)
        self._exemplar: Optional[Tuple[str, float]] = None

    def labels(self, **labels) -> "Histogram":
        key = _label_key(labels)
        if not key:
            return self
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Histogram(self.name, self.help, self._lock,
                                  self.buckets)
                self._children[key] = child
            return child

    def observe(self, value: float, t: Optional[float] = None) -> None:
        """Record one observation. `t` overrides the ring timestamp
        (time.monotonic() by default) — the fake-clock seam windowed
        tests inject through, paired with `now=` on quantile()."""
        v = float(value)
        ts = time.monotonic() if t is None else float(t)
        with self._lock:
            self._sum += v
            self._n += 1
            self._ring.append((ts, v))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def window_values(self, window_s: Optional[float] = None,
                      now: Optional[float] = None) -> List[float]:
        """Observations from the last `window_s` seconds (ring-bounded;
        None = everything still in the ring), oldest first. `now`
        defaults to time.monotonic() — pass the same clock observe()
        was stamped with when injecting a fake one. The window is
        two-sided, (now - window_s, now]: an observation stamped AFTER
        `now` is on a different clock (a fake-clock test sharing the
        process-global registry with a real-clock reader) and must not
        leak into this reader's view of "recent"."""
        cutoff = None
        if window_s is not None:
            ref = time.monotonic() if now is None else float(now)
            cutoff = (ref - float(window_s), ref)
        with self._lock:
            if cutoff is None:
                return [v for _, v in self._ring]
            return [v for ts, v in self._ring
                    if cutoff[0] <= ts <= cutoff[1]]

    def quantile(self, q: float, window_s: Optional[float] = None,
                 now: Optional[float] = None) -> float:
        """Nearest-rank quantile over the windowed ring (0.0 when no
        observation lands in the window) — the ONE latency-percentile
        definition the scrape gauges, /stats, and the SLO monitor all
        share."""
        vals = sorted(self.window_values(window_s, now=now))
        if not vals:
            return 0.0
        qf = min(1.0, max(0.0, float(q)))
        idx = min(len(vals) - 1, int(round(qf * (len(vals) - 1))))
        return float(vals[idx])

    def exemplar(self, trace_id: str, value: float) -> None:
        """Attach the most recent exemplar observation (a request id the
        flight recorder holds a full phase timeline for). Exposed as an
        OpenMetrics-style comment after the `_count` line so a scrape
        links a tail bucket to `GET /debug/requests`."""
        with self._lock:
            self._exemplar = (str(trace_id), float(value))

    def _touched(self) -> bool:
        return self._n > 0 or not self._children

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class MetricsRegistry:
    """Thread-safe named-family registry with pluggable collectors
    (callbacks run before every export/snapshot to sample lazy sources:
    device memory, host RSS, jit caches)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []

    # ------------------------------------------------------- registration
    def _family(self, cls, name: str, help: str, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, self._lock, **kw)
                self._families[name] = fam
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {fam.kind}")
            return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._family(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._family(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._family(Histogram, name, help, buckets=buckets)

    def register_collector(self, fn: Callable[["MetricsRegistry"], None]):
        with self._lock:
            self._collectors.append(fn)
        return fn

    def collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:
                pass  # a broken sampler must never fail a scrape

    # ------------------------------------------------------------ export
    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        self.collect()
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        lines: List[str] = []
        for fam in families:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam._cells():
                if isinstance(child, Histogram):
                    cum = 0
                    for b, c in zip(child.buckets, child._counts):
                        cum += c
                        bkey = key + (("le", _fmt(b)),)
                        lines.append(
                            f"{fam.name}_bucket{_label_str(bkey)} {cum}")
                    cum += child._counts[-1]
                    ikey = key + (("le", "+Inf"),)
                    lines.append(
                        f"{fam.name}_bucket{_label_str(ikey)} {cum}")
                    lines.append(
                        f"{fam.name}_sum{_label_str(key)} "
                        f"{_fmt(child._sum)}")
                    lines.append(
                        f"{fam.name}_count{_label_str(key)} {child._n}")
                    if child._exemplar is not None:
                        tid, val = child._exemplar
                        lines.append(
                            f"# EXEMPLAR {fam.name}{_label_str(key)} "
                            f'trace_id="{_escape_label(tid)}" '
                            f"value={_fmt(val)} see=/debug/requests")
                else:
                    lines.append(
                        f"{fam.name}{_label_str(key)} "
                        f"{_fmt(child._value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, float]:
        """Flat {name{labels}: value}; histograms contribute _count and
        _sum. The bench-JSON embedding format."""
        self.collect()
        out: Dict[str, float] = {}
        with self._lock:
            families = sorted(self._families.values(),
                              key=lambda f: f.name)
        for fam in families:
            for key, child in fam._cells():
                ls = _label_str(key)
                if isinstance(child, Histogram):
                    out[f"{fam.name}_count{ls}"] = child._n
                    out[f"{fam.name}_sum{ls}"] = round(child._sum, 3)
                else:
                    out[f"{fam.name}{ls}"] = round(child._value, 6)
        return out


# ---------------------------------------------------------------------------
# Runtime samplers (host RSS, device HBM, jit caches)
# ---------------------------------------------------------------------------
def host_rss_bytes() -> float:
    """Peak resident set size in BYTES. getrusage reports ru_maxrss in
    KiB on Linux but BYTES on macOS — the unit branch lives here so no
    caller is ever 1024× off again."""
    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return float(ru) if sys.platform == "darwin" else float(ru) * 1024.0


def device_memory_stats() -> List[Dict[str, float]]:
    """Per-device {device, bytes_in_use, peak_bytes_in_use} sampled from
    jax.local_devices(); 0s where the backend exposes no memory_stats()
    (CPU). Shared by the scrape collector and StatsListener so the
    sampling logic has exactly one implementation."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        out.append({
            "device": str(d),
            "bytes_in_use": float(stats.get("bytes_in_use", 0) or 0),
            "peak_bytes_in_use": float(
                stats.get("peak_bytes_in_use", 0) or 0),
        })
    return out


# jit-cache probes: (label, weakref-to-jitted-fn); sampled at scrape time
# so dead networks drop out and the hot loop never touches them.
_jit_probes: List[Tuple[str, "weakref.ref"]] = []
_jit_lock = threading.Lock()


def register_jit_probe(label: str, fn) -> None:
    """Expose `jit_cache_size{fn=label}` for one jax.jit callable (the
    per-shape compile count regression tests pin). Weakly referenced:
    the probe dies with its network."""
    try:
        ref = weakref.ref(fn)
    except TypeError:
        return
    with _jit_lock:
        # replace a dead or same-labeled probe rather than accumulate
        _jit_probes[:] = [(l, r) for l, r in _jit_probes
                          if r() is not None and l != label]
        _jit_probes.append((label, ref))


def _sample_runtime(reg: MetricsRegistry) -> None:
    reg.gauge("host_rss_bytes",
              "Peak host resident set size (platform-correct units)"
              ).set(host_rss_bytes())
    g_use = reg.gauge("device_bytes_in_use",
                      "Device (HBM) bytes currently allocated; 0 when "
                      "the backend exposes no memory_stats")
    g_peak = reg.gauge("device_peak_bytes_in_use",
                       "Peak device (HBM) bytes allocated; 0 when the "
                       "backend exposes no memory_stats")
    for d in device_memory_stats():
        g_use.labels(device=d["device"]).set(d["bytes_in_use"])
        g_peak.labels(device=d["device"]).set(d["peak_bytes_in_use"])
    with _jit_lock:
        probes = list(_jit_probes)
    if probes:
        from .telemetry import jit_cache_size
        g = reg.gauge("jit_cache_size",
                      "Compiled-executable cache size per jitted fn "
                      "(-1: no probe on this jax version)")
        for label, ref in probes:
            fn = ref()
            if fn is not None:
                g.labels(fn=label).set(jit_cache_size(fn))


# ---------------------------------------------------------------------------
# Process-global registry
# ---------------------------------------------------------------------------
_registry: Optional[MetricsRegistry] = None
_registry_lock = threading.Lock()


def registry() -> MetricsRegistry:
    """The process-global registry (created on first use, with the
    runtime samplers installed)."""
    global _registry
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                reg = MetricsRegistry()
                reg.register_collector(_sample_runtime)
                reg.gauge("process_start_time_seconds",
                          "Unix time this registry was created"
                          ).set(time.time())
                _registry = reg
                # Attach the compile-event listener NOW (it feeds the
                # registry's xla_compilations_total) so a scrape sees the
                # family even before anyone asks for the count. After
                # _registry is set — telemetry calls back into registry().
                from . import telemetry
                telemetry.compilation_count()
    return _registry


def record_train_step(steps: int = 1, samples: int = 0) -> None:
    """One-call hot-loop hook for the networks' commit paths: bumps
    train_iterations_total (and train_samples_total when the caller
    knows the batch rows). Shape metadata only — never touches device
    values, so it can never fence."""
    reg = registry()
    reg.counter("train_iterations_total",
                "Optimizer steps taken (all networks)").inc(steps)
    if samples:
        reg.counter("train_samples_total",
                    "Training examples consumed").inc(samples)


def record_etl(reg: MetricsRegistry, etl_ms: float, host_ms: float,
               h2d_ms: float, samples: int = 0) -> None:
    """Per-batch data-pipeline wait (the fit loops' lastEtlTime signal),
    host/h2d split included."""
    reg.gauge("etl_ms", "Data-pipeline wait for the last batch"
              ).set(etl_ms)
    reg.gauge("etl_host_ms",
              "Host-side (producer) share of the last ETL wait"
              ).set(host_ms)
    reg.gauge("etl_h2d_ms",
              "Host-to-device transfer share of the last ETL wait"
              ).set(h2d_ms)
    reg.histogram("etl_wait_ms",
                  "Distribution of per-batch data-pipeline waits"
                  ).observe(etl_ms)
    if samples:
        reg.counter("train_samples_total",
                    "Training examples consumed").inc(samples)


def batch_rows(ds) -> int:
    """Batch size of a DataSet / MultiDataSet from shape METADATA only
    (np.asarray on a device-resident batch would d2h-copy in the hot
    loop)."""
    try:
        f = getattr(ds, "features", None)
        if f is None:
            return 0
        if isinstance(f, (list, tuple)):
            f = f[0] if f else None
        shape = getattr(f, "shape", None)
        return int(shape[0]) if shape else 0
    except Exception:
        return 0
