"""Fault-tolerant training control plane (docs/robustness.md).

Four pillars on top of the serializer/metrics/tracing stack:

* **Crash-safe checkpointing** — :class:`CheckpointManager`: atomic
  checkpoint files (utils/model_serializer.save_model writes tmp + fsync
  + rename), an atomically-replaced ``manifest.json`` recording
  step/epoch/mid-epoch position plus a sha256 content checksum per
  checkpoint, and `keep_last` / `keep_every_n_epochs` retention.
* **Auto-resume** — ``fit(..., checkpoint=mgr, resume=True)`` restores
  the newest *valid* checkpoint (torn/corrupt files are skipped with a
  warning), fast-forwards epoch/iteration/batch counters, and restores
  the dropout key stream so the resumed run is bitwise-identical to an
  uninterrupted one (deterministic, unshuffled pipelines).
* **Divergence sentinels** — :class:`DivergenceSentinel`: one fused
  jitted all-finite reduction over loss+params per checked step, with
  policy ``warn | skip_step | rollback`` (rollback = restore the last
  checkpoint + LR backoff through the updaters).
* **Retry/backoff** — :class:`RetryPolicy` + :func:`retry_call`:
  exponential backoff with jitter and a wall-clock deadline, used by the
  parameter-server transport and remote workers; every retry increments
  ``retries_total{edge}`` and emits a span.

All recovery actions are observable: counters registered by
:func:`register_metrics` (surfaced by ``bench.py --once`` and the
``/metrics`` endpoint) and spans in the trace ring.
"""
from __future__ import annotations

import itertools
import json
import hashlib
import logging
import os
import random as _random
import time
from dataclasses import dataclass
from http.client import HTTPException
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import faults
from ..utils.faults import FaultInjected
from ..utils.model_serializer import (CheckpointCorruptError,
                                      load_checkpoint_state, restore_model,
                                      save_model, validate_checkpoint)
from . import metrics as metrics_mod
from . import tracing

log = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"

# one help string per family so every call site registers identically
_HELP = {
    "checkpoint_saves_total": "Checkpoints written by CheckpointManager",
    "restores_total": "Checkpoint restores (auto-resume + rollback)",
    "checkpoint_corrupt_total":
        "Checkpoints skipped as torn/corrupt during restore scans",
    "nonfinite_steps_total":
        "Training steps where the divergence sentinel saw a non-finite "
        "loss or parameter, by policy",
    "rollbacks_total":
        "Divergence rollbacks (checkpoint restored + LR backoff applied)",
    "retries_total": "Transient-failure retries per distributed edge",
    "worker_respawns_total":
        "Parameter-server worker loops respawned after an error",
}


def register_metrics(reg=None):
    """Pre-register every resilience counter family so they appear in
    snapshots/exposition even before the first recovery event."""
    reg = reg or metrics_mod.registry()
    for name, help_ in _HELP.items():
        reg.counter(name, help_)
    return reg


def _counter(name: str):
    return metrics_mod.registry().counter(name, _HELP[name])


def counter(name: str):
    """Public accessor for a resilience counter family (by `_HELP` name)
    — lets sibling layers (e.g. the multihost StepCheckpointManager)
    bump shared families like ``checkpoint_corrupt_total`` without
    duplicating help strings."""
    return _counter(name)


# ---------------------------------------------------------------------------
# Retry/backoff
# ---------------------------------------------------------------------------

#: exception types retried by default: flaky transport (URLError/HTTPError/
#: timeouts are OSError subclasses; HTTPException covers half-closed
#: keep-alives) plus injected transient faults.
TRANSIENT_ERRORS: Tuple[type, ...] = (OSError, HTTPException, FaultInjected)

_jitter_rand = _random.Random()


@dataclass
class RetryPolicy:
    """Exponential backoff with full-range jitter and a deadline.

    Delay before retry *k* (0-based) is
    ``min(base_delay * multiplier**k, max_delay) * (1 ± jitter)``.
    ``deadline`` bounds total elapsed time across attempts; a retry that
    would sleep past it re-raises instead. ``max_retries=0`` disables
    retrying entirely.
    """

    max_retries: int = 5
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.25
    deadline: Optional[float] = 30.0

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        """Build from ``DL4JTPU_RETRY_*`` env knobs (docs/robustness.md):
        MAX, BASE_MS, MULT, MAX_MS, JITTER, DEADLINE_S."""
        e = os.environ.get
        return cls(
            max_retries=int(e("DL4JTPU_RETRY_MAX", 5)),
            base_delay=float(e("DL4JTPU_RETRY_BASE_MS", 50)) / 1000.0,
            multiplier=float(e("DL4JTPU_RETRY_MULT", 2.0)),
            max_delay=float(e("DL4JTPU_RETRY_MAX_MS", 2000)) / 1000.0,
            jitter=float(e("DL4JTPU_RETRY_JITTER", 0.25)),
            deadline=float(e("DL4JTPU_RETRY_DEADLINE_S", 30)) or None,
        )

    def delay(self, attempt: int, rand=None) -> float:
        d = min(self.base_delay * (self.multiplier ** attempt),
                self.max_delay)
        if self.jitter:
            r = (rand or _jitter_rand).random()      # U[0,1)
            d *= 1.0 + self.jitter * (2.0 * r - 1.0)
        return max(0.0, d)


def retry_call(fn: Callable[[], Any], *, edge: str,
               policy: Optional[RetryPolicy] = None,
               retryable: Tuple[type, ...] = TRANSIENT_ERRORS,
               clock: Callable[[], float] = time.monotonic,
               sleep: Callable[[float], None] = time.sleep,
               rand=None) -> Any:
    """Call `fn` with the policy's backoff schedule on transient errors.

    Non-retryable exceptions propagate immediately; retryable ones
    propagate once the attempt budget or deadline is exhausted. Each
    retry increments ``retries_total{edge}`` and emits a span.
    `clock`/`sleep`/`rand` are injectable for fake-clock tests.
    """
    policy = policy or RetryPolicy.from_env()
    start = clock()
    for attempt in itertools.count():
        try:
            return fn()
        except retryable as e:
            if attempt >= policy.max_retries:
                raise
            delay = policy.delay(attempt, rand)
            if policy.deadline is not None and \
                    (clock() - start) + delay > policy.deadline:
                log.warning("%s: retry deadline (%.1fs) exhausted after "
                            "%d attempt(s); giving up on %s",
                            edge, policy.deadline, attempt + 1, e)
                raise
            _counter("retries_total").labels(edge=edge).inc()
            log.warning("%s: transient failure (attempt %d/%d): %s; "
                        "retrying in %.0f ms", edge, attempt + 1,
                        policy.max_retries, e, delay * 1000.0)
            with tracing.span("retry", edge=edge, attempt=attempt):
                sleep(delay)


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class CheckpointManager:
    """Crash-safe checkpoint directory with manifest, retention, resume.

    Layout: ``<dir>/checkpoint-<iteration>.zip`` files (atomic writes via
    save_model) plus an atomically-replaced ``manifest.json``::

        {"format_version": 1, "checkpoints": [
            {"file": ..., "iteration": N, "epoch": E,
             "batches_into_epoch": B, "sha256": ..., "size": ...}, ...]}

    ``epoch`` counts *completed* epochs at save time and
    ``batches_into_epoch`` the batches already consumed in the epoch in
    flight — exactly what fit needs to fast-forward on resume. A save
    interrupted by SIGKILL (see the ``checkpoint.write`` fault point)
    leaves the manifest pointing at the previous complete checkpoint.

    Cadence (used by the fit-loop hooks and the listener adapter):
    `save_every_n_iterations` saves mid-epoch on iteration multiples;
    `save_every_n_epochs` saves at epoch boundaries (default every
    epoch). Retention: `keep_last` newest are kept, plus epoch-boundary
    checkpoints of every `keep_every_n_epochs`-th epoch are pinned.
    """

    def __init__(self, directory: str, *, keep_last: int = 3,
                 keep_every_n_epochs: Optional[int] = None,
                 save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = 1,
                 save_updater: bool = True):
        self.directory = os.path.abspath(os.fspath(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.keep_last = int(keep_last)
        self.keep_every_n_epochs = keep_every_n_epochs
        self.save_every_n_iterations = save_every_n_iterations
        self.save_every_n_epochs = save_every_n_epochs
        self.save_updater = bool(save_updater)

    # ------------------------------------------------------------- manifest
    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    def checkpoints(self) -> List[Dict[str, Any]]:
        """Manifest records, oldest → newest. Falls back to a directory
        scan (no checksums) when the manifest is missing/unreadable, so a
        directory of bare checkpoint files is still resumable."""
        try:
            with open(self.manifest_path, "r", encoding="utf-8") as f:
                recs = json.load(f).get("checkpoints", [])
            if isinstance(recs, list):
                return recs
        except (OSError, ValueError):
            pass
        recs = []
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.startswith("checkpoint-")
                           and n.endswith(".zip"))
        except OSError:
            names = []
        for n in names:
            recs.append({"file": n})
        return recs

    def _write_manifest(self, records: List[Dict[str, Any]]) -> None:
        payload = json.dumps({"format_version": 1, "checkpoints": records},
                             indent=1)
        tmp = f"{self.manifest_path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)

    def _path(self, rec: Dict[str, Any]) -> str:
        return os.path.join(self.directory, rec["file"])

    # ----------------------------------------------------------------- save
    def save(self, model, *, batches_into_epoch: int = 0,
             normalizer=None) -> Dict[str, Any]:
        """Atomically write a checkpoint + updated manifest; prune."""
        fname = f"checkpoint-{int(model.iteration):08d}.zip"
        path = os.path.join(self.directory, fname)
        with tracing.span("checkpoint/save", iteration=int(model.iteration)):
            save_model(model, path, save_updater=self.save_updater,
                       normalizer=normalizer)
            rec = {
                "file": fname,
                "iteration": int(model.iteration),
                "epoch": int(model.epoch),
                "batches_into_epoch": int(batches_into_epoch),
                "sha256": _sha256(path),
                "size": os.path.getsize(path),
            }
            records = [r for r in self.checkpoints()
                       if r.get("file") != fname]
            records.append(rec)
            records.sort(key=lambda r: (r.get("iteration", -1),
                                        r.get("file", "")))
            records = self._prune(records)
            self._write_manifest(records)
        _counter("checkpoint_saves_total").inc()
        return rec

    def _prune(self, records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
        if self.keep_last <= 0 or len(records) <= self.keep_last:
            return records
        keep_ids = {id(r) for r in records[-self.keep_last:]}
        kept = []
        for r in records:
            n = self.keep_every_n_epochs
            pinned = (n and r.get("batches_into_epoch", 0) == 0
                      and r.get("epoch", 0) > 0
                      and r.get("epoch", 0) % n == 0)
            if id(r) in keep_ids or pinned:
                kept.append(r)
            else:
                try:
                    os.unlink(self._path(r))
                except OSError:
                    pass
        return kept

    # -------------------------------------------------------------- restore
    def _valid(self, rec: Dict[str, Any]) -> bool:
        path = self._path(rec)
        if not os.path.exists(path):
            return False
        want = rec.get("sha256")
        if want and _sha256(path) != want:
            return False
        try:
            validate_checkpoint(path, deep=not want)
        except CheckpointCorruptError:
            return False
        return True

    def latest_valid(self) -> Optional[Dict[str, Any]]:
        """Newest checkpoint that passes checksum + structural validation;
        torn/corrupt ones are skipped with a warning."""
        for rec in reversed(self.checkpoints()):
            if self._valid(rec):
                return rec
            _counter("checkpoint_corrupt_total").inc()
            log.warning("skipping torn/corrupt checkpoint %s in %s",
                        rec.get("file"), self.directory)
        return None

    def restore_into(self, model) -> Optional[Dict[str, Any]]:
        """Load the newest valid checkpoint's training state into an
        existing model; returns its manifest record (None if no valid
        checkpoint exists)."""
        rec = self.latest_valid()
        if rec is None:
            return None
        path = self._path(rec)
        with tracing.span("checkpoint/restore", file=rec.get("file")):
            meta = load_checkpoint_state(model, path,
                                         load_updater=self.save_updater)
        _counter("restores_total").inc()
        out = dict(rec)
        out.setdefault("iteration", meta.get("iteration", 0))
        out.setdefault("epoch", meta.get("epoch", 0))
        out.setdefault("batches_into_epoch", 0)
        return out

    def restore_latest(self, load_updater: bool = True):
        """Rebuild a fresh model from the newest valid checkpoint.
        Returns ``(model, record)`` or ``(None, None)``."""
        rec = self.latest_valid()
        if rec is None:
            return None, None
        with tracing.span("checkpoint/restore", file=rec.get("file")):
            model = restore_model(self._path(rec), load_updater=load_updater)
        _counter("restores_total").inc()
        return model, rec

    # ------------------------------------------------- fit-loop cadence hooks
    def on_batch(self, model, batches_into_epoch: int) -> None:
        n = self.save_every_n_iterations
        if n and int(model.iteration) % n == 0:
            self.save(model, batches_into_epoch=batches_into_epoch)

    def on_epoch(self, model) -> None:
        n = self.save_every_n_epochs
        if n and int(model.epoch) % n == 0:
            self.save(model, batches_into_epoch=0)

    def listener(self):
        """An IterationListener adapter driving this manager from
        `model.add_listener(...)` (for loops that don't take
        ``checkpoint=``, e.g. custom training drivers)."""
        from .listeners import CheckpointListener
        return CheckpointListener(manager=self)


# ---------------------------------------------------------------------------
# Divergence sentinel
# ---------------------------------------------------------------------------

class DivergenceError(RuntimeError):
    """Training diverged and the sentinel could not (or may no longer)
    recover: no valid checkpoint, or the rollback budget is exhausted."""


class DivergenceSentinel:
    """Per-step non-finite watchdog for the fit loops.

    After each (checked) step, one fused jitted reduction computes a
    single all-finite flag over the step loss and every floating-point
    parameter leaf — one scalar device read, amortizable via
    `check_every`. On a non-finite flag:

    * ``warn`` — log + count, keep training;
    * ``skip_step`` — restore the pre-step params/updater/RNG snapshot
      (kept as a device-side copy each step, safe against buffer
      donation) and continue — the poisoned batch's update is dropped;
    * ``rollback`` — restore the newest valid checkpoint from the
      attached :class:`CheckpointManager`, multiply every updater's
      learning rate by `lr_backoff`, and invalidate the compiled train
      steps (the LR is baked into the trace). At most `max_rollbacks`
      before :class:`DivergenceError`.

    The ``step.nonfinite`` fault point forces the flag for chaos tests.
    """

    POLICIES = ("warn", "skip_step", "rollback")

    def __init__(self, policy: str = "warn", *,
                 checkpoint: Optional[CheckpointManager] = None,
                 lr_backoff: float = 0.5, check_every: int = 1,
                 max_rollbacks: int = 3):
        if policy not in self.POLICIES:
            raise ValueError(f"policy must be one of {self.POLICIES}, "
                             f"got {policy!r}")
        if policy == "rollback" and checkpoint is None:
            raise ValueError("policy='rollback' requires a checkpoint= "
                             "CheckpointManager to roll back to")
        if policy == "skip_step" and int(check_every) != 1:
            # a step-k NaN detected at step k+j would restore an
            # already-poisoned snapshot
            raise ValueError("policy='skip_step' requires check_every=1")
        self.policy = policy
        self.checkpoint = checkpoint
        self.lr_backoff = float(lr_backoff)
        self.check_every = max(1, int(check_every))
        self.max_rollbacks = int(max_rollbacks)
        self.rollbacks = 0
        self.nonfinite_steps = 0
        self._snapshot = None
        self._flag_fn = None

    # ------------------------------------------------------------- fit hooks
    def before_step(self, model) -> None:
        if self.policy != "skip_step":
            return
        from ..utils.params import tree_copy
        import jax.numpy as jnp
        # fresh copies every step: the train step DONATES the live trees,
        # so a snapshot must never alias them
        self._snapshot = (
            tree_copy(model.params_tree),
            tree_copy(model.opt_state),
            tree_copy(model.state_tree),
            None if model._rng is None else jnp.array(model._rng),
            int(model.iteration),
        )

    def after_step(self, model) -> bool:
        """Returns True when a non-finite step was detected (and the
        policy's recovery action was applied)."""
        if self.check_every > 1 and \
                int(model.iteration) % self.check_every != 0:
            return False
        if not self._nonfinite(model):
            self._snapshot = None
            return False
        self.nonfinite_steps += 1
        _counter("nonfinite_steps_total").labels(policy=self.policy).inc()
        with tracing.span("sentinel/" + self.policy,
                          iteration=int(model.iteration)):
            if self.policy == "warn":
                log.warning("non-finite loss/params at iteration %d "
                            "(policy=warn: continuing)", model.iteration)
            elif self.policy == "skip_step":
                self._skip_step(model)
            else:
                self._rollback(model)
        return True

    # -------------------------------------------------------------- internals
    def _nonfinite(self, model) -> bool:
        if faults.check("step.nonfinite"):
            return True
        import jax
        import jax.numpy as jnp
        if self._flag_fn is None:
            def _all_finite(loss, params):
                ok = jnp.all(jnp.isfinite(jnp.asarray(loss, jnp.float32)))
                for leaf in jax.tree_util.tree_leaves(params):
                    if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
                        ok = ok & jnp.all(jnp.isfinite(leaf))
                return ok
            self._flag_fn = jax.jit(_all_finite)
        loss = model.score_value
        if loss is None:
            loss = jnp.float32(0.0)
        return not bool(self._flag_fn(loss, model.params_tree))

    def _skip_step(self, model) -> None:
        if self._snapshot is None:
            log.warning("non-finite step but no pre-step snapshot; "
                        "falling back to warn")
            return
        params, opt, state, rng, iteration = self._snapshot
        self._snapshot = None
        model.params_tree = params
        model.opt_state = opt
        model.state_tree = state
        if rng is not None:
            model._rng = rng
        model.iteration = iteration   # setter drops the device-side cache
        model.score_value = None
        log.warning("non-finite step at iteration %d: update dropped, "
                    "pre-step state restored (policy=skip_step)", iteration)

    def _rollback(self, model) -> None:
        if self.rollbacks >= self.max_rollbacks:
            raise DivergenceError(
                f"training diverged {self.rollbacks + 1} times; rollback "
                f"budget ({self.max_rollbacks}) exhausted")
        rec = self.checkpoint.restore_into(model)
        if rec is None:
            raise DivergenceError(
                "non-finite step with policy='rollback' but no valid "
                f"checkpoint in {self.checkpoint.directory}")
        self.rollbacks += 1
        for layer in _iter_layers(model):
            upd = getattr(layer, "updater", None)
            if upd is not None and getattr(upd, "learning_rate", None):
                upd.learning_rate = float(upd.learning_rate) * self.lr_backoff
        # the learning rate is baked into the compiled train step: drop
        # the jitted entry points so the next step retraces with the
        # backed-off rate
        model._build_jitted()
        model.score_value = None
        _counter("rollbacks_total").inc()
        log.warning("non-finite step: rolled back to %s (iteration %s), "
                    "learning rates scaled by %g (%d/%d rollbacks used)",
                    rec.get("file"), rec.get("iteration"), self.lr_backoff,
                    self.rollbacks, self.max_rollbacks)


def _iter_layers(model):
    layers = getattr(model, "layers", None)
    if layers is not None:
        return list(layers)
    return [model.conf.nodes[n].layer for n in model._layer_nodes]
