"""Bench observability plane: the scoreboard can never go dark again.

Round 5 shipped the motivating corpse: ``BENCH_r05.json`` is
``"parsed": null`` ("bench subprocess exceeded 420s with no completed
repeat") — the perf program's own measurement plane hung and the round
lost its scoreboard line. The discipline MLPerf-style harnesses apply to
workload results (every run produces a schema-valid, provenance-stamped
artifact or a *typed* failure) applies here:

* **Child liveness** — ``bench.py --once`` children publish heartbeat
  lines ``{workload, repeat, step, phase, ts}`` on a side channel (a
  file named in ``DL4JTPU_BENCH_HB_FILE``): a background beat thread
  every ~2 s proves the interpreter still schedules threads (XLA
  compiles release the GIL, so a *long compile keeps beating*), and the
  measurement loops beat with their (repeat, step) position. The parent
  :class:`ChildWatchdog` distinguishes **alive-but-slow** (fresh beats
  past the deadline → extend within the hard cap) from **wedged** (beats
  stopped → kill + typed ``"failure": "wedged"`` row) from **timeout**
  (deadline passed with no evidence of life). Ages are computed entirely
  on the PARENT's monotonic clock, same policy as the cluster health
  plane — child clock skew cannot false-trip the watchdog.

* **Tunnel probe** — :func:`probe_device` runs a tiny jitted op in a
  throwaway subprocess under its own timeout before any child is
  spawned, so a dead device tunnel reports ``"tunnel": "dead"`` instead
  of hanging the first child for the whole budget.

* **Run ledger** — every bench invocation appends one schema-validated
  row (git sha, host, backend, status, degraded/timeout flags,
  per-repeat raw values) to the append-only ``BENCH_ledger.jsonl``;
  :func:`check_rows` is the regression sentinel (`bench.py check`) and
  :func:`render_report` the trajectory view (`bench.py report`).

Fault points (``utils/faults.py``): ``bench.child`` fires on every child
heartbeat when the side channel is armed — ``delay:`` wedges the child
mid-measurement; ``bench.probe`` fires inside the probe subprocess
before it touches the device — ``delay:`` wedges the probe into a
``"tunnel": "dead"`` verdict.

Metric families (pre-registered at 0 by :func:`register_metrics` so a
snapshot distinguishes "never fired" from "absent"):
``bench_rows_total{status}``, ``bench_degraded_total``,
``bench_regressions_total``, ``bench_baseline_corrupt_total``.

Module import stays jax-free on purpose: the parent process and the
fake-clock tests exercise the watchdog/ledger machinery without paying
a backend initialization.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..utils import faults
from .metrics import registry

__all__ = [
    "ALIVE", "WEDGED", "TIMEOUT", "STATUSES", "SCHEMA_VERSION",
    "ChildWatchdog", "ChildResult", "run_child", "probe_device",
    "start_child_heartbeat", "child_heartbeat", "read_heartbeats",
    "make_row", "validate_row", "append_row", "read_ledger",
    "ledger_path", "baseline_path", "baseline_key", "load_baseline",
    "save_baseline", "check_rows", "render_report", "register_metrics",
    "host_sentinel_ms",
]

SCHEMA_VERSION = 1

# Watchdog verdicts (also the typed-failure vocabulary in artifacts).
ALIVE = "alive"
WEDGED = "wedged"
TIMEOUT = "timeout"

# Terminal row statuses the ledger schema accepts.
STATUSES = ("ok", "degraded", "wedged", "timeout", "failed",
            "dead_tunnel")

_HB_ENV = "DL4JTPU_BENCH_HB_FILE"


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------
def register_metrics() -> None:
    """Pre-register the bench plane's families (every status label at 0)
    so BENCH snapshots always carry them — including their absence of
    activity."""
    reg = registry()
    rows = reg.counter(
        "bench_rows_total",
        "Ledger rows appended by the bench scoreboard plane, by "
        "terminal status")
    for status in STATUSES:
        rows.touch(status=status)
    reg.counter("bench_degraded_total",
                "Bench invocations that fell back to the in-process "
                "reduced-config degraded mode")
    reg.counter("bench_regressions_total",
                "Regressions flagged by the bench.py check sentinel")
    reg.counter("bench_baseline_corrupt_total",
                "Corrupt/unreadable BENCH_baseline.json files tolerated "
                "(fell back to empty instead of crashing)")


# ---------------------------------------------------------------------------
# Child side: heartbeat emission
# ---------------------------------------------------------------------------
_hb_lock = threading.Lock()
_hb_pos: Dict[str, Any] = {"workload": "", "repeat": -1, "step": -1,
                           "phase": ""}
_hb_thread: Optional[threading.Thread] = None


def start_child_heartbeat(workload: str, interval_s: float = 2.0) -> bool:
    """Arm this process as a bench child: record the workload, start the
    background beat thread, and publish an immediate ``start`` beat.
    No-op (returns False) unless the parent armed the side channel via
    ``DL4JTPU_BENCH_HB_FILE``."""
    global _hb_thread
    if not os.environ.get(_HB_ENV):
        return False
    with _hb_lock:
        _hb_pos["workload"] = workload
    if _hb_thread is None or not _hb_thread.is_alive():
        _hb_thread = threading.Thread(
            target=_beat_loop, args=(interval_s,), daemon=True,
            name="bench-heartbeat")
        _hb_thread.start()
    child_heartbeat(phase="start")
    return True


def _beat_loop(interval_s: float) -> None:
    # Liveness semantics: XLA compiles release the GIL, so this thread
    # keeps beating through a minutes-long compile (alive-but-slow); a
    # process wedged hard enough to stop scheduling threads stops
    # beating and the parent's stall timeout converts that to a typed
    # failure.
    while True:
        time.sleep(interval_s)
        try:
            child_heartbeat()
        except faults.FaultInjected:
            return  # a fail: plan on bench.child silences the channel


def child_heartbeat(repeat: Optional[int] = None,
                    step: Optional[int] = None,
                    phase: Optional[str] = None) -> None:
    """Publish one heartbeat line on the side channel (no-op when the
    channel is unarmed). The ``bench.child`` fault point fires here —
    a ``delay:`` plan wedges the child between beats, which is exactly
    the failure mode the watchdog exists to catch."""
    path = os.environ.get(_HB_ENV)
    if not path:
        return
    faults.fire("bench.child")
    with _hb_lock:
        if repeat is not None:
            _hb_pos["repeat"] = int(repeat)
        if step is not None:
            _hb_pos["step"] = int(step)
        if phase is not None:
            _hb_pos["phase"] = phase
        beat = dict(_hb_pos)
    beat["ts"] = time.time()
    try:
        with open(path, "a") as f:
            f.write(json.dumps(beat) + "\n")
            f.flush()
            os.fsync(f.fileno())
    except OSError:
        pass  # a torn side channel must never fail the measurement


def read_heartbeats(path: str, offset: int = 0
                    ) -> Tuple[List[Dict[str, Any]], int]:
    """Incremental heartbeat reader: parse complete lines past `offset`
    (bytes), skip a torn tail (it is re-read on the next poll), and
    return (beats, new_offset)."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return [], offset
    beats: List[Dict[str, Any]] = []
    consumed = 0
    for line in data.splitlines(keepends=True):
        if not line.endswith(b"\n"):
            break
        consumed += len(line)
        try:
            beat = json.loads(line.decode("utf-8", "replace"))
        except ValueError:
            continue
        if isinstance(beat, dict):
            beats.append(beat)
    return beats, offset + consumed


# ---------------------------------------------------------------------------
# Parent side: watchdog + child runner
# ---------------------------------------------------------------------------
class ChildWatchdog:
    """Pure liveness state machine over one bench child (injectable
    clock — the fake-clock tests drive it without subprocesses).

    Verdicts from :meth:`decide`:

    * ``alive``   — within deadline, or past it with fresh beats and
      inside the hard cap (`extended` latches True: alive-but-slow).
    * ``wedged``  — the child HAS beaten before, then went silent for
      longer than ``stall_timeout_s``: kill + typed failure.
    * ``timeout`` — deadline passed with no beats ever (nothing to
      distinguish slow from dead), or the hard cap is exhausted.

    All ages use the parent's clock; beat payload timestamps are carried
    for diagnostics only (cross-process clock skew cannot false-trip).
    """

    def __init__(self, deadline_s: float, stall_timeout_s: float,
                 hard_cap_s: Optional[float] = None, clock=time.monotonic):
        self._clock = clock
        self._start = clock()
        self._last_activity = self._start
        self.deadline_s = float(deadline_s)
        self.stall_timeout_s = float(stall_timeout_s)
        self.hard_cap_s = max(float(hard_cap_s or 0.0), self.deadline_s)
        self.heartbeats = 0
        self.last_beat: Optional[Dict[str, Any]] = None
        self.extended = False

    def observe(self, beat: Optional[Dict[str, Any]] = None) -> None:
        # single writer: only the parent's beat-reader thread calls this
        self.heartbeats += 1  # jaxlint: atomic
        self.last_beat = beat
        self._last_activity = self._clock()

    def elapsed(self) -> float:
        return self._clock() - self._start

    def decide(self) -> str:
        now = self._clock()
        elapsed = now - self._start
        stalled = now - self._last_activity > self.stall_timeout_s
        if self.heartbeats and stalled:
            return WEDGED
        if elapsed > self.deadline_s:
            if self.heartbeats and not stalled and elapsed <= self.hard_cap_s:
                self.extended = True
                return ALIVE
            return TIMEOUT
        return ALIVE


class ChildResult:
    """Outcome of one watched child: `status` is ``ok`` / ``failed``
    (nonzero exit) / ``wedged`` / ``timeout``."""

    __slots__ = ("status", "returncode", "stdout", "stderr", "beats",
                 "last_beat", "extended", "duration_s")

    def __init__(self, status, returncode, stdout, stderr, beats,
                 last_beat, extended, duration_s):
        self.status = status
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr
        self.beats = beats
        self.last_beat = last_beat
        self.extended = extended
        self.duration_s = duration_s


def _kill(proc: "subprocess.Popen") -> None:
    try:
        proc.terminate()
        try:
            proc.wait(timeout=5)
            return
        except subprocess.TimeoutExpired:
            pass
        proc.kill()
        proc.wait(timeout=5)
    except Exception:
        pass  # already gone / unkillable: the parent moves on regardless


def run_child(cmd: Sequence[str], *, deadline_s: float,
              stall_timeout_s: float, hard_cap_s: Optional[float] = None,
              env: Optional[Dict[str, str]] = None,
              cwd: Optional[str] = None, clock=time.monotonic,
              poll_s: float = 0.25) -> ChildResult:
    """Spawn one bench child with the heartbeat side channel armed and
    watch it to a terminal verdict. stdout/stderr go to temp files (a
    pipe could deadlock on a chatty child with no reader)."""
    fd, hb_path = tempfile.mkstemp(prefix="dl4jtpu_bench_hb_",
                                   suffix=".jsonl")
    os.close(fd)
    out_path, err_path = hb_path + ".out", hb_path + ".err"
    child_env = dict(os.environ if env is None else env)
    child_env[_HB_ENV] = hb_path
    wd = ChildWatchdog(deadline_s, stall_timeout_s, hard_cap_s,
                       clock=clock)
    verdict = "ok"
    try:
        with open(out_path, "wb") as out_f, open(err_path, "wb") as err_f:
            proc = subprocess.Popen(list(cmd), stdout=out_f,
                                    stderr=err_f, env=child_env, cwd=cwd)
            offset = 0
            while True:
                rc = proc.poll()
                beats, offset = read_heartbeats(hb_path, offset)
                for b in beats:
                    wd.observe(b)
                if rc is not None:
                    break
                v = wd.decide()
                if v != ALIVE:
                    verdict = v
                    _kill(proc)
                    rc = proc.returncode
                    break
                time.sleep(poll_s)
        with open(out_path, "r", errors="replace") as f:
            stdout = f.read()
        with open(err_path, "r", errors="replace") as f:
            stderr = f.read()
        if verdict == "ok" and rc != 0:
            verdict = "failed"
        return ChildResult(verdict, rc, stdout, stderr, wd.heartbeats,
                           wd.last_beat, wd.extended, wd.elapsed())
    finally:
        for p in (hb_path, out_path, err_path):
            try:
                os.unlink(p)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Tunnel / device liveness probe
# ---------------------------------------------------------------------------
# The probe loads faults.py STANDALONE (importlib from path) so the
# bench.probe fault point fires before the heavyweight package / jax
# import — a delay:-wedged probe dies on its subprocess timeout in
# seconds, not after a backend init.
_PROBE_CODE = """\
import importlib.util, sys, time
spec = importlib.util.spec_from_file_location("bench_probe_faults", {fp!r})
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.fire("bench.probe")
t0 = time.perf_counter()
import jax
v = float(jax.jit(lambda x: x + 1.0)(1.0))
assert v == 2.0, v
print("PROBE_OK %.1f" % ((time.perf_counter() - t0) * 1000.0))
"""


def probe_device(timeout_s: float = 120.0,
                 python: Optional[str] = None) -> Dict[str, Any]:
    """Up-front tunnel/device liveness check: a tiny jitted op (with the
    scalar-fetch fence — block_until_ready does not truly wait on
    tunneled platforms) in a throwaway subprocess under its own
    timeout. Returns ``{"tunnel": "ok", "probe_ms": ...}`` or
    ``{"tunnel": "dead", "error": ...}`` — it never hangs the caller."""
    faults_path = os.path.join(_repo_root(), "deeplearning4j_tpu",
                               "utils", "faults.py")
    code = _PROBE_CODE.format(fp=faults_path)
    try:
        out = subprocess.run([python or sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s, env=dict(os.environ))
    except subprocess.TimeoutExpired:
        return {"tunnel": "dead", "timeout_s": timeout_s,
                "error": f"probe exceeded {timeout_s:.0f}s"}
    except OSError as e:
        return {"tunnel": "dead", "error": f"probe spawn failed: {e}"}
    last = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
    if out.returncode == 0 and last.startswith("PROBE_OK"):
        try:
            ms = float(last.split()[1])
        except (IndexError, ValueError):
            ms = -1.0
        return {"tunnel": "ok", "probe_ms": ms}
    return {"tunnel": "dead", "rc": out.returncode,
            "error": (out.stderr or out.stdout)[-500:]}


def host_sentinel_ms(n: int = 3) -> Tuple[float, float]:
    """Fixed busy-loop calibration: the same ~50 ms of pure-Python work
    every time, timed `n` times → (median, min) in ms. A median far
    above min — or both far above BASELINE.md's nominal — means the
    host is contended and wall-clock numbers carry that noise."""
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        s = 0
        for i in range(1_200_000):
            s += i * i
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2] * 1000, times[0] * 1000


# ---------------------------------------------------------------------------
# Run ledger (append-only BENCH_ledger.jsonl)
# ---------------------------------------------------------------------------
_REQUIRED_FIELDS: Dict[str, Any] = {
    "schema": int,
    "ts": (int, float),
    "git_sha": str,
    "host": str,
    "backend": str,
    "workload": str,
    "status": str,
    "degraded": bool,
    "timeout": bool,
    "repeats": list,
}
_OPTIONAL_FIELDS: Dict[str, Any] = {
    "metric": str,
    "value": (int, float),
    "unit": str,
    "vs_baseline": (int, float),
    "failure": str,
    "probe": dict,
    "spread": dict,
    "extras": dict,
}


def ledger_path(repo_dir: Optional[str] = None) -> str:
    return (os.environ.get("DL4JTPU_BENCH_LEDGER")
            or os.path.join(repo_dir or _repo_root(),
                            "BENCH_ledger.jsonl"))


def baseline_path(repo_dir: Optional[str] = None) -> str:
    return (os.environ.get("DL4JTPU_BENCH_BASELINE")
            or os.path.join(repo_dir or _repo_root(),
                            "BENCH_baseline.json"))


def git_sha(repo_dir: Optional[str] = None) -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=repo_dir or _repo_root())
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else "unknown"
    except Exception:
        return "unknown"


def _default_backend() -> str:
    plat = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
    if plat:
        return plat
    # Only consult jax if someone already paid for the import; the
    # parent process must stay importable without a backend.
    mod = sys.modules.get("jax")
    if mod is not None:
        try:
            return str(mod.default_backend())
        except Exception:
            pass
    return "unknown"


def make_row(workload: str, status: str, metric: Optional[str] = None,
             value: Optional[float] = None, unit: Optional[str] = None,
             *, degraded: bool = False, timeout: bool = False,
             repeats: Sequence[float] = (), failure: Optional[str] = None,
             probe: Optional[Dict[str, Any]] = None,
             spread: Optional[Dict[str, Any]] = None,
             extras: Optional[Dict[str, Any]] = None,
             vs_baseline: Optional[float] = None,
             backend: Optional[str] = None,
             ts: Optional[float] = None) -> Dict[str, Any]:
    """Build a provenance-stamped ledger row (schema version, git sha,
    host, backend) from one bench outcome."""
    row: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "ts": float(ts if ts is not None else time.time()),
        "git_sha": git_sha(),
        "host": socket.gethostname(),
        "backend": backend or _default_backend(),
        "workload": workload,
        "status": status,
        "degraded": bool(degraded),
        "timeout": bool(timeout),
        "repeats": [float(v) for v in repeats],
    }
    for key, val in (("metric", metric), ("value", value), ("unit", unit),
                     ("vs_baseline", vs_baseline), ("failure", failure),
                     ("probe", probe), ("spread", spread),
                     ("extras", extras)):
        if val is not None:
            row[key] = val
    return row


def validate_row(row: Any) -> List[str]:
    """Schema check; returns a list of problems (empty = valid). Strict
    on purpose: unknown keys are rejected so validation means
    something."""
    if not isinstance(row, dict):
        return ["row is not an object"]
    problems = []
    for field, types in _REQUIRED_FIELDS.items():
        if field not in row:
            problems.append(f"missing required field {field!r}")
        elif not isinstance(row[field], types) or isinstance(
                row[field], bool) != (types is bool):
            problems.append(
                f"field {field!r} has type {type(row[field]).__name__}")
    for field, val in row.items():
        if field in _REQUIRED_FIELDS:
            continue
        types = _OPTIONAL_FIELDS.get(field)
        if types is None:
            problems.append(f"unknown field {field!r}")
        elif not isinstance(val, types) or (
                isinstance(val, bool) and types != bool):
            problems.append(
                f"field {field!r} has type {type(val).__name__}")
    if row.get("schema") not in (None, SCHEMA_VERSION):
        problems.append(f"unsupported schema {row.get('schema')!r}")
    status = row.get("status")
    if isinstance(status, str) and status not in STATUSES:
        problems.append(f"unknown status {status!r}")
    if status in ("ok", "degraded"):
        for field in ("metric", "value", "unit"):
            if row.get(field) is None:
                problems.append(
                    f"{status} row is missing {field!r}")
    if isinstance(row.get("repeats"), list) and any(
            not isinstance(v, (int, float)) or isinstance(v, bool)
            for v in row["repeats"]):
        problems.append("repeats entries must be numbers")
    problems.extend(_validate_row_kind(row))
    return problems


# Per-workload extras contracts: a healthy row of these kinds without
# its comparison/accuracy extras is a schema violation, not a style
# choice — the quantized-serving A/B is only trustworthy if every row
# records the drift the precision introduced alongside the speedup
# (docs/serving.md §quantized: speed without an accuracy receipt is
# how silent quality regressions ship).
_ROW_KIND_EXTRAS: Dict[str, Tuple[str, ...]] = {
    "serving_quant": ("quant_speedup_int8", "quant_speedup_bf16",
                      "max_drift_int8", "max_drift_bf16"),
    "quant_matmul_ab": ("winner", "dispatch_verdict",
                        "int8_arms_bit_exact"),
    # The self-tuning A/B (docs/observability.md §"The serving control
    # loop"): a speedup without both arms' p99, the verdict, and the
    # tuner's own decision trail is unauditable.
    "serving_autotune": ("static_p99_ms", "tuned_p99_ms", "tuner_win",
                         "decision_trail"),
    # The decode A/B (docs/serving.md §decode): a tokens/sec headline
    # without the naive-recompute arm, the speedup ratio, the
    # inter-token tail, and the KV-cache utilization receipt doesn't
    # prove the paged cache earned its complexity.
    "serving_decode": ("tokens_per_sec", "naive_tokens_per_sec",
                       "kv_cache_speedup", "inter_token_p99_ms",
                       "kv_utilization"),
    # The federation chaos row (docs/serving.md §"Replica federation"):
    # an aggregate-rps headline without the single-replica baseline,
    # the eviction/failover counter receipts, and an explicit zero
    # non-typed-failure count doesn't prove the fleet scaled OR that
    # the SIGKILL arm degraded in a typed, retryable way.
    "serving_federation": ("aggregate_rps", "single_replica_rps",
                           "evictions", "failover_retries",
                           "non_typed_failures"),
}


def _validate_row_kind(row: Dict[str, Any]) -> List[str]:
    required = _ROW_KIND_EXTRAS.get(row.get("workload"))
    if not required or row.get("status") != "ok" or row.get("degraded"):
        return []  # salvage rows are exempt (they are never scored)
    extras = row.get("extras")
    if not isinstance(extras, dict):
        return [f"{row['workload']} row is missing extras "
                f"({', '.join(required)})"]
    return [f"{row['workload']} row extras missing {key!r}"
            for key in required if key not in extras]


def append_row(row: Dict[str, Any], path: Optional[str] = None) -> None:
    """Validate and append one row to the append-only ledger (write +
    flush + fsync — a crash can tear at most the final line, which
    :func:`read_ledger` tolerates). Bumps ``bench_rows_total{status}``."""
    problems = validate_row(row)
    if problems:
        raise ValueError("invalid ledger row: " + "; ".join(problems))
    with open(path or ledger_path(), "a") as f:
        f.write(json.dumps(row) + "\n")
        f.flush()
        os.fsync(f.fileno())
    registry().counter("bench_rows_total").labels(
        status=row["status"]).inc()


def read_ledger(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """All parseable rows, in append order. Torn/corrupt lines are
    skipped (counted into bench_baseline_corrupt_total's sibling spirit:
    a ledger read must never crash the sentinel)."""
    p = path or ledger_path()
    rows: List[Dict[str, Any]] = []
    try:
        with open(p, "r", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return rows
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# Best-so-far baseline (BENCH_baseline.json) — atomic + corruption-tolerant
# ---------------------------------------------------------------------------
def baseline_key(metric: str, backend: Optional[str] = None) -> str:
    """Baseline table key. Legacy unsuffixed keys are the TPU-recorded
    history (every pre-round-11 number came through the tunnel); other
    backends namespace as ``metric@backend`` so a CPU-rig run is never
    scored against TPU throughput."""
    if not backend or backend in ("tpu", "axon", "unknown"):
        return metric
    return f"{metric}@{backend}"


def load_baseline(path: Optional[str] = None) -> Dict[str, float]:
    """Best-so-far table; a corrupt/truncated/mistyped file degrades to
    empty with a ``bench_baseline_corrupt_total`` bump instead of
    crashing the scoreboard."""
    p = path or baseline_path()
    if not os.path.exists(p):
        return {}
    try:
        with open(p) as f:
            table = json.load(f)
        if isinstance(table, dict):
            if "metric" in table:  # migrate old single-metric format
                return {str(table["metric"]): float(table["value"])}
            return {str(k): float(v) for k, v in table.items()}
    except (ValueError, TypeError, OSError):
        pass
    registry().counter("bench_baseline_corrupt_total").inc()
    return {}


def save_baseline(table: Dict[str, float],
                  path: Optional[str] = None) -> None:
    """Atomic replace (same-dir tmp + fsync + os.replace, the
    utils/model_serializer discipline) — a crash mid-write can no
    longer leave a truncated baseline behind."""
    p = path or baseline_path()
    tmp = f"{p}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(table, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Regression sentinel + trajectory report
# ---------------------------------------------------------------------------
def check_rows(rows: Sequence[Dict[str, Any]],
               baseline: Dict[str, float], band: float = 0.03,
               metrics: Optional[Sequence[str]] = None
               ) -> Tuple[List[str], List[str]]:
    """Compare the freshest healthy row per metric against best-so-far
    with a noise band. The band widens to the row's own recorded
    process-to-process spread when that is larger (the round-4
    6852-vs-7014 lesson: drift without spread data reads as
    regression). Degraded rows are reported but never scored — their
    reduced configs measure a different thing. Returns
    (regressed_metrics, report_lines)."""
    latest: Dict[str, Dict[str, Any]] = {}
    skipped_degraded = 0
    for row in rows:
        metric = row.get("metric")
        if not metric:
            continue
        if row.get("status") == "ok" and not row.get("degraded"):
            latest[metric] = row  # append order: last healthy row wins
        elif row.get("status") == "degraded":
            skipped_degraded += 1
    failures: List[str] = []
    lines: List[str] = []
    for metric in sorted(latest):
        if metrics and metric not in metrics:
            continue
        row = latest[metric]
        value = float(row.get("value") or 0.0)
        key = baseline_key(metric, row.get("backend"))
        best = baseline.get(key)
        if not best or best <= 0:
            lines.append(f"  --  {metric}: no baseline under {key!r} "
                         f"(recorded {value:g})")
            continue
        eff_band = band
        spread = row.get("spread") or {}
        if value > 0 and isinstance(spread.get("min"), (int, float)) \
                and isinstance(spread.get("max"), (int, float)):
            eff_band = max(band,
                           (spread["max"] - spread["min"]) / value)
        ratio = value / best
        if ratio < 1.0 - eff_band:
            failures.append(metric)
            lines.append(
                f"  REG {metric}: {value:g} vs best {best:g} "
                f"(x{ratio:.3f}, band {eff_band:.3f})")
        else:
            lines.append(
                f"  ok  {metric}: {value:g} vs best {best:g} "
                f"(x{ratio:.3f}, band {eff_band:.3f})")
    if skipped_degraded:
        lines.append(f"  --  {skipped_degraded} degraded row(s) not "
                     "scored (reduced-config measurements)")
    return failures, lines


def _tier_extras_lines(row: Dict[str, Any]) -> List[str]:
    """Per-tier latency / shed / starvation detail for rows whose
    extras carry it (the serving_multimodel A/B) — one indented line
    per tier plus a shed/starvation summary, so `bench.py report`
    surfaces the tier SLO picture without re-running the bench."""
    extras = row.get("extras") or {}
    tiers = extras.get("tier_latency_ms")
    out: List[str] = []
    if isinstance(tiers, dict):
        for tier in sorted(tiers, key=lambda t:
                           {"critical": 0, "standard": 1,
                            "batch": 2}.get(t, 9)):
            v = tiers.get(tier) or {}
            out.append(f"      tier {tier}: p50 {v.get('p50', 0):g}ms  "
                       f"p99 {v.get('p99', 0):g}ms")
    bits = []
    if "tier_sheds" in extras:
        bits.append(f"sheds {extras['tier_sheds']}")
    if "starvation_total" in extras:
        bits.append(f"starvation {extras['starvation_total']}")
    if "fused_speedup" in extras:
        bits.append(f"fused x{extras['fused_speedup']:g}")
    # Quantized-serving A/B detail (the serving_quant / quant_matmul_ab
    # rows): speedup-with-drift so the report shows the accuracy cost
    # next to the throughput win, and the dispatch verdict for the
    # op-level row.
    if "quant_speedup_int8" in extras:
        bits.append(f"int8 x{extras['quant_speedup_int8']:g} "
                    f"(drift {extras.get('max_drift_int8', 0):g})")
    if "quant_speedup_bf16" in extras:
        bits.append(f"bf16 x{extras['quant_speedup_bf16']:g} "
                    f"(drift {extras.get('max_drift_bf16', 0):g})")
    if "dispatch_verdict" in extras:
        bits.append(f"dispatch {extras['dispatch_verdict']}")
    if bits:
        out.append("      " + "  ".join(bits))
    return out


def render_report(rows: Sequence[Dict[str, Any]],
                  baseline: Dict[str, float]) -> str:
    """Round-over-round trajectory per metric from the ledger: one
    chronological line per row with provenance and status flags."""
    by_metric: Dict[str, List[Dict[str, Any]]] = {}
    anon: List[Dict[str, Any]] = []
    for row in rows:
        metric = row.get("metric")
        if metric:
            by_metric.setdefault(metric, []).append(row)
        else:
            anon.append(row)
    out: List[str] = []
    for metric in sorted(by_metric):
        history = by_metric[metric]
        best = baseline.get(baseline_key(
            metric, history[-1].get("backend")))
        head = f"{metric}"
        if best:
            head += f"  (best {best:g})"
        out.append(head)
        for row in history:
            ts = time.strftime("%Y-%m-%dT%H:%M:%S",
                               time.localtime(row.get("ts", 0)))
            flags = row.get("status", "?")
            if row.get("degraded") and flags != "degraded":
                flags += ",degraded"
            if row.get("timeout") and flags != "timeout":
                flags += ",timeout"
            value = row.get("value")
            val = f"{value:g} {row.get('unit', '')}".strip() \
                if value is not None else "-"
            ratio = ""
            if best and value:
                ratio = f"  x{value / best:.3f}"
            out.append(f"  {ts}  sha={row.get('git_sha', '?')}  "
                       f"backend={row.get('backend', '?')}  "
                       f"[{flags}]  {val}{ratio}")
            out.extend(_tier_extras_lines(row))
    for row in anon:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S",
                           time.localtime(row.get("ts", 0)))
        out.append(f"{row.get('workload', '?')}  {ts}  "
                   f"[{row.get('status', '?')}]  "
                   f"{row.get('failure', '')}".rstrip())
    return "\n".join(out) if out else "(empty ledger)"
