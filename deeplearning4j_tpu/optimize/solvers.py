"""Second-order / line-search solvers: LineGradientDescent,
ConjugateGradient, LBFGS.

Reference parity: optimize/Solver.java:43-60 dispatches on
OptimizationAlgorithm to solvers over BaseOptimizer
(optimize/solvers/{StochasticGradientDescent,LineGradientDescent,
ConjugateGradient,LBFGS}.java + BackTrackLineSearch.java). SGD remains
the production path inside the jitted train step; these batch solvers
optimize the FULL-BATCH loss like the reference's (which the reference
itself notes are for small/full-batch problems).

TPU-native redesign: the loss is one jitted scalar function of the FLAT
parameter vector (utils/params flatten/unflatten); value+gradient come
from one jitted value_and_grad call per evaluation; direction updates
(Polak-Ribière beta, the L-BFGS two-loop recursion) are a handful of
device-side vector ops. Backtracking line search (Armijo) mirrors
BackTrackLineSearch.java's contract.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import params as param_utils


class _FlatProblem:
    """Scalar loss over the flat parameter vector of a network."""

    def __init__(self, net, x, y, fmask=None, lmask=None):
        self.net = net
        template = net.params_tree
        x = jnp.asarray(x)
        y = jnp.asarray(y)

        def loss_flat(flat):
            params = param_utils.unflatten_params(template, flat)
            loss, _ = net._loss_pure(params, net.state_tree, x, y, fmask,
                                     lmask, None, False)
            return loss

        self.value_and_grad = jax.jit(jax.value_and_grad(loss_flat))
        # value-only for line-search trials: a trial needs no gradient, so
        # skipping the backward pass roughly halves per-iteration compute
        self.value = jax.jit(loss_flat)
        self.flat0 = param_utils.flatten_params(net.params_tree)

    def commit(self, flat):
        self.net.params_tree = param_utils.unflatten_params(
            self.net.params_tree, flat)


def backtrack_line_search(value_fn, w, direction, f0, g0, *,
                          step0: float = 1.0, c1: float = 1e-4,
                          shrink: float = 0.5,
                          max_steps: int = 20) -> Tuple[jnp.ndarray, float]:
    """Armijo backtracking (reference BackTrackLineSearch.java): shrink the
    step until f(w + a·d) <= f0 + c1·a·gᵀd. `value_fn` is VALUE-ONLY (no
    backward pass per trial). Returns (new_w, new_f); falls back to the
    unmoved point when no step satisfies the condition."""
    slope = float(jnp.vdot(g0, direction))
    if slope >= 0:  # not a descent direction: flip (reference resets)
        direction = -direction
        slope = -slope
    a = step0
    for _ in range(max_steps):
        w_new = w + a * direction
        f_new = float(value_fn(w_new))
        if f_new <= f0 + c1 * a * slope:
            return w_new, f_new
        a *= shrink
    return w, f0


class BaseSolver:
    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6):
        self.max_iterations = int(max_iterations)
        self.tolerance = float(tolerance)
        self.scores: List[float] = []

    def optimize(self, net, x, y, fmask=None, lmask=None) -> float:
        """Minimize the full-batch score; commits params to the net and
        returns the final score (reference Solver.optimize())."""
        net._check_init()
        prob = _FlatProblem(net, x, y, fmask, lmask)
        w = prob.flat0
        f, g = prob.value_and_grad(w)
        f = float(f)
        self.scores = [f]
        state = self._init_state(w, g)
        for it in range(self.max_iterations):
            direction, state = self._direction(g, state)
            w_new, f_new = backtrack_line_search(
                prob.value, w, direction, f, g)
            if f - f_new < self.tolerance:
                w = w_new
                self.scores.append(f_new)
                break
            g_new = prob.value_and_grad(w_new)[1]
            state = self._post_step(state, w, w_new, g, g_new)
            w, f, g = w_new, f_new, g_new
            self.scores.append(f)
        prob.commit(w)
        net.score_value = self.scores[-1]
        return self.scores[-1]

    # hooks ---------------------------------------------------------------
    def _init_state(self, w, g):
        return None

    def _direction(self, g, state):
        raise NotImplementedError

    def _post_step(self, state, w, w_new, g, g_new):
        return state


class LineGradientDescent(BaseSolver):
    """Steepest descent + line search (reference
    solvers/LineGradientDescent.java)."""

    def _direction(self, g, state):
        return -g, state


class ConjugateGradient(BaseSolver):
    """Nonlinear CG, Polak-Ribière with restart (reference
    solvers/ConjugateGradient.java)."""

    def _init_state(self, w, g):
        return {"prev_g": g, "prev_d": -g, "first": True}

    def _direction(self, g, state):
        if state["first"]:
            d = -g
        else:
            pg = state["prev_g"]
            beta = float(jnp.vdot(g, g - pg) /
                         jnp.maximum(jnp.vdot(pg, pg), 1e-30))
            beta = max(0.0, beta)  # PR+ restart
            d = -g + beta * state["prev_d"]
        state = {**state, "prev_d": d, "first": False}
        return d, state

    def _post_step(self, state, w, w_new, g, g_new):
        return {**state, "prev_g": g}


class LBFGS(BaseSolver):
    """Limited-memory BFGS, two-loop recursion (reference
    solvers/LBFGS.java; memory m=10 like the reference default)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6,
                 memory: int = 10):
        super().__init__(max_iterations, tolerance)
        self.memory = int(memory)

    def _init_state(self, w, g):
        return {"s": [], "y": []}

    def _direction(self, g, state):
        s_list, y_list = state["s"], state["y"]
        q = g
        alphas = []
        for s, y in zip(reversed(s_list), reversed(y_list)):
            rho = 1.0 / float(jnp.maximum(jnp.vdot(y, s), 1e-30))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho))
            q = q - a * y
        if y_list:
            y_last, s_last = y_list[-1], s_list[-1]
            gamma = float(jnp.vdot(s_last, y_last) /
                          jnp.maximum(jnp.vdot(y_last, y_last), 1e-30))
            q = q * gamma
        for (a, rho), s, y in zip(reversed(alphas), s_list, y_list):
            b = rho * float(jnp.vdot(y, q))
            q = q + (a - b) * s
        return -q, state

    def _post_step(self, state, w, w_new, g, g_new):
        s = w_new - w
        y = g_new - g
        if float(jnp.vdot(s, y)) > 1e-10:  # curvature condition
            state["s"].append(s)
            state["y"].append(y)
            if len(state["s"]) > self.memory:
                state["s"].pop(0)
                state["y"].pop(0)
        return state


def solver_for(algorithm, **kw) -> BaseSolver:
    """Reference Solver.Builder dispatch (optimize/Solver.java:43-60)."""
    from ..nn.conf.builders import OptimizationAlgorithm as OA
    table = {
        OA.LINE_GRADIENT_DESCENT: LineGradientDescent,
        OA.CONJUGATE_GRADIENT: ConjugateGradient,
        OA.LBFGS: LBFGS,
    }
    if algorithm not in table:
        raise ValueError(
            f"{algorithm} has no batch solver (SGD runs inside the jitted "
            "train step via fit())")
    return table[algorithm](**kw)
