"""XLA compilation telemetry.

"One compile per epoch" is an invariant worth enforcing, not inferring:
a ragged final batch silently compiling a second train-step program
costs seconds of wall time per epoch and shows up nowhere. This module
counts backend compilations two ways:

* A process-global counter fed by a `jax.monitoring` duration listener
  on the backend-compile event — every XLA compilation in the process,
  whatever jitted function triggered it. `CompilationTracker` snapshots
  it around a region (bench.py wraps whole workloads;
  PerformanceListener reports the delta between reports).
* `jit_cache_size(fn)` — the per-function executable-cache size of one
  `jax.jit` callable (e.g. `net._train_step_fn`), the precise "how many
  distinct shapes did THIS step compile for" probe the regression tests
  pin.

The monitoring listener registers lazily on first use and never
unregisters (jax.monitoring only offers clear-all); it is a counter
bump per compilation — harmless at steady state, where the whole point
is that compilations stop happening.
"""
from __future__ import annotations

import logging
import threading

log = logging.getLogger(__name__)

_lock = threading.Lock()
_listening = False
_warned_no_monitoring = False

# The event jax records around every backend (XLA) compilation; stable
# across recent jax versions. Matching on the suffix keeps us robust to
# the '/jax/core' vs '/jax' prefix shuffle between releases.
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"


def _counter():
    # The registry is the single source of truth for the count; this
    # module owns registration + the snapshot-delta ergonomics.
    from .metrics import registry
    return registry().counter(
        "xla_compilations_total",
        "Backend (XLA) compilations observed by the jax.monitoring "
        "listener")


def _on_event(event: str, duration: float, **_kw) -> None:
    if event.endswith(_COMPILE_EVENT_SUFFIX):
        _counter().inc()


def _ensure_listener() -> bool:
    global _listening, _warned_no_monitoring
    if _listening:
        return True
    with _lock:
        if _listening:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_event)
        except Exception as e:
            # One-shot and LOUD: without this, a zero compile count is
            # indistinguishable from "listener never attached".
            if not _warned_no_monitoring:
                _warned_no_monitoring = True
                log.warning(
                    "jax.monitoring unavailable (%s): XLA compilation "
                    "counters will read 0 — compile-count telemetry is "
                    "OFF, not quiet", e)
            return False
        _listening = True
    return True


def compilation_count() -> int:
    """Process-global backend compilations observed since the listener
    registered (monotonic; meaningful as deltas). Reads the registry's
    `xla_compilations_total` counter — one source of truth with the
    `/metrics` scrape."""
    _ensure_listener()
    return int(_counter().value())


class CompilationTracker:
    """Snapshot-delta view of the global compile counter.

        with CompilationTracker() as trk:
            net.fit(it, epochs=1)
        assert trk.count == 1

    Usable as a context manager or via explicit `.start()`."""

    def __init__(self):
        self.start_count = compilation_count()

    def start(self) -> "CompilationTracker":
        self.start_count = compilation_count()
        return self

    @property
    def count(self) -> int:
        return compilation_count() - self.start_count

    def __enter__(self) -> "CompilationTracker":
        return self.start()

    def __exit__(self, *exc) -> None:
        pass


def jit_cache_size(fn) -> int:
    """Number of compiled executables cached by one jax.jit callable —
    the per-shape compile count of THAT function. Returns -1 when the
    jax version exposes no cache probe."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return -1
    try:
        return int(probe())
    except Exception:
        return -1


# ---------------------------------------------------------------------------
# Recompile-churn guard
#
# jit_cache_size says HOW MANY shapes a step compiled for; it cannot say
# the fit loop keeps feeding new ones. This guard records the distinct
# shape signatures each logical step has seen and goes loud — one
# warning plus a labeled counter — when a step crosses the threshold:
# the canonical symptom is a data pipeline emitting ragged batches
# (every epoch tail a fresh compile) or unbucketed variable-length
# sequences. bench.py surfaces the offenders in its JSON.
# ---------------------------------------------------------------------------
ENV_CHURN_THRESHOLD = "DL4JTPU_RECOMPILE_CHURN_THRESHOLD"
DEFAULT_CHURN_THRESHOLD = 5

_churn_lock = threading.Lock()
_step_signatures: dict = {}   # label -> set of signatures
_churn_warned: set = set()    # labels already warned (one-shot)


def churn_threshold() -> int:
    import os
    try:
        return int(os.environ.get(ENV_CHURN_THRESHOLD,
                                  DEFAULT_CHURN_THRESHOLD))
    except ValueError:
        return DEFAULT_CHURN_THRESHOLD


def shape_signature(*args) -> tuple:
    """Cheap hashable signature of a call's data arguments: per-arg
    (shape, dtype) with None passing through. Metadata only — never
    forces a device sync."""
    sig = []
    for a in args:
        if a is None:
            sig.append(None)
        else:
            sig.append((tuple(getattr(a, "shape", ())),
                        str(getattr(a, "dtype", ""))))
    return tuple(sig)


def note_step_signature(label: str, sig: tuple) -> int:
    """Record one call signature for a logical step; returns the number
    of distinct signatures seen. Crossing the threshold fires ONE loud
    warning per label and bumps `recompile_churn_total{fn=label}` for
    every new signature past it."""
    with _churn_lock:
        seen = _step_signatures.setdefault(label, set())
        if sig in seen:
            return len(seen)
        seen.add(sig)
        n = len(seen)
        over = n > churn_threshold()
        warn = over and label not in _churn_warned
        if warn:
            _churn_warned.add(label)
    if over:
        from .metrics import registry
        registry().counter(
            "recompile_churn_total",
            "Distinct call signatures past the churn threshold — each "
            "one was a recompile of an already-hot step"
            ).labels(fn=label).inc()
    if warn:
        log.warning(
            "RECOMPILE CHURN: %s has now been called with %d distinct "
            "shape signatures (threshold %d) — every new signature "
            "recompiles. Bucket or pad your batches "
            "(pad_to_bucket=True, docs/perf_compile_cache.md)",
            label, n, churn_threshold())
    return n


def churn_offenders(top: int = 5):
    """Worst logical steps by distinct-signature count, for bench/debug
    output: [(label, n_signatures), ...] sorted descending."""
    with _churn_lock:
        items = [(lbl, len(sigs)) for lbl, sigs in _step_signatures.items()]
    items.sort(key=lambda kv: (-kv[1], kv[0]))
    return items[:max(0, int(top))]


def reset_churn() -> None:
    """Forget recorded signatures and re-arm the one-shot warnings
    (test isolation)."""
    with _churn_lock:
        _step_signatures.clear()
        _churn_warned.clear()
