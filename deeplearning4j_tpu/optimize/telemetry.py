"""XLA compilation telemetry.

"One compile per epoch" is an invariant worth enforcing, not inferring:
a ragged final batch silently compiling a second train-step program
costs seconds of wall time per epoch and shows up nowhere. This module
counts backend compilations two ways:

* A process-global counter fed by a `jax.monitoring` duration listener
  on the backend-compile event — every XLA compilation in the process,
  whatever jitted function triggered it. `CompilationTracker` snapshots
  it around a region (bench.py wraps whole workloads;
  PerformanceListener reports the delta between reports).
* `jit_cache_size(fn)` — the per-function executable-cache size of one
  `jax.jit` callable (e.g. `net._train_step_fn`), the precise "how many
  distinct shapes did THIS step compile for" probe the regression tests
  pin.

The monitoring listener registers lazily on first use and never
unregisters (jax.monitoring only offers clear-all); it is a counter
bump per compilation — harmless at steady state, where the whole point
is that compilations stop happening.
"""
from __future__ import annotations

import logging
import threading

log = logging.getLogger(__name__)

_lock = threading.Lock()
_listening = False
_warned_no_monitoring = False

# The event jax records around every backend (XLA) compilation; stable
# across recent jax versions. Matching on the suffix keeps us robust to
# the '/jax/core' vs '/jax' prefix shuffle between releases.
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"


def _counter():
    # The registry is the single source of truth for the count; this
    # module owns registration + the snapshot-delta ergonomics.
    from .metrics import registry
    return registry().counter(
        "xla_compilations_total",
        "Backend (XLA) compilations observed by the jax.monitoring "
        "listener")


def _on_event(event: str, duration: float, **_kw) -> None:
    if event.endswith(_COMPILE_EVENT_SUFFIX):
        _counter().inc()


def _ensure_listener() -> bool:
    global _listening, _warned_no_monitoring
    if _listening:
        return True
    with _lock:
        if _listening:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_event)
        except Exception as e:
            # One-shot and LOUD: without this, a zero compile count is
            # indistinguishable from "listener never attached".
            if not _warned_no_monitoring:
                _warned_no_monitoring = True
                log.warning(
                    "jax.monitoring unavailable (%s): XLA compilation "
                    "counters will read 0 — compile-count telemetry is "
                    "OFF, not quiet", e)
            return False
        _listening = True
    return True


def compilation_count() -> int:
    """Process-global backend compilations observed since the listener
    registered (monotonic; meaningful as deltas). Reads the registry's
    `xla_compilations_total` counter — one source of truth with the
    `/metrics` scrape."""
    _ensure_listener()
    return int(_counter().value())


class CompilationTracker:
    """Snapshot-delta view of the global compile counter.

        with CompilationTracker() as trk:
            net.fit(it, epochs=1)
        assert trk.count == 1

    Usable as a context manager or via explicit `.start()`."""

    def __init__(self):
        self.start_count = compilation_count()

    def start(self) -> "CompilationTracker":
        self.start_count = compilation_count()
        return self

    @property
    def count(self) -> int:
        return compilation_count() - self.start_count

    def __enter__(self) -> "CompilationTracker":
        return self.start()

    def __exit__(self, *exc) -> None:
        pass


def jit_cache_size(fn) -> int:
    """Number of compiled executables cached by one jax.jit callable —
    the per-shape compile count of THAT function. Returns -1 when the
    jax version exposes no cache probe."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return -1
    try:
        return int(probe())
    except Exception:
        return -1
