"""Nestable span tracing over the training loop, with a Chrome
trace-event exporter.

The fit loops emit the span taxonomy `fit / epoch / step /
{etl, dispatch, device}` (docs/observability.md). Spans are
`time.perf_counter` intervals recorded into a bounded ring buffer —
O(1) memory however long training runs — and export as Chrome
trace-event-format JSON (`ph:"X"` complete events; load in
chrome://tracing or Perfetto), also served live at `GET /trace` on the
UI server.

Three design points keep steady-state overhead negligible:

* Disabled (the default), `span()` returns a shared no-op context
  manager: one branch per call site, nothing recorded.
* jax dispatch is async, so a `dispatch` span measures host-side
  enqueue time only. The sampled FENCE (`fence(step, value)`, every
  `fence_every`-th step) calls `jax.block_until_ready` and records the
  wait as a `device` span — the dispatch-side vs device-compute split.
  block_until_ready adds no computation and no compilation, so the
  1-compile-per-epoch invariant and numerics are untouched.
* `annotate=True` additionally enters `jax.profiler.TraceAnnotation`
  (and `StepTraceAnnotation` for spans carrying a `step_num` arg) so
  spans line up with XLA activity in a real profiler capture.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

__all__ = ["enable", "disable", "is_enabled", "clear", "span", "begin",
           "add_span", "fence", "export_trace_events", "dump",
           "DEFAULT_FENCE_EVERY"]

# Default fence sampling once tracing is enabled: 1 fenced step in 16
# bounds the pipelining loss to ~1/16 of one step's dispatch-ahead.
# With tracing disabled there is NO fencing at all.
DEFAULT_FENCE_EVERY = 16

_lock = threading.Lock()
_enabled = False
_annotate = False
_fence_every = 0
_ring: deque = deque(maxlen=4096)


class _NullSpan:
    """Reusable no-op: the disabled-path return of span()/begin()."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def end(self):
        pass

    def cancel(self):
        pass


_NULL = _NullSpan()


class Span:
    """One live interval; use as a context manager or via begin()/end().
    cancel() discards it (a `step` span opened before the iterator
    reported exhaustion)."""

    __slots__ = ("name", "args", "cat", "_t0", "_ann", "_done")

    def __init__(self, name: str, args: Dict[str, Any],
                 cat: Optional[str] = None):
        self.name = name
        self.args = args
        self.cat = cat
        self._ann = None
        self._done = False
        if _annotate:
            self._ann = _make_annotation(name, args)
            if self._ann is not None:
                self._ann.__enter__()
        self._t0 = time.perf_counter()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def end(self):
        if self._done:
            return
        self._done = True
        dur = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        _record(self.name, self._t0, dur, self.args, self.cat)

    def cancel(self):
        if self._done:
            return
        self._done = True
        if self._ann is not None:
            self._ann.__exit__(None, None, None)


def _make_annotation(name: str, args: Dict[str, Any]):
    try:
        from jax import profiler
        if "step_num" in args and hasattr(profiler, "StepTraceAnnotation"):
            return profiler.StepTraceAnnotation(
                name, step_num=int(args["step_num"]))
        return profiler.TraceAnnotation(name)
    except Exception:
        return None


def _record(name: str, t0: float, dur: float,
            args: Optional[Dict[str, Any]], cat: Optional[str] = None):
    ev = {"name": name, "ts": t0 * 1e6, "dur": dur * 1e6,
          "tid": threading.get_ident()}
    if cat:
        ev["cat"] = cat
    if args:
        ev["args"] = args
    _ring.append(ev)  # deque.append is atomic; maxlen bounds memory


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------
def enable(ring_size: int = 4096, annotate: bool = False,
           fence_every: int = DEFAULT_FENCE_EVERY) -> None:
    """Turn tracing on. `fence_every=0` disables the sampled device
    fence (dispatch-side timings only); `annotate=True` mirrors spans
    into jax.profiler annotations."""
    global _enabled, _annotate, _fence_every, _ring
    with _lock:
        _ring = deque(_ring, maxlen=int(ring_size))
        _annotate = bool(annotate)
        _fence_every = max(0, int(fence_every))
        _enabled = True


def disable() -> None:
    global _enabled, _annotate, _fence_every
    with _lock:
        _enabled = False
        _annotate = False
        _fence_every = 0


def is_enabled() -> bool:
    return _enabled


def clear() -> None:
    _ring.clear()


def span(name: str, cat: Optional[str] = None, **args):
    """Context manager for one interval; no-op (shared singleton) when
    tracing is disabled. `cat` tags the Chrome-export category ("train"
    when omitted)."""
    if not _enabled:
        return _NULL
    return Span(name, args, cat)


def begin(name: str, cat: Optional[str] = None, **args):
    """Explicitly-ended span for intervals that cannot nest lexically
    (the step span opened before the iterator is polled)."""
    if not _enabled:
        return _NULL
    return Span(name, args, cat)


def add_span(name: str, start: float, dur_s: float,
             cat: Optional[str] = None, **args) -> None:
    """Record a retroactive span from an already-measured interval
    (`start` in time.perf_counter seconds): the fit loops time ETL with
    perf_counter anyway, so the span costs nothing extra. `cat` tags the
    event category in the Chrome export ("train" when omitted) — the
    serving flight recorder uses "serve" so a serving incident and a
    training profile separate cleanly in one viewer."""
    if not _enabled:
        return
    _record(name, start, dur_s, args or None, cat)


def add_spans(spans, cat: Optional[str] = None, **args) -> None:
    """Bulk `add_span`: `spans` is [(name, start_s, dur_s)]. One enabled
    check and ONE shared args dict for the whole group — the flight
    recorder emits seven phase spans per served request, and per-span
    kwargs repacking is measurable at serving rates. The shared dict is
    stored by reference; callers must not mutate it afterwards."""
    if not _enabled:
        return
    shared = args or None
    tid = threading.get_ident()
    for name, start, dur_s in spans:
        ev = {"name": name, "ts": start * 1e6, "dur": dur_s * 1e6,
              "tid": tid}
        if cat:
            ev["cat"] = cat
        if shared:
            ev["args"] = shared
        _ring.append(ev)


def fence(step: int, value) -> Optional[float]:
    """Sampled dispatch-queue drain: every `fence_every`-th step, block
    until `value` (typically the committed loss) is device-complete and
    record the wait as a `device` span. Returns the wait in ms when it
    ran, else None. No-op when tracing is off or fence_every == 0."""
    if not _enabled or _fence_every <= 0 or value is None:
        return None
    if step % _fence_every != 0:
        return None
    t0 = time.perf_counter()
    try:
        import jax
        jax.block_until_ready(value)
    except Exception:
        return None
    dur = time.perf_counter() - t0
    _record("device", t0, dur, {"step": int(step)})
    return dur * 1000.0


def export_trace_events() -> Dict[str, Any]:
    """Chrome trace-event-format dict: {"traceEvents": [...],
    "displayTimeUnit": "ms"}. Events are ph:"X" completes; nesting is
    derived by the viewer from ts/dur containment per tid."""
    pid = os.getpid()
    events = []
    for ev in list(_ring):
        out = {"name": ev["name"], "ph": "X", "pid": pid,
               "tid": ev["tid"], "ts": round(ev["ts"], 3),
               "dur": round(ev["dur"], 3), "cat": ev.get("cat", "train")}
        if "args" in ev:
            out["args"] = ev["args"]
        events.append(out)
    events.sort(key=lambda e: e["ts"])
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump(path: str) -> str:
    """Write the current ring as trace-event JSON; returns the path."""
    with open(path, "w") as f:
        json.dump(export_trace_events(), f)
    return path
