"""Parallelism: device meshes, data-parallel training, batched inference.

Replaces the reference's entire deeplearning4j-scaleout tree (ParallelWrapper
thread zoo, Spark parameter averaging, Aeron parameter server — SURVEY.md
§2.4) with sharded jit over a jax.sharding.Mesh.
"""
from .cluster_health import (BarrierTimeoutError, ClusterDesyncError,
                             ClusterHealthError, ClusterHealthMonitor,
                             GraceCheckpointed, HealthConfig, PeerLostError,
                             timed_collective)
from .inference import (DeadlineExceededError, DecodeStepError,
                        InferenceMode, KVCacheExhaustedError,
                        ParallelInference, QueueFullError, ServerClosedError)
from .multihost import (CheckpointManager, MultiHostRunner,
                        StepCheckpointManager)
from .mesh import (DATA_AXIS, MODEL_AXIS, SEQ_AXIS, batch_sharded,
                   create_mesh, data_parallel_mesh, replicate, replicated,
                   shard_batch)
from .param_server import (HttpParameterServerClient, ParameterServer,
                           ParameterServerHttpNode, ParameterServerTrainer,
                           remote_worker_fit)
from .pipeline import PipelineParallelWrapper, pipeline_mesh
from .sequence import SequenceParallelWrapper, seq_parallel_mesh
from .tensor import TensorParallelWrapper, tensor_parallel_mesh
from .wrapper import ParallelWrapper
