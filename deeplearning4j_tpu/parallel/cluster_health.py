"""Cluster health plane for multihost training (docs/robustness.md).

`MultiHostRunner` guards lockstep *counts* up front, but once the SPMD
loop runs, a peer that dies, stalls, or is preempted turns every
surviving process into a silent deadlock at the next collective. This
module converts those silent hangs into prompt **typed** failures and
preemption into a clean checkpoint, with four cooperating pieces:

* **Heartbeat watchdog** — :class:`ClusterHealthMonitor`: a per-process
  background thread exchanging ``(process_id, step, ts)`` beats over a
  lightweight side channel (chief-hosted ``JsonHttpServer``; an
  in-process transport for tests). A peer whose beats go stale past
  ``timeout_s`` raises :class:`PeerLostError`; a peer that keeps beating
  but stops advancing its step while others advance raises
  :class:`ClusterDesyncError` — both carry the offending peer ids, and
  the default failure action hard-exits the process (exit code
  :data:`ClusterHealthMonitor.EXIT_CODE`) so the job restarter can act
  instead of burning a pod on a wedged collective.
* **Timed collectives** — :func:`timed_collective` wraps a blocking
  collective (barrier / lockstep allgather) in a watchdog deadline and
  raises :class:`BarrierTimeoutError` instead of hanging forever.
* **Preemption grace** — a SIGTERM flag (``request_grace``) rides the
  beats; `MultiHostRunner.fit` agrees on a stop step via a tiny
  allgather, writes one coordinated grace checkpoint, and exits 0
  (:class:`GraceCheckpointed` is the control-flow signal).
* **Straggler telemetry** — per-peer ``cluster_peer_beat_age_seconds`` /
  ``cluster_peer_step_lag`` gauges plus ``cluster_desync_total{kind}``
  and ``cluster_grace_checkpoints_total`` counters, all chaos-testable
  through the ``heartbeat.send`` / ``step.stall`` fault points.

All ages are measured on the **chief's** monotonic clock (the chief
stamps each beat on receipt and returns its own ``now`` with the
table), so cross-host clock skew never enters the staleness math.
Clocks, transports, and the failure action are injectable for
fake-clock unit tests.

Env knobs (the ``DL4JTPU_HEARTBEAT_*`` family, docs/robustness.md):

    DL4JTPU_HEARTBEAT=1                enable the plane in MultiHostRunner
    DL4JTPU_HEARTBEAT_INTERVAL_S       beat cadence           (default 1)
    DL4JTPU_HEARTBEAT_TIMEOUT_S        beat-staleness deadline (default 30)
    DL4JTPU_HEARTBEAT_STALL_S          step-stall deadline     (default 60)
    DL4JTPU_HEARTBEAT_BARRIER_TIMEOUT_S  collective deadline   (default 300)
    DL4JTPU_HEARTBEAT_PORT             chief beat port (default:
                                       coordinator port + 1)
    DL4JTPU_HEARTBEAT_GRACE_EVERY      grace-poll cadence in steps (default 1)
"""
from __future__ import annotations

import logging
import os
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..optimize import metrics as metrics_mod
from ..utils import faults
from ..utils.http_server import JsonHttpServer, json_request

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Typed failures
# ---------------------------------------------------------------------------

class ClusterHealthError(RuntimeError):
    """Base of every typed cluster-health failure. Carries the offending
    peer ids so the restarter/operator knows WHICH process to look at."""

    def __init__(self, message: str, peers: Optional[List[int]] = None):
        super().__init__(message)
        self.peers = list(peers or [])


class PeerLostError(ClusterHealthError):
    """A peer's heartbeats went stale past the timeout (killed,
    preempted without grace, or network-partitioned)."""


class ClusterDesyncError(ClusterHealthError):
    """A peer is alive (fresh beats) but stopped advancing its step
    while others advance — a wedged main thread or a stalled host."""


class BarrierTimeoutError(ClusterHealthError):
    """A known blocking point (barrier / lockstep allgather / grace
    checkpoint) did not complete within its deadline."""


class GraceCheckpointed(Exception):
    """Control-flow signal: the cluster agreed to stop, the grace
    checkpoint was written, and the process should exit 0."""

    def __init__(self, step: int):
        super().__init__(f"grace checkpoint written at step {step}")
        self.step = int(step)


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class HealthConfig:
    """Tuning knobs for the health plane (see module docstring for the
    matching ``DL4JTPU_HEARTBEAT_*`` env family)."""

    interval_s: float = 1.0          # beat cadence
    timeout_s: float = 30.0          # beat staleness => PeerLostError
    stall_timeout_s: float = 60.0    # step stagnation => ClusterDesyncError
    barrier_timeout_s: float = 300.0  # blocking collective deadline
    grace_every: int = 1             # grace-flag allgather cadence (steps)
    port: Optional[int] = None       # chief beat port (None: coord port + 1)

    @classmethod
    def from_env(cls) -> "HealthConfig":
        port = os.environ.get("DL4JTPU_HEARTBEAT_PORT")
        return cls(
            interval_s=_env_float("DL4JTPU_HEARTBEAT_INTERVAL_S", 1.0),
            timeout_s=_env_float("DL4JTPU_HEARTBEAT_TIMEOUT_S", 30.0),
            stall_timeout_s=_env_float("DL4JTPU_HEARTBEAT_STALL_S", 60.0),
            barrier_timeout_s=_env_float(
                "DL4JTPU_HEARTBEAT_BARRIER_TIMEOUT_S", 300.0),
            grace_every=max(1, int(_env_float(
                "DL4JTPU_HEARTBEAT_GRACE_EVERY", 1))),
            port=int(port) if port else None,
        )


def health_enabled_from_env() -> bool:
    """True when ``DL4JTPU_HEARTBEAT`` opts the process into the plane."""
    return os.environ.get("DL4JTPU_HEARTBEAT", "").strip() not in (
        "", "0", "false", "no")


# Beat kinds: the same beat table (and the same staleness rule) now
# carries two populations — training peers watched by
# ClusterHealthMonitor, and serving replicas watched by the federation
# front-end (serving/federation.py). The ``kind`` field keeps them
# distinguishable when both ride one table.
KIND_TRAINER = "trainer"
KIND_REPLICA = "replica"


def beat_ages(table: dict) -> Dict[str, float]:
    """Age of every beat in a chief-stamped table, in seconds on the
    CHIEF's monotonic clock (``recv_ts`` stamped at receipt vs the
    table's ``now``) — the one staleness rule shared by the training
    watchdog (:meth:`ClusterHealthMonitor._evaluate`) and the serving
    federation's eviction sweep, so "dark past timeout_s" means the
    same thing on both planes. Beats missing ``recv_ts`` read as age
    0 (just arrived)."""
    beats = table.get("beats", {})
    chief_now = float(table.get("now", 0.0))
    return {str(k): max(0.0, chief_now - float(b.get("recv_ts", chief_now)))
            for k, b in beats.items()}


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

_HELP = {
    "cluster_peer_beat_age_seconds":
        "Age of each peer's newest heartbeat on the chief clock",
    "cluster_peer_step_lag":
        "Optimizer steps each peer trails the local process by",
    "cluster_heartbeats_sent_total": "Heartbeats published by this process",
    "cluster_heartbeat_failures_total":
        "Heartbeat sends/fetches that failed (transport or injected)",
    "cluster_desync_total":
        "Typed cluster-health failures raised, by kind "
        "(peer_lost | desync | barrier_timeout)",
    "cluster_grace_checkpoints_total":
        "Coordinated preemption-grace checkpoints written",
}


def register_metrics(reg=None):
    """Pre-register every cluster-health family so MULTICHIP/BENCH
    snapshots carry them even before the first beat."""
    reg = reg or metrics_mod.registry()
    for name, help_ in _HELP.items():
        if name.endswith("_total"):
            reg.counter(name, help_)
        else:
            reg.gauge(name, help_)
    return reg


def _counter(name: str):
    return metrics_mod.registry().counter(name, _HELP[name])


def _gauge(name: str):
    return metrics_mod.registry().gauge(name, _HELP[name])


# ---------------------------------------------------------------------------
# Beat transports
# ---------------------------------------------------------------------------

class InProcessBeatTransport:
    """Shared in-memory beat table — the sockets-free transport unit
    tests share between several monitors. Also the chief's local store
    inside :class:`HttpBeatTransport` (the chief never loops through
    its own HTTP socket)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._beats: Dict[int, dict] = {}

    def publish(self, beat: dict) -> None:
        rec = dict(beat)
        rec["recv_ts"] = self._clock()
        with self._lock:
            self._beats[int(beat["process_id"])] = rec

    def table(self) -> dict:
        with self._lock:
            beats = {str(k): dict(v) for k, v in self._beats.items()}
        return {"now": self._clock(), "beats": beats}

    def close(self) -> None:
        pass


class HttpBeatTransport:
    """Chief-hosted HTTP side channel over :class:`JsonHttpServer`.

    Process 0 serves ``POST /beat`` + ``GET /beats``; every process
    (chief included, via the local store) publishes its beat and fetches
    the chief-stamped table. Deliberately independent of the jax
    coordinator transport: when the cluster wedges inside a collective,
    this channel keeps working.
    """

    def __init__(self, process_id: int, host: str, port: int, *,
                 chief: bool = False, clock: Callable[[], float] =
                 time.monotonic, request_timeout_s: float = 2.0):
        self.process_id = int(process_id)
        self.chief = bool(chief)
        self._url = f"http://{host}:{int(port)}"
        self._timeout = float(request_timeout_s)
        self._store: Optional[InProcessBeatTransport] = None
        self._server: Optional[JsonHttpServer] = None
        if self.chief:
            store = InProcessBeatTransport(clock)
            self._store = store

            def _post_beat(payload):
                store.publish(payload)
                return 200, {"ok": True}

            self._server = JsonHttpServer(
                get_routes={"/beats": lambda _p: (200, store.table())},
                post_routes={"/beat": _post_beat},
                port=int(port), host=host, pool_size=4).start()

    @property
    def url(self) -> str:
        return self._url

    def publish(self, beat: dict) -> None:
        if self._store is not None:
            self._store.publish(beat)
            return
        json_request(self._url + "/beat", beat, timeout=self._timeout)

    def table(self) -> dict:
        if self._store is not None:
            return self._store.table()
        return json_request(self._url + "/beats", timeout=self._timeout)

    def close(self) -> None:
        if self._server is not None:
            self._server.stop()
            self._server = None


# ---------------------------------------------------------------------------
# The watchdog
# ---------------------------------------------------------------------------

def _default_on_failure(err: ClusterHealthError) -> None:
    """Tear the process down so the restarter can act. The main thread
    is (by hypothesis) wedged inside a collective, so a raised exception
    could never reach it — a hard exit is the only honest action.
    os._exit skips atexit/flush, so write the diagnosis directly."""
    sys.stderr.write(
        f"ClusterHealthMonitor: {type(err).__name__}: {err} "
        f"(peers={err.peers}) — hard-exiting with code "
        f"{ClusterHealthMonitor.EXIT_CODE} for the restarter\n")
    sys.stderr.flush()
    log.critical("cluster health failure: %s: %s", type(err).__name__, err)
    os._exit(ClusterHealthMonitor.EXIT_CODE)


class ClusterHealthMonitor:
    """Per-process heartbeat watchdog (see module docstring).

    State transitions, evaluated once per poll against the chief-stamped
    beat table::

        HEALTHY ──beat age > timeout_s──────────────▶ PEER_LOST
        HEALTHY ──peer step frozen > stall_timeout_s
                  while the local step advances─────▶ DESYNC
        (either) ──record failure, bump cluster_desync_total,
                   call on_failure (default: hard exit 17)

    ``notify_step`` feeds the step-progress side (wired as a
    ParallelWrapper step hook); ``request_grace`` flips the preemption
    bit that rides the beats. ``check()`` re-raises a recorded failure
    in the *caller's* thread — the fit loop calls it at step boundaries
    so the typed error surfaces in the main thread too whenever the
    main thread is still alive to see it.
    """

    EXIT_CODE = 17  # distinct from SIGKILL'd (-9) and clean (0) exits

    def __init__(self, process_id: int, num_processes: int, transport, *,
                 config: Optional[HealthConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_failure: Optional[Callable[[ClusterHealthError],
                                               None]] = None):
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        self.transport = transport
        self.config = config or HealthConfig.from_env()
        self._clock = clock
        self._on_failure = on_failure or _default_on_failure
        self._lock = threading.RLock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ---- shared state: every access under self._lock ----
        self._step = 0
        self._step_changed_at = clock()
        self._grace = False
        self._peer_grace = False
        self._failure: Optional[ClusterHealthError] = None
        self._started_at: Optional[float] = None
        # peer id -> (last seen step, local ts when that step first seen)
        self._peer_steps: Dict[int, Tuple[int, float]] = {}
        self._transport_fail_since: Optional[float] = None
        register_metrics()

    # --------------------------------------------------------------- control
    def start(self) -> "ClusterHealthMonitor":
        with self._lock:
            if self._thread is not None:
                return self
            self._started_at = self._clock()
        self._stop_evt.clear()
        t = threading.Thread(target=self._loop, daemon=True,
                             name=f"cluster-health-{self.process_id}")
        with self._lock:
            self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=5)
        self.transport.close()

    # ------------------------------------------------------------ main-thread
    def notify_step(self, step: int) -> None:
        """Report optimizer progress (wired as a wrapper step hook).
        The ``step.stall`` fault point swallows the report — the peer
        keeps beating but looks frozen, the deterministic stand-in for a
        wedged main thread."""
        if faults.check("step.stall"):
            return
        with self._lock:
            if int(step) > self._step:
                self._step = int(step)
                self._step_changed_at = self._clock()

    def request_grace(self) -> None:
        """Flag preemption (SIGTERM handler); the bit rides the beats."""
        with self._lock:
            self._grace = True

    def grace_requested(self) -> bool:
        """True once this process — or any peer, via the beat table —
        asked for a grace checkpoint."""
        with self._lock:
            return self._grace or self._peer_grace

    def failure(self) -> Optional[ClusterHealthError]:
        with self._lock:
            return self._failure

    def check(self) -> None:
        """Raise the recorded typed failure in the caller's thread."""
        with self._lock:
            failure = self._failure
        if failure is not None:
            raise failure

    # ------------------------------------------------------------------ poll
    def poll_once(self) -> Optional[ClusterHealthError]:
        """One beat + fetch + evaluate cycle (the loop body; callable
        directly with a fake clock in tests). Records and reports the
        first failure, then becomes a no-op."""
        with self._lock:
            if self._failure is not None:
                return self._failure
            beat = {"process_id": self.process_id, "step": self._step,
                    "grace": bool(self._grace), "kind": KIND_TRAINER,
                    "send_ts": self._clock()}
        ok = True
        try:
            # the fault point covers both grammars: fail: suppresses the
            # send, delay:SEL@MS injects channel latency then sends
            faults.fire("heartbeat.send")
            self.transport.publish(beat)
            _counter("cluster_heartbeats_sent_total").inc()
        except Exception as e:  # incl. FaultInjected: transport must never kill the watchdog
            ok = False
            _counter("cluster_heartbeat_failures_total").inc()
            log.debug("heartbeat publish failed: %s", e)
        table = None
        try:
            table = self.transport.table()
        except Exception as e:
            ok = False
            _counter("cluster_heartbeat_failures_total").inc()
            log.debug("heartbeat fetch failed: %s", e)
        now_local = self._clock()
        hosts_channel = bool(getattr(self.transport, "chief", True))
        err: Optional[ClusterHealthError] = None
        with self._lock:
            if ok and table is not None:
                self._transport_fail_since = None
            elif self._transport_fail_since is None:
                self._transport_fail_since = now_local
            if table is not None:
                err = self._evaluate(table, now_local)
            elif not hosts_channel and \
                    self._transport_fail_since is not None and \
                    now_local - self._transport_fail_since > \
                    self.config.timeout_s:
                # non-chief with an unreachable side channel: the chief
                # process (which hosts it) is gone
                err = PeerLostError(
                    f"process {self.process_id}: beat channel (chief) "
                    f"unreachable for over {self.config.timeout_s:.1f}s — "
                    "treating the chief as lost", peers=[0])
            if err is not None:
                self._failure = err
        if err is not None:
            kind = "peer_lost" if isinstance(err, PeerLostError) else "desync"
            _counter("cluster_desync_total").labels(kind=kind).inc()
            self._on_failure(err)
        return err

    # ------------------------------------------------------------- internals
    def _evaluate(self, table: dict,
                  now_local: float) -> Optional[ClusterHealthError]:
        """Watchdog state machine over one chief-stamped table. Caller
        holds self._lock."""
        cfg = self.config
        beats = table.get("beats", {})
        ages = beat_ages(table)
        self._peer_grace = any(
            b.get("grace") for k, b in beats.items()
            if int(k) != self.process_id)
        my_fresh = now_local - self._step_changed_at <= cfg.stall_timeout_s
        started_at = self._started_at      # one snapshot per evaluation
        lost: List[int] = []
        lost_ages: List[float] = []
        stalled: List[int] = []
        for pid in range(self.num_processes):
            if pid == self.process_id:
                continue
            b = beats.get(str(pid))
            if b is None:
                # startup grace: a peer that has NEVER beaten is only
                # lost once the cluster has had timeout_s to assemble
                if started_at is not None and \
                        now_local - started_at > cfg.timeout_s:
                    lost.append(pid)
                    lost_ages.append(float("inf"))
                continue
            age = ages.get(str(pid), 0.0)
            _gauge("cluster_peer_beat_age_seconds").labels(
                peer=str(pid)).set(age)
            pstep = int(b.get("step", 0))
            seen = self._peer_steps.get(pid)
            if seen is None or pstep > seen[0]:
                self._peer_steps[pid] = (pstep, now_local)
                seen = self._peer_steps[pid]
            lag = max(0, self._step - pstep)
            _gauge("cluster_peer_step_lag").labels(peer=str(pid)).set(lag)
            if age > cfg.timeout_s:
                lost.append(pid)
                lost_ages.append(age)
                continue
            if lag > 0 and my_fresh and \
                    now_local - seen[1] > cfg.stall_timeout_s:
                stalled.append(pid)
        if lost:
            ages = ", ".join("never" if a == float("inf") else f"{a:.1f}s"
                             for a in lost_ages)
            return PeerLostError(
                f"peer(s) {lost} missed heartbeats past "
                f"{cfg.timeout_s:.1f}s (beat ages: {ages}) while process "
                f"{self.process_id} is at step {self._step}", peers=lost)
        if stalled:
            return ClusterDesyncError(
                f"peer(s) {stalled} kept beating but made no step "
                f"progress for over {cfg.stall_timeout_s:.1f}s while "
                f"process {self.process_id} advanced to step "
                f"{self._step}", peers=stalled)
        return None

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                if self.poll_once() is not None:
                    return
            except Exception:
                log.exception("cluster health loop error (continuing)")
            self._stop_evt.wait(self.config.interval_s)


# ---------------------------------------------------------------------------
# Timed collectives
# ---------------------------------------------------------------------------

def timed_collective(fn: Callable[[], object], *, name: str,
                     timeout_s: Optional[float],
                     monitor: Optional[ClusterHealthMonitor] = None):
    """Run a blocking collective under a watchdog deadline.

    The collective runs on a daemon worker thread while the caller
    waits with a timeout; on expiry the caller gets a typed
    :class:`BarrierTimeoutError` (or the monitor's richer
    PeerLost/Desync diagnosis, when one is recorded) instead of hanging
    forever. The abandoned worker thread stays blocked — acceptable,
    because every caller of this path is about to tear the process
    down.
    """
    if not timeout_s or timeout_s <= 0:
        return fn()
    out: dict = {}
    done = threading.Event()

    def _run():
        try:
            out["value"] = fn()
        except BaseException as e:  # propagate into the waiting thread
            out["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, daemon=True,
                         name=f"collective-{name}")
    t.start()
    if not done.wait(timeout_s):
        _counter("cluster_desync_total").labels(kind="barrier_timeout").inc()
        if monitor is not None:
            monitor.check()  # prefer the watchdog's peer-level diagnosis
        raise BarrierTimeoutError(
            f"collective {name!r} did not complete within "
            f"{float(timeout_s):.1f}s — a peer is gone or wedged")
    if "error" in out:
        raise out["error"]
    return out.get("value")
