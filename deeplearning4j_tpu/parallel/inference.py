"""ParallelInference: multi-client serving with dynamic batching.

Reference parity: parallelism/ParallelInference.java:33-126 — N model
replicas behind a queue; `InferenceMode.SEQUENTIAL` round-robins whole
requests over replicas, `InferenceMode.BATCHED` coalesces queued requests
into one forward pass via BatchedInferenceObservable
(inference/observers/BatchedInferenceObservable.java), each caller blocking
until its slice of the result is ready.

TPU-native redesign: replicas-as-threads make no sense when one jitted
forward already saturates the chip — the win on TPU is BATCH SIZE (MXU
utilization scales with rows). So BATCHED mode is the headline path: a
collector thread drains the request queue, pads the coalesced batch to a
power-of-two bucket (static shapes → a handful of XLA compilations, ever),
runs ONE jitted forward, and scatters row slices back to the waiting
callers. SEQUENTIAL mode runs each request as its own forward under a lock
(the single-program analog of round-robin replicas — device order is
preserved, which is the observable semantic of the reference mode).
"""
from __future__ import annotations

import collections
import enum
import queue
import threading
import time
from typing import List, Optional

import numpy as np


class InferenceMode(enum.Enum):
    """Reference parallelism/inference/InferenceMode.java."""
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class _Request:
    __slots__ = ("x", "event", "result", "error")

    def __init__(self, x: np.ndarray):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


def _next_bucket(n: int) -> int:
    """Smallest power of two >= n (static-shape buckets keep XLA from
    recompiling per request mix — the TPU analog of the reference's
    variable dynamic batch)."""
    b = 1
    while b < n:
        b <<= 1
    return b


class ParallelInference:
    """Thread-safe serving facade over a trained MultiLayerNetwork /
    ComputationGraph (reference ParallelInference.Builder surface)."""

    def __init__(self, model, *, inference_mode: InferenceMode = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 batch_timeout_ms: float = 2.0):
        if not getattr(model, "_initialized", False):
            raise RuntimeError("Model must be init()ed (or restored) before "
                               "serving")
        self.model = model
        self.inference_mode = inference_mode
        self.batch_limit = int(batch_limit)
        self.batch_timeout_ms = float(batch_timeout_ms)
        self._lock = threading.Lock()
        self._enqueue_lock = threading.Lock()
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        # Observability: recent executed batch sizes (bounded — a serving
        # object lives for days) + a lifetime forward counter.
        self.executed_batch_sizes = collections.deque(maxlen=1024)
        self.total_forwards = 0
        if inference_mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(
                target=self._collector_loop, name="ParallelInference-collector",
                daemon=True)
            self._worker.start()

    # ---------------------------------------------------------------- builder
    @staticmethod
    def builder(model) -> "ParallelInferenceBuilder":
        return ParallelInferenceBuilder(model)

    # ----------------------------------------------------------------- warmup
    def warmup(self, *, max_bucket: Optional[int] = None,
               time_steps: Optional[int] = None) -> "ParallelInference":
        """Serving cold-start eliminator: AOT-precompile the model's
        inference path for every power-of-two bucket this server can
        coalesce to (1, 2, 4, ... batch_limit's bucket), so the FIRST
        client request at any bucket pays neither trace nor XLA compile.
        The model stays inference-only — its training jits remain
        unbuilt (the lazy-jit contract in nn/multilayer.py).

        `max_bucket` caps the sweep (default: the batch_limit bucket);
        `time_steps` sizes recurrent inputs (MultiLayerNetwork/
        ComputationGraph.precompile contract)."""
        top = _next_bucket(max_bucket or self.batch_limit)
        b = 1
        while b <= top:
            self.model.warmup(b, time_steps=time_steps)
            b <<= 1
        return self

    # ----------------------------------------------------------------- output
    def output(self, x) -> np.ndarray:
        """Predict for one request (any leading batch size). Thread-safe;
        in BATCHED mode blocks until the coalesced forward containing this
        request completes (reference output() → observable wait)."""
        x = np.asarray(x)
        if x.ndim == 0:
            raise ValueError("Request must have a leading batch dimension")
        if self.inference_mode == InferenceMode.SEQUENTIAL:
            if self._shutdown:
                raise RuntimeError("ParallelInference has been shut down")
            with self._lock:
                return self._forward(x)
        req = _Request(x)
        # Enqueue under the same lock shutdown() uses to place its sentinel,
        # so no request can ever land BEHIND the sentinel and starve.
        with self._enqueue_lock:
            if self._shutdown:
                raise RuntimeError("ParallelInference has been shut down")
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                raise RuntimeError(
                    f"ParallelInference queue limit ({self._queue.maxsize}) "
                    "exceeded — server overloaded") from None
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def _forward(self, x: np.ndarray) -> np.ndarray:
        return self.model.output(x)

    # -------------------------------------------------------------- collector
    def _collector_loop(self):
        try:
            self._collect()
        except BaseException as e:
            # Collector must never die silently: mark the server down
            # (under the enqueue lock so no request can slip in after the
            # drain) and fail every queued caller so nobody waits forever.
            with self._enqueue_lock:
                self._shutdown = True
                while True:
                    try:
                        r = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if r is not None:
                        r.error = e
                        r.event.set()
            raise

    def _collect(self):
        while True:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                if self._shutdown:
                    return
                continue
            if first is None:  # shutdown sentinel: serve stragglers, exit
                self._drain_and_exit()
                return
            batch = [first]
            rows = first.x.shape[0]
            # Linger briefly for co-arriving requests (the reference's
            # observable window) — unless this request alone already fills
            # the batch — then drain whatever is queued.
            if rows < self.batch_limit:
                time.sleep(self.batch_timeout_ms / 1000.0)
            saw_sentinel = False
            while rows < self.batch_limit:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    saw_sentinel = True
                    break
                batch.append(nxt)
                rows += nxt.x.shape[0]
            self._run_batch(batch)
            if saw_sentinel:
                self._drain_and_exit()
                return

    def _drain_and_exit(self):
        """Serve every request still queued at shutdown (none can arrive
        after the sentinel — enqueue holds the same lock)."""
        leftovers = []
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if r is not None:
                leftovers.append(r)
        if leftovers:
            self._run_batch(leftovers)

    def _run_batch(self, batch: List[_Request]):
        try:
            xs = np.concatenate([r.x for r in batch], axis=0)
            n = xs.shape[0]
            bucket = _next_bucket(n)
            if bucket > n:
                pad = np.repeat(xs[-1:], bucket - n, axis=0)
                xs = np.concatenate([xs, pad], axis=0)
            with self._lock:
                out = self._forward(xs)
            self.executed_batch_sizes.append(n)
            self.total_forwards += 1
            ofs = 0
            for r in batch:
                k = r.x.shape[0]
                r.result = out[ofs:ofs + k]
                ofs += k
                r.event.set()
        except BaseException as e:
            if len(batch) == 1:
                batch[0].error = e
                batch[0].event.set()
                return
            # One bad request must not poison its batchmates: retry each
            # request alone so only the offender sees the error (the
            # reference's observables fail independently).
            for r in batch:
                self._run_batch([r])

    # --------------------------------------------------------------- shutdown
    def shutdown(self):
        with self._enqueue_lock:
            if self._shutdown:
                return
            self._shutdown = True
            if self._worker is not None:
                # May briefly block if the queue is full; the collector
                # keeps draining without this lock, so it always frees up.
                self._queue.put(None)
        if self._worker is not None:
            self._worker.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


class ParallelInferenceBuilder:
    """Fluent builder mirroring reference ParallelInference.Builder."""

    def __init__(self, model):
        self._model = model
        self._mode = InferenceMode.BATCHED
        self._batch_limit = 32
        self._queue_limit = 64
        self._timeout_ms = 2.0

    def inference_mode(self, mode: InferenceMode):
        self._mode = mode
        return self

    def batch_limit(self, n: int):
        self._batch_limit = int(n)
        return self

    def queue_limit(self, n: int):
        self._queue_limit = int(n)
        return self

    def batch_timeout_ms(self, ms: float):
        self._timeout_ms = float(ms)
        return self

    def build(self) -> ParallelInference:
        return ParallelInference(
            self._model, inference_mode=self._mode,
            batch_limit=self._batch_limit, queue_limit=self._queue_limit,
            batch_timeout_ms=self._timeout_ms)
