"""ParallelInference: multi-client serving with dynamic batching.

Reference parity: parallelism/ParallelInference.java:33-126 — N model
replicas behind a queue; `InferenceMode.SEQUENTIAL` round-robins whole
requests over replicas, `InferenceMode.BATCHED` coalesces queued requests
into one forward pass via BatchedInferenceObservable
(inference/observers/BatchedInferenceObservable.java), each caller blocking
until its slice of the result is ready.

TPU-native redesign: replicas-as-threads make no sense when one jitted
forward already saturates the chip — the win on TPU is BATCH SIZE (MXU
utilization scales with rows). So BATCHED mode is the headline path: a
collector thread drains the request queue, pads the coalesced batch to a
power-of-two bucket (static shapes → a handful of XLA compilations, ever),
runs ONE jitted forward, and scatters row slices back to the waiting
callers. SEQUENTIAL mode runs each request as its own forward under a lock
(the single-program analog of round-robin replicas — device order is
preserved, which is the observable semantic of the reference mode).
"""
from __future__ import annotations

import collections
import contextlib
import enum
import queue
import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ..data.padding import next_pow2_bucket, repeat_tail_rows
from ..utils import faults


class InferenceMode(enum.Enum):
    """Reference parallelism/inference/InferenceMode.java."""
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class ServerClosedError(RuntimeError):
    """The server was shut down while (or before) this request was
    queued — the caller gets this instead of hanging forever."""


class BatchExecutionError(RuntimeError):
    """A coalesced forward raised: only the requests riding THAT batch
    fail (with this typed wrapper; `__cause__` carries the original
    exception) — batchmates of a poisoned request are retried alone,
    later batches are unaffected, and the collector thread survives.
    The circuit breaker (serving/breaker.py) counts these."""


class NonFiniteOutputError(BatchExecutionError):
    """A forward returned NaN/Inf rows with `check_finite` on — the
    poisoned-model signal that trips a circuit breaker immediately
    instead of waiting out N consecutive failures."""


class QueueFullError(RuntimeError):
    """Admission queue at capacity: the backpressure signal (the
    serving gateway maps this to a shed, not a 500)."""


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before a forward could serve it —
    shed early rather than queued to death (Clipper-style SLO
    awareness)."""


class DecodeStepError(BatchExecutionError):
    """One iteration-level decode step failed for the requests riding
    it: the victims get this typed wrapper (KV blocks freed), decode
    batchmates keep generating on the next step. Subclass of
    BatchExecutionError so breaker/gateway accounting is inherited."""


class KVCacheExhaustedError(QueueFullError):
    """The paged KV cache has no free blocks for this admission or
    growth step — the decode plane's backpressure signal. Subclass of
    QueueFullError so the gateway maps it to a shed (429), not a 500."""


class _Request:
    __slots__ = ("x", "event", "result", "error", "deadline", "transform",
                 "tag", "trace")

    def __init__(self, x: np.ndarray, deadline: Optional[float] = None,
                 transform: Optional[Callable] = None,
                 tag: Optional[str] = None, trace=None):
        self.x = x
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # Absolute time.monotonic() seconds; None = no SLO.
        self.deadline = deadline
        # Per-request output view: applied to this request's row slice
        # after the scatter (a FusedModelGroup member's column slice). A
        # raising transform fails ONLY this request, never batchmates.
        self.transform = transform
        # Routing identity for failure attribution (BatchExecutionError
        # .request_tags) — the member name inside a fused group.
        self.tag = tag
        # Flight-recorder RequestTrace (serving/flight_recorder.py) or
        # None (the default — every touch point below is one `is None`
        # branch, keeping the untraced path identical).
        self.trace = trace

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline


# Back-compat alias: the pow2 rounding now lives in data/padding.py so
# the pad-to-bucket iterator, this engine, and the serving gateway share
# ONE bucket rule.
_next_bucket = next_pow2_bucket


class ParallelInference:
    """Thread-safe serving facade over a trained MultiLayerNetwork /
    ComputationGraph (reference ParallelInference.Builder surface)."""

    def __init__(self, model, *, inference_mode: InferenceMode = InferenceMode.BATCHED,
                 batch_limit: int = 32, queue_limit: int = 64,
                 batch_timeout_ms: float = 2.0, check_finite: bool = False,
                 packed_admission: bool = False, pack_bucket: int = 0):
        if not getattr(model, "_initialized", False):
            raise RuntimeError("Model must be init()ed (or restored) before "
                               "serving")
        self.model = model
        self.inference_mode = inference_mode
        self.batch_limit = int(batch_limit)
        self.batch_timeout_ms = float(batch_timeout_ms)
        # Packed admission (docs/serving.md §packed): coalesce short
        # single-row sequence requests into ONE [1, pack_bucket] row
        # separated by segment ids, instead of one batch row each — the
        # serving counterpart of PackToBucketIterator. Requires a model
        # whose attention layers run packed_segments=True (outputs are
        # then bitwise-identical to solo forwards). Ineligible requests
        # (multi-row, non-sequence, too long) fall back to the ordinary
        # row-coalescing path and are counted.
        self.packed_admission = bool(packed_admission)
        self.pack_bucket = int(pack_bucket)
        if self.packed_admission:
            if inference_mode != InferenceMode.BATCHED:
                raise ValueError(
                    "packed_admission requires InferenceMode.BATCHED")
            if self.pack_bucket < 1:
                raise ValueError(
                    "packed_admission needs pack_bucket >= 1 (the token "
                    "capacity of the packed row)")
        self.total_packed_requests = 0
        self.total_pack_fallbacks = 0
        # check_finite: scan each forward's output for NaN/Inf and fail
        # the batch with NonFiniteOutputError (the breaker's instant
        # trip). Off by default — the host-side isfinite scan is cheap
        # but not free; ModelPool turns it on for served entries.
        self.check_finite = bool(check_finite)
        self._lock = threading.Lock()
        self._enqueue_lock = threading.Lock()
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        # Observability: recent executed batch sizes (bounded — a serving
        # object lives for days) + a lifetime forward counter.
        self.executed_batch_sizes = collections.deque(maxlen=1024)
        self.total_forwards = 0
        self.total_shed = 0
        self.total_batch_failures = 0
        # Stats counters are bumped from caller threads (shed paths,
        # SEQUENTIAL forwards) and the collector concurrently; a bare
        # += would lose updates, so they share one cheap guard.
        self._stats_lock = threading.Lock()
        # EWMA of one coalesced forward's wall time (written under
        # self._lock right after the forward it measures; the admission
        # estimate reads it lock-free — a stale float is fine there).
        self._ewma_batch_s = 0.0
        # Buckets warmup() precompiled — the hot-swap warm set.
        self.warmed_buckets: List[int] = []
        # Gateway hooks: on_shed(request, reason) on every deadline drop;
        # on_batch(requests, rows, bucket, dur_s) after every forward;
        # on_batch_error(exc, n_requests) after every FAILED forward
        # (the breaker/metrics seam — called once per failed forward
        # attempt, including the solo retries of a poisoned batch).
        self.on_shed: Optional[Callable] = None
        self.on_batch: Optional[Callable] = None
        self.on_batch_error: Optional[Callable] = None
        # Cross-model device arbitration (serving/scheduler.py): when a
        # DeviceScheduler is attached, every coalesced forward holds a
        # scheduler slot for the duration of the dispatch. None (the
        # default) keeps the exact pre-scheduler single-model path.
        self.scheduler = None
        self.sched_name: Optional[str] = None
        if inference_mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(
                target=self._collector_loop, name="ParallelInference-collector",
                daemon=True)
            self._worker.start()

    def _pack_eligible(self, x: np.ndarray) -> bool:
        """A request can ride a packed row iff it is a single sequence:
        one batch row of [1, t, features] with 1 <= t <= pack_bucket."""
        return (x.ndim == 3 and x.shape[0] == 1
                and 0 < x.shape[1] <= self.pack_bucket)

    # ---------------------------------------------------------------- builder
    @staticmethod
    def builder(model) -> "ParallelInferenceBuilder":
        return ParallelInferenceBuilder(model)

    # ----------------------------------------------------------------- warmup
    def warmup(self, *, max_bucket: Optional[int] = None,
               time_steps: Optional[int] = None) -> "ParallelInference":
        """Serving cold-start eliminator: AOT-precompile the model's
        inference path for every power-of-two bucket this server can
        coalesce to (1, 2, 4, ... batch_limit's bucket), so the FIRST
        client request at any bucket pays neither trace nor XLA compile.
        The model stays inference-only — its training jits remain
        unbuilt (the lazy-jit contract in nn/multilayer.py).

        `max_bucket` caps the sweep (default: the batch_limit bucket);
        `time_steps` sizes recurrent inputs (MultiLayerNetwork/
        ComputationGraph.precompile contract)."""
        top = next_pow2_bucket(max_bucket or self.batch_limit)
        b = 1
        while b <= top:
            self.model.warmup(b, time_steps=time_steps)
            if b not in self.warmed_buckets:
                self.warmed_buckets.append(b)
            b <<= 1
        if self.packed_admission:
            # The packed forward passes a features_mask (the segment
            # ids), which is a DIFFERENT jit pytree signature than the
            # maskless sweep above — warm it too, or the first packed
            # batch pays the compile warmup exists to prevent.
            x_s = self.model._feature_struct(1, self.pack_bucket)
            self.model.output(np.zeros(x_s.shape, x_s.dtype),
                              features_mask=np.zeros(
                                  (1, self.pack_bucket), np.float32))
        return self

    # ------------------------------------------------------------ admission
    def queue_depth(self) -> int:
        """Requests currently queued (approximate — qsize races with the
        collector by design; it is a gauge, not an invariant)."""
        return self._queue.qsize()

    def estimate_wait_s(self) -> float:
        """Expected time until a request admitted NOW completes: queued
        batches ahead of it plus its own forward, at the EWMA batch
        time. 0.0 until the first forward seeds the EWMA (admit
        optimistically while cold)."""
        svc = self._ewma_batch_s
        if svc <= 0.0:
            return 0.0
        batches_ahead = self.queue_depth() // max(1, self.batch_limit)
        return (batches_ahead + 1) * svc

    def _sched_slot(self, cost: float = 1.0):
        """The device-budget gate for one coalesced forward: a WFQ slot
        when a DeviceScheduler is attached, a no-op otherwise (so the
        default single-model path is untouched). Entered INSIDE
        self._lock — a paused() hot-swap therefore never parks holding
        the shared dispatch slot, and the scheduler takes no engine
        locks, so the ordering cannot deadlock."""
        if self.scheduler is None:
            return contextlib.nullcontext()
        return self.scheduler.slot(self.sched_name or "?", cost=cost)

    @contextlib.contextmanager
    def paused(self):
        """Hold the execution lock: the in-flight forward (if any)
        completes, then dispatch stalls — queued requests WAIT, they are
        not dropped or failed. The hot-swap window: ModelPool assigns
        new params inside this context and traffic resumes against them
        on exit."""
        with self._lock:
            yield self

    # ----------------------------------------------------------------- output
    def output(self, x, *, deadline: Optional[float] = None,
               transform: Optional[Callable] = None,
               tag: Optional[str] = None, trace=None) -> np.ndarray:
        """Predict for one request (any leading batch size). Thread-safe;
        in BATCHED mode blocks until the coalesced forward containing this
        request completes (reference output() → observable wait).

        `deadline` is an absolute time.monotonic() second count: a
        request still unserved past it is failed with
        :class:`DeadlineExceededError` instead of riding a forward it
        can no longer use (the gateway's SLO shed contract). A full
        admission queue raises :class:`QueueFullError` (backpressure),
        a closed server :class:`ServerClosedError`.

        `transform` post-processes this request's own row slice before
        the caller sees it (a fused group's member-column view); a
        raising transform fails only this request. `tag` names the
        request for failure attribution (``err.request_tags``).

        `trace` is an optional flight-recorder RequestTrace: the engine
        marks phase cut-points on it as the request crosses queue /
        pack / scheduler / forward / unpack (docs/observability.md
        §"Request flight recorder"); None (the default) records
        nothing."""
        x = np.asarray(x)
        if x.ndim == 0:
            raise ValueError("Request must have a leading batch dimension")
        if self.inference_mode == InferenceMode.SEQUENTIAL:
            with self._enqueue_lock:
                closed = self._shutdown
            if closed:
                raise ServerClosedError(
                    "ParallelInference has been shut down")
            with self._lock:
                req = _Request(x, deadline, transform, tag, trace)
                if req.expired():
                    self._shed(req, "expired")
                    raise DeadlineExceededError(
                        "deadline passed before dispatch")
                try:
                    with self._sched_slot(float(x.shape[0])):
                        if trace is not None:
                            # lock + slot wait ends here; no coalescing
                            # in SEQUENTIAL mode so no queue/pack phases
                            trace.mark("sched_wait")
                            trace.mark("dispatch")
                        # swap-pause design: _lock held through the
                        # forward so hot-swap can quiesce the device
                        out = self._forward(x)  # jaxlint: disable=JL403
                        if trace is not None:
                            out = np.asarray(out)  # recorder result fence
                            trace.mark("device")
                    self._require_finite(out)
                    if transform is not None:
                        out = transform(out)
                    if trace is not None:
                        trace.mark("unpack")
                except (DeadlineExceededError, QueueFullError,
                        ServerClosedError):
                    raise
                except BaseException as e:
                    raise self._batch_failure(e, 1, reqs=[req])
                return out
        req = _Request(x, deadline, transform, tag, trace)
        # Enqueue under the same lock shutdown() uses to place its sentinel,
        # so no request can ever land BEHIND the sentinel and starve.
        with self._enqueue_lock:
            if self._shutdown:
                raise ServerClosedError(
                    "ParallelInference has been shut down")
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                raise QueueFullError(
                    f"ParallelInference queue limit ({self._queue.maxsize}) "
                    "exceeded — server overloaded") from None
        req.event.wait()
        if req.error is not None:
            raise req.error
        return req.result

    def _shed(self, req: _Request, reason: str) -> None:
        with self._stats_lock:
            self.total_shed += 1
        cb = self.on_shed
        if cb is not None:
            try:
                cb(req, reason)
            except Exception:
                pass  # a broken hook must never take the server down

    def _finish(self, r: _Request, rows) -> None:
        """Deliver one request's row slice, through its transform when it
        carries one. A raising transform (e.g. a fused member's column
        turned non-finite) fails ONLY this request — batchmates already
        have (or will get) their own slices. The unpack mark lands
        BEFORE event.set(): once the caller wakes it owns the trace, so
        the engine must not touch it afterwards."""
        if r.transform is not None:
            try:
                rows = r.transform(rows)
            except BaseException as te:
                if r.trace is not None:
                    r.trace.mark("unpack")
                r.error = self._batch_failure(te, 1, reqs=[r])
                r.event.set()
                return
        r.result = rows
        if r.trace is not None:
            r.trace.mark("unpack")
        r.event.set()

    def _forward(self, x: np.ndarray) -> np.ndarray:
        # Chaos seam (docs/robustness.md): armed "serve.forward" plans
        # fail or delay this forward deterministically by call ordinal.
        faults.fire("serve.forward")
        return self.model.output(x)

    def _require_finite(self, out) -> None:
        if self.check_finite and not np.isfinite(np.asarray(out)).all():
            raise NonFiniteOutputError(
                "forward returned non-finite (NaN/Inf) outputs")

    def _batch_failure(self, e: BaseException, n_requests: int,
                       reqs: Optional[List[_Request]] = None
                       ) -> BatchExecutionError:
        """Record one failed forward attempt and return the typed error
        the affected callers will see (original exception chained).
        When the failed requests are known, their tags ride along as
        ``err.request_tags`` so a shared-engine hook (FusedModelGroup)
        can attribute the failure to the right member breakers without
        changing the on_batch_error signature."""
        if isinstance(e, BatchExecutionError):
            err = e
        else:
            err = BatchExecutionError(
                f"forward failed for a {n_requests}-request batch: {e}")
            err.__cause__ = e
        if reqs is not None and not hasattr(err, "request_tags"):
            err.request_tags = [r.tag for r in reqs]
        with self._stats_lock:
            self.total_batch_failures += 1
        cb = self.on_batch_error
        if cb is not None:
            try:
                cb(err, n_requests)
            except Exception:
                pass  # a broken hook must never take the server down
        return err

    # -------------------------------------------------------------- collector
    def _collector_loop(self):
        try:
            if self.packed_admission:
                self._collect_packed()
            else:
                self._collect()
        except BaseException as e:
            # Collector must never die silently: mark the server down
            # (under the enqueue lock so no request can slip in after the
            # drain) and fail every queued caller so nobody waits forever.
            with self._enqueue_lock:
                self._shutdown = True
                while True:
                    try:
                        r = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    if r is not None:
                        r.error = e
                        r.event.set()
            raise

    def _collect(self):
        # The bucket ceiling warmup() precompiled to. Coalescing must
        # never assemble a batch past it: rows that overshoot would
        # round to an UNWARMED pow2 bucket and trigger a steady-state
        # XLA compile (the exact thing warmup exists to prevent). A
        # request that would overflow is carried to the next batch
        # instead. (A single request larger than the ceiling still runs
        # alone and pays its honest compile — that is the client's
        # batch, not a coalescing artifact.)
        cap = next_pow2_bucket(self.batch_limit)
        carry: Optional[_Request] = None
        while True:
            if carry is not None:
                first, carry = carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    # Unlocked poll of a monotonic flag: worst case is
                    # one extra 0.1 s get() before the sentinel lands.
                    if self._shutdown:  # jaxlint: atomic
                        return
                    continue
            if first is None:  # shutdown sentinel: serve stragglers, exit
                self._drain_and_exit()
                return
            batch = [first]
            rows = first.x.shape[0]
            # Linger briefly for co-arriving requests (the reference's
            # observable window) — unless this request alone already fills
            # the batch — then drain whatever is queued.
            if rows < self.batch_limit:
                time.sleep(self.batch_timeout_ms / 1000.0)
            saw_sentinel = False
            while rows < self.batch_limit:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    saw_sentinel = True
                    break
                if rows + nxt.x.shape[0] > cap:
                    carry = nxt  # would overflow the warmed bucket set
                    break
                batch.append(nxt)
                rows += nxt.x.shape[0]
            self._run_batch(batch)
            if saw_sentinel:
                self._drain_and_exit(carry)
                return

    def _drain_and_exit(self, carry: Optional[_Request] = None):
        """Serve every request still queued at shutdown (none can arrive
        after the sentinel — enqueue holds the same lock), in cap-sized
        batches so even the shutdown flush stays on warmed buckets."""
        leftovers = [] if carry is None else [carry]
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if r is not None:
                leftovers.append(r)
        cap = next_pow2_bucket(self.batch_limit)
        batch: List[_Request] = []
        rows = 0
        for r in leftovers:
            if batch and rows + r.x.shape[0] > cap:
                self._run_batch(batch)
                batch, rows = [], 0
            batch.append(r)
            rows += r.x.shape[0]
        if batch:
            self._run_batch(batch)

    def _run_batch(self, batch: List[_Request]):
        # SLO late-shed: a request whose deadline passed while queued
        # cannot make its SLO — fail it NOW rather than spend forward
        # rows on an answer nobody is waiting for.
        now = time.monotonic()
        live = []
        for r in batch:
            if r.expired(now):
                self._shed(r, "expired")
                if r.trace is not None:
                    r.trace.mark("queue_wait")  # died waiting: show where
                r.error = DeadlineExceededError(
                    "deadline passed while queued")
                r.event.set()
            else:
                live.append(r)
        batch = live
        if not batch:
            return
        # Flight-recorder cut-points: one shared timestamp per phase
        # boundary fans out to every traced batchmate (they rode the
        # same forward, so they share the same timeline past this line).
        traced = [r for r in batch if r.trace is not None]
        if traced:
            tq = time.perf_counter()
            for r in traced:
                r.trace.mark("queue_wait", tq)
        try:
            xs = np.concatenate([r.x for r in batch], axis=0)
            n = xs.shape[0]
            bucket = next_pow2_bucket(n)
            # Pad to the bucket under the shared repeat-tail contract
            # (data/padding.py) — same rule as the fit pipeline, no loss
            # mask needed on the inference path (pad rows are sliced off
            # before any caller sees them).
            xs = repeat_tail_rows(xs, bucket - n)
            if traced:
                tp = time.perf_counter()
                for r in traced:
                    r.trace.mark("pack", tp)
                    r.trace.ctx["batch_rows"] = n
                    r.trace.ctx["bucket"] = bucket
            t0 = time.perf_counter()
            with self._lock:
                with self._sched_slot(float(n)):
                    if traced:
                        # slot granted: sched_wait (incl. any swap-pause
                        # lock stall) ends; dispatch is the host-side
                        # gap from grant to the forward call below
                        tg = time.perf_counter()
                        po = (self.scheduler.last_passovers(
                            self.sched_name)
                            if self.scheduler is not None else 0)
                        for r in traced:
                            r.trace.mark("sched_wait", tg)
                            r.trace.mark("dispatch")
                            if po:
                                r.trace.ctx["sched_passovers"] = po
                    # swap-pause design: _lock held through the forward
                    out = self._forward(xs)  # jaxlint: disable=JL403
                    if traced:
                        # recorder-only result fence INSIDE the slot so
                        # device compute is charged to the slot it used;
                        # the untraced path never syncs here
                        out = np.asarray(out)
                        td = time.perf_counter()
                        for r in traced:
                            r.trace.mark("device", td)
                dur = time.perf_counter() - t0
                # EWMA seeds on the first forward, then smooths at 0.2 —
                # reactive enough for the admission estimate, stable
                # enough not to flap on one slow batch.
                self._ewma_batch_s = dur if self._ewma_batch_s <= 0.0 \
                    else 0.8 * self._ewma_batch_s + 0.2 * dur
            self._require_finite(out)
            self.executed_batch_sizes.append(n)
            with self._stats_lock:
                self.total_forwards += 1
            cb = self.on_batch
            if cb is not None:
                try:
                    cb(batch, n, bucket, dur)
                except Exception:
                    pass  # a broken hook must never take the server down
            ofs = 0
            for r in batch:
                k = r.x.shape[0]
                self._finish(r, out[ofs:ofs + k])
                ofs += k
        except BaseException as e:
            # Batch-failure isolation: the failed forward is recorded
            # (on_batch_error feeds the breaker + metrics), the affected
            # futures fail with a TYPED error, and the collector thread
            # survives to run the next batch — a raising forward never
            # strands a caller and never kills the engine.
            err = self._batch_failure(e, len(batch), reqs=batch)
            # Close the failed attempt's window on every traced request
            # (forward/finite failures are device-phase by far the
            # common case) so the timeline stays contiguous across the
            # solo retries below, which append fresh phase segments.
            for r in traced:
                r.trace.mark("device")
                r.trace.ctx["failed_attempts"] = \
                    r.trace.ctx.get("failed_attempts", 0) + 1
            if len(batch) == 1:
                batch[0].error = err
                batch[0].event.set()
                return
            # One bad request must not poison its batchmates: retry each
            # request alone so only the offender sees the error (the
            # reference's observables fail independently).
            for r in batch:
                self._run_batch([r])

    # ---------------------------------------------------------------- packed
    def _collect_packed(self):
        """Packed-admission collector: capacity is TOKENS in one
        [1, pack_bucket] row, not batch rows. Eligible requests coalesce
        by first-come token fit (carry on overflow, same as the bucket
        overshoot carry of _collect); an ineligible request flushes the
        packed batch and runs through the ordinary row path alone —
        deadline, breaker, and shutdown semantics are shared with
        _collect."""
        cap = self.pack_bucket
        carry: Optional[_Request] = None
        while True:
            if carry is not None:
                first, carry = carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    # Unlocked poll of a monotonic flag: worst case is
                    # one extra 0.1 s get() before the sentinel lands.
                    if self._shutdown:  # jaxlint: atomic
                        return
                    continue
            if first is None:  # shutdown sentinel: serve stragglers, exit
                self._drain_and_exit_packed()
                return
            if not self._pack_eligible(first.x):
                self._note_pack_fallback(1)
                self._run_batch([first])
                continue
            batch = [first]
            toks = first.x.shape[1]
            if toks < cap:
                time.sleep(self.batch_timeout_ms / 1000.0)
            saw_sentinel = False
            while toks < cap:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    saw_sentinel = True
                    break
                if not self._pack_eligible(nxt.x) or \
                        toks + nxt.x.shape[1] > cap:
                    carry = nxt
                    break
                batch.append(nxt)
                toks += nxt.x.shape[1]
            self._run_packed(batch)
            if saw_sentinel:
                self._drain_and_exit_packed(carry)
                return

    def _drain_and_exit_packed(self, carry: Optional[_Request] = None):
        """Shutdown flush for packed mode: serve queued stragglers in
        token-capacity packed rows; ineligible ones run alone through
        the row path."""
        leftovers = [] if carry is None else [carry]
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if r is not None:
                leftovers.append(r)
        batch: List[_Request] = []
        toks = 0
        for r in leftovers:
            if not self._pack_eligible(r.x):
                self._note_pack_fallback(1)
                self._run_batch([r])
                continue
            if batch and toks + r.x.shape[1] > self.pack_bucket:
                self._run_packed(batch)
                batch, toks = [], 0
            batch.append(r)
            toks += r.x.shape[1]
        if batch:
            self._run_packed(batch)

    def _note_pack_fallback(self, n: int) -> None:
        with self._stats_lock:
            self.total_pack_fallbacks += n
        from ..data.padding import record_packing
        record_packing("serve", fallbacks=n)

    def _run_packed(self, batch: List[_Request]):
        now = time.monotonic()
        live = []
        for r in batch:  # SLO late-shed, same contract as _run_batch
            if r.expired(now):
                self._shed(r, "expired")
                if r.trace is not None:
                    r.trace.mark("queue_wait")
                r.error = DeadlineExceededError(
                    "deadline passed while queued")
                r.event.set()
            else:
                live.append(r)
        batch = live
        if not batch:
            return
        traced = [r for r in batch if r.trace is not None]
        if traced:
            tq = time.perf_counter()
            for r in traced:
                r.trace.mark("queue_wait", tq)
        try:
            # Chaos seam: an armed "serve.pack" plan fails the assembly
            # (and, below, the unpack) of a packed row deterministically.
            faults.fire("serve.pack")
            feat = batch[0].x.shape[2]
            xs = np.zeros((1, self.pack_bucket, feat), batch[0].x.dtype)
            segmask = np.zeros((1, self.pack_bucket), np.float32)
            ofs = 0
            for s, r in enumerate(batch, start=1):
                t_i = r.x.shape[1]
                xs[0, ofs:ofs + t_i] = r.x[0]
                segmask[0, ofs:ofs + t_i] = s
                ofs += t_i
            if traced:
                tp = time.perf_counter()
                for r in traced:
                    r.trace.mark("pack", tp)
                    r.trace.ctx["packed_with"] = len(batch)
                    r.trace.ctx["packed_tokens"] = ofs
                    r.trace.ctx["pack_bucket"] = self.pack_bucket
            t0 = time.perf_counter()
            with self._lock:
                with self._sched_slot(float(len(batch))):
                    if traced:
                        tg = time.perf_counter()
                        po = (self.scheduler.last_passovers(
                            self.sched_name)
                            if self.scheduler is not None else 0)
                        for r in traced:
                            r.trace.mark("sched_wait", tg)
                            r.trace.mark("dispatch")
                            if po:
                                r.trace.ctx["sched_passovers"] = po
                    faults.fire("serve.forward")
                    # swap-pause design: _lock held through the forward
                    out = self.model.output(  # jaxlint: disable=JL403
                        xs, features_mask=segmask)
                    if traced:
                        out = np.asarray(out)  # recorder result fence
                        td = time.perf_counter()
                        for r in traced:
                            r.trace.mark("device", td)
                dur = time.perf_counter() - t0
                self._ewma_batch_s = dur if self._ewma_batch_s <= 0.0 \
                    else 0.8 * self._ewma_batch_s + 0.2 * dur
            self._require_finite(out)
            self.executed_batch_sizes.append(len(batch))
            with self._stats_lock:
                self.total_forwards += 1
                self.total_packed_requests += len(batch)
            from ..data.padding import record_packing
            record_packing("serve", items=len(batch), real_tokens=ofs,
                           padded_tokens=self.pack_bucket)
            cb = self.on_batch
            if cb is not None:
                try:
                    cb(batch, len(batch), self.pack_bucket, dur)
                except Exception:
                    pass  # a broken hook must never take the server down
            faults.fire("serve.pack")
            out = np.asarray(out)
            ofs = 0
            for r in batch:
                t_i = r.x.shape[1]
                self._finish(r, out[:, ofs:ofs + t_i])
                ofs += t_i
        except BaseException as e:
            err = self._batch_failure(e, len(batch), reqs=batch)
            for r in traced:  # close the failed window (see _run_batch)
                r.trace.mark("device")
                r.trace.ctx["failed_attempts"] = \
                    r.trace.ctx.get("failed_attempts", 0) + 1
            if len(batch) == 1:
                batch[0].error = err
                batch[0].event.set()
                return
            # Packed-batch isolation mirrors _run_batch: retry each
            # request in its own packed row so only the offender fails.
            for r in batch:
                self._run_packed([r])

    # --------------------------------------------------------------- shutdown
    def _fail_pending(self, exc: BaseException) -> None:
        """Fail every request still queued so no caller is stranded
        blocking on its event (satellite fix: a dead or wedged collector
        used to leave them waiting forever)."""
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                return
            if r is not None:
                r.error = exc
                r.event.set()

    def shutdown(self, join_timeout: float = 5.0):
        """Close the server: stragglers already queued are SERVED by the
        collector's drain pass; anything it could not serve within the
        join window (collector dead, forward wedged) is failed with
        :class:`ServerClosedError` instead of hanging its caller."""
        already = False
        with self._enqueue_lock:
            if self._shutdown:
                already = True
            else:
                self._shutdown = True
        if not already and self._worker is not None:
            # Sentinel goes in OUTSIDE the lock: with a full queue this
            # put blocks until the collector drains a slot, and holding
            # _enqueue_lock through that window would wedge every
            # enqueuer (and any concurrent shutdown) behind a blocked
            # close. Admission is already fenced: _shutdown is set, so
            # new requests fail typed before touching the queue.
            self._queue.put(None)
        if self._worker is not None and not already:
            self._worker.join(timeout=join_timeout)
        # After the join window nothing will ever serve these — and on a
        # REPEAT shutdown() the sweep is how callers stranded by a first
        # failed close get released.
        self._fail_pending(ServerClosedError(
            "ParallelInference was shut down before this request ran"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


class ParallelInferenceBuilder:
    """Fluent builder mirroring reference ParallelInference.Builder."""

    def __init__(self, model):
        self._model = model
        self._mode = InferenceMode.BATCHED
        self._batch_limit = 32
        self._queue_limit = 64
        self._timeout_ms = 2.0
        self._check_finite = False
        self._packed_admission = False
        self._pack_bucket = 0

    def inference_mode(self, mode: InferenceMode):
        self._mode = mode
        return self

    def batch_limit(self, n: int):
        self._batch_limit = int(n)
        return self

    def queue_limit(self, n: int):
        self._queue_limit = int(n)
        return self

    def batch_timeout_ms(self, ms: float):
        self._timeout_ms = float(ms)
        return self

    def check_finite(self, enabled: bool = True):
        self._check_finite = bool(enabled)
        return self

    def packed_admission(self, bucket: int):
        """Coalesce short sequence requests into one [1, bucket] packed
        row (segment ids through the feature mask). The served model's
        attention layers must run packed_segments=True."""
        self._packed_admission = True
        self._pack_bucket = int(bucket)
        return self

    def build(self) -> ParallelInference:
        return ParallelInference(
            self._model, inference_mode=self._mode,
            batch_limit=self._batch_limit, queue_limit=self._queue_limit,
            batch_timeout_ms=self._timeout_ms,
            check_finite=self._check_finite,
            packed_admission=self._packed_admission,
            pack_bucket=self._pack_bucket)
