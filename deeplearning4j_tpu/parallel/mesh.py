"""Device-mesh helpers: the substrate for every parallelism strategy.

Reference parity: the reference's parallelism is device *enumeration* —
ParallelWrapper spawns one trainer thread per device
(parallelism/ParallelWrapper.java:460-468), Spark enumerates executors, the
Aeron parameter server enumerates endpoints. TPU-native, the analogous
object is a `jax.sharding.Mesh`: a named, possibly multi-host grid of
devices over which shardings are expressed and XLA inserts collectives
(psum over ICI/DCN) automatically.

Axis conventions used throughout this framework:
  * "data"  — data parallelism (batch axis). The reference's ONLY strategy.
  * "model" — tensor parallelism (feature/hidden axis). New scope.
  * "seq"   — sequence/context parallelism for long sequences. New scope.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
STAGE_AXIS = "stage"


def shard_map_compat(f, mesh, in_specs, out_specs):
    """jax.shard_map across the API move: top-level `jax.shard_map`
    (check_vma kwarg) on recent jax, `jax.experimental.shard_map`
    (check_rep kwarg) on 0.4.x. Replication checking is disabled either
    way — callers here return per-shard values stitched by out_specs."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=False)
    from jax.experimental.shard_map import shard_map as sm
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=False)


def create_mesh(shape: Optional[Sequence[int]] = None,
                axis_names: Sequence[str] = (DATA_AXIS,),
                devices=None) -> Mesh:
    """Build a Mesh over the given (or all) devices.

    `shape=None` puts every device on the first axis (pure DP — the
    reference ParallelWrapper default of "all devices in the box")."""
    devices = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = [len(devices)] + [1] * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"Mesh shape {tuple(shape)} needs {n} devices, "
                         f"have {len(devices)}")
    grid = np.array(devices[:n]).reshape(shape)
    return Mesh(grid, tuple(axis_names))


def data_parallel_mesh(num_devices: Optional[int] = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested a {num_devices}-device data-parallel mesh but only "
                f"{len(devices)} devices are visible: {devices}")
        devices = devices[:num_devices]
    return create_mesh([len(devices)], (DATA_AXIS,), devices)


def is_multiprocess(mesh: Mesh) -> bool:
    """True when the mesh spans devices owned by more than one process
    (multi-host: the Spark-cluster analog, ICI/DCN instead of shuffle)."""
    return jax.process_count() > 1 and \
        any(d.process_index != jax.process_index() for d in mesh.devices.flat)


def place(arr, sharding: NamedSharding, mesh: Mesh):
    """Place an array under a sharding, multiprocess-safe.

    Single-process: plain device_put. Multi-process: device_put cannot
    address remote devices, so the global array is assembled from each
    process's local portion (for batch-sharded data: this process's
    partition; for replicated: the full host copy) — the TPU-native
    analog of the Spark driver broadcasting NetBroadcastTuple
    (ParameterAveragingTrainingMaster.java:346-357)."""
    if arr is None:
        return None
    if is_multiprocess(mesh):
        return jax.make_array_from_process_local_data(sharding, np.asarray(arr))
    return jax.device_put(arr, sharding)


def place_global(arr, sharding: NamedSharding, mesh: Mesh):
    """Place a host value that is IDENTICAL on every process, sharded
    arbitrarily across the global mesh.

    This is the other multiprocess placement contract from `place`:
    `place` assembles a global array from per-process LOCAL PORTIONS
    (the DP data-feeding convention), while place_global takes the same
    full value everywhere and lets each process slice out its
    addressable shards (make_array_from_callback) — what tensor/
    sequence parallelism need for params after same-seed init or
    restore, and for whole batches fed identically to every process.
    Single-process: plain device_put."""
    if arr is None:
        return None
    if is_multiprocess(mesh):
        a = np.asarray(arr)
        return jax.make_array_from_callback(a.shape, sharding,
                                            lambda idx: a[idx])
    return jax.device_put(arr, sharding)


def gather_replicated(tree, mesh: Mesh):
    """All-gather a (possibly cross-process-sharded) pytree back to
    REPLICATED device arrays — jit identity with replicated output
    shardings, so XLA inserts the all-gathers. COLLECTIVE under a
    multiprocess mesh: every process must call in lockstep. After this,
    np.asarray on any leaf is legal (fully addressable), which is what
    checkpoint serialization needs (ModelSerializer writes host npz)."""
    if tree is None:
        return None
    rep = replicated(mesh)
    with mesh:
        return jax.jit(lambda t: t, out_shardings=rep)(tree)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard the leading (batch) dimension across `axis`."""
    return NamedSharding(mesh, PartitionSpec(axis))


def shard_batch(mesh: Mesh, tree, axis: str = DATA_AXIS):
    """Place a pytree of host arrays on the mesh, batch-dim sharded. In a
    multi-process mesh each process passes its LOCAL partition and the
    global batch is their concatenation in process order."""
    sh = batch_sharded(mesh, axis)
    return jax.tree_util.tree_map(
        lambda x: place(x, sh, mesh), tree, is_leaf=lambda x: x is None)


def replicate(mesh: Mesh, tree):
    """Replicate a pytree of arrays across the whole mesh (every process
    must hold the same values — true after same-seed init or checkpoint
    restore)."""
    sh = replicated(mesh)
    return jax.tree_util.tree_map(lambda x: place(x, sh, mesh), tree)


def pad_batch_to_multiple(arr: np.ndarray, multiple: int) -> Tuple[np.ndarray, int]:
    """Pad batch dim up to a multiple (XLA needs even shards); returns
    (padded, original_n). Padding repeats the last example so batch stats
    stay finite; callers rescale loss/metrics by original_n when needed."""
    n = arr.shape[0]
    rem = n % multiple
    if rem == 0:
        return arr, n
    pad = multiple - rem
    reps = np.repeat(arr[-1:], pad, axis=0)
    return np.concatenate([arr, reps], axis=0), n
