"""Multi-host training runner: the Spark-driver / TrainingMaster role.

Reference parity: dl4j-spark's SparkDl4jMultiLayer.fit(JavaRDD) →
ParameterAveragingTrainingMaster (ParameterAveragingTrainingMaster.java:
346-357 split sizing, :867-896 treeAggregate + param/updater averaging) —
a driver JVM broadcasts (conf, params, updaterState) to executor JVMs,
each executor trains on its RDD partition, results aggregate over the
Spark shuffle.

TPU-native redesign: there is no driver/executor asymmetry. Every host
runs the SAME SPMD program over a global jax.sharding.Mesh spanning all
processes' devices (jax.distributed); XLA collectives over ICI (intra-
slice) / DCN (inter-slice) replace the broadcast + treeAggregate
transport. "Broadcast" degenerates to same-seed init (or same checkpoint)
+ replicated placement; "aggregate" is the gradient allreduce (sync DP,
averaging_frequency=1) or the every-F-steps parameter average (local SGD)
that ParallelWrapper already implements — this runner only adds the
process bootstrap, per-process data partitioning contract, lockstep
guards, and chief-only checkpointing.

Launch contract (one process per host, like one Spark executor per node):

    runner = MultiHostRunner(coordinator_address="host0:1234",
                             num_processes=4, process_id=rank)
    runner.initialize()
    net = MultiLayerNetwork(conf).init(seed=SAME_EVERYWHERE)
    runner.fit(net, local_x, local_y, epochs=..., batch_size=...)
    runner.save_checkpoint(net, "gs://.../model.zip")   # chief writes

Env fallbacks: JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID. On TPU pods, pass auto_detect=True to let jax's cluster
detection fill everything in.
"""
from __future__ import annotations

import collections
import logging
import os
import signal
import threading
from typing import Optional

import jax
import numpy as np

from . import cluster_health as health_lib
from . import mesh as mesh_lib
from .cluster_health import HealthConfig
from .wrapper import ParallelWrapper

log = logging.getLogger(__name__)


class StepCheckpointManager:
    """Step-numbered checkpoint directory with atomic writes and a
    retention bound — the substrate of the auto-resume story (the
    reference has no elastic recovery at all, SURVEY.md §5.3; this is
    deliberate beyond-parity scope: checkpoint-restart is the realistic
    TPU preemption baseline).

    Distinct from :class:`deeplearning4j_tpu.optimize.resilience.\
CheckpointManager` (manifest + sha256 + cadence/retention policy, the
    single-process fit-loop integration): this one is the *multihost*
    flavor — bare ``checkpoint_step<N>.zip`` files, chief-written under
    cluster barriers (docs/robustness.md §cluster-health). The old
    ``CheckpointManager`` name is kept as a deprecated alias."""

    PATTERN = "checkpoint_step%d.zip"

    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = int(keep)
        if self.keep < 1:
            # keep=0 would make the retention slice [:-0] == [:0] a
            # silent no-op (keeps everything); reject instead of surprising
            raise ValueError("keep must be >= 1, got %d" % self.keep)
        os.makedirs(directory, exist_ok=True)

    def _entries(self):
        import re
        out = []
        for name in os.listdir(self.directory):
            m = re.match(r"^checkpoint_step(\d+)\.zip$", name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out)

    def latest(self):
        """(step, path) of the newest checkpoint, or None."""
        entries = self._entries()
        return entries[-1] if entries else None

    def latest_valid(self):
        """(step, path) of the newest checkpoint that passes structural
        validation. A torn newest file (e.g. a kill during a non-atomic
        copy INTO the directory — the writer itself is atomic) must not
        crash resume on every process: it is skipped with a warning and
        a ``checkpoint_corrupt_total`` bump, falling back to the
        next-newest — matching
        ``optimize.resilience.CheckpointManager.latest_valid()``."""
        from ..optimize import resilience
        from ..utils.model_serializer import (CheckpointCorruptError,
                                              validate_checkpoint)
        for step, path in reversed(self._entries()):
            try:
                validate_checkpoint(path, deep=True)
            except CheckpointCorruptError as e:
                resilience.counter("checkpoint_corrupt_total").inc()
                log.warning("skipping torn/corrupt checkpoint %s: %s",
                            path, e)
                continue
            return step, path
        return None

    def save(self, model, step: int) -> str:
        """Atomic write (tmp + rename — a killed writer can never leave
        a truncated 'latest' checkpoint) + retention prune."""
        from ..utils.model_serializer import save_model
        final = os.path.join(self.directory, self.PATTERN % step)
        tmp = final + ".tmp"
        save_model(model, tmp)
        os.replace(tmp, final)
        for _, path in self._entries()[:-self.keep]:
            try:
                os.remove(path)
            except OSError:
                pass
        return final

    def restore_into(self, model) -> Optional[int]:
        """Load the newest *valid* checkpoint's trees INTO the caller's
        model object (the restart path keeps its own net instance).
        Returns the restored step, or None when no valid checkpoint
        exists."""
        entry = self.latest_valid()
        if entry is None:
            return None
        step, path = entry
        from ..utils.model_serializer import restore_model
        restored = restore_model(path)
        model.params_tree = restored.params_tree
        model.state_tree = restored.state_tree
        model.opt_state = restored.opt_state
        model.iteration = restored.iteration
        model.epoch = restored.epoch
        if restored._rng is not None:
            # same-final-params resume for rng-consuming models
            # (dropout): post-resume steps must split from the SAME key
            # stream position the uninterrupted run had
            model._rng = restored._rng
        return step


#: Deprecated alias (pre-round-9 name). It collided with
#: ``optimize.resilience.CheckpointManager``; new code should import
#: :class:`StepCheckpointManager`.
CheckpointManager = StepCheckpointManager


class MultiHostRunner:
    def __init__(self, coordinator_address: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 auto_detect: bool = False,
                 health: Optional[object] = None):
        self.coordinator_address = coordinator_address or \
            os.environ.get("JAX_COORDINATOR_ADDRESS")
        self.num_processes = num_processes if num_processes is not None else \
            int(os.environ["JAX_NUM_PROCESSES"]) \
            if "JAX_NUM_PROCESSES" in os.environ else None
        self.process_id = process_id if process_id is not None else \
            int(os.environ["JAX_PROCESS_ID"]) \
            if "JAX_PROCESS_ID" in os.environ else None
        self.auto_detect = auto_detect
        self._initialized = False
        self._mesh = None
        # Cluster health plane (docs/robustness.md §cluster-health):
        # health=True/HealthConfig arms it explicitly; health=None defers
        # to the DL4JTPU_HEARTBEAT env knob; health=False disables.
        if health is False:
            self.health_config: Optional[HealthConfig] = None
        elif isinstance(health, HealthConfig):
            self.health_config = health
        elif health is True or health_lib.health_enabled_from_env():
            self.health_config = HealthConfig.from_env()
        else:
            self.health_config = None
        self._monitor: Optional[health_lib.ClusterHealthMonitor] = None
        self.last_grace_step: Optional[int] = None
        # Bounded LRU: wrappers pin their models, so an unbounded cache
        # would leak every model ever fit (hyperparameter sweeps).
        self._wrappers = collections.OrderedDict()
        self._wrapper_cache_size = 4

    def _wrapper_for(self, model, averaging_frequency: int) -> ParallelWrapper:
        """Reuse one wrapper per (model, frequency) so repeated fit calls
        keep their jitted helpers instead of recompiling every time."""
        key = (id(model), int(averaging_frequency))
        w = self._wrappers.get(key)
        if w is not None and w.model is model:
            self._wrappers.move_to_end(key)
            return w
        w = ParallelWrapper(model, mesh=self.mesh(),
                            averaging_frequency=averaging_frequency)
        self._wrappers[key] = w
        while len(self._wrappers) > self._wrapper_cache_size:
            self._wrappers.popitem(last=False)
        return w

    # ------------------------------------------------------------- bootstrap
    def initialize(self) -> "MultiHostRunner":
        """Join the cluster (idempotent). jax.distributed.initialize must
        run BEFORE any jax call that touches the backend, so this method
        makes no jax queries until after the join. Explicit
        coordinator/num/id is the spark-master-URL analog; auto_detect=True
        defers entirely to jax's cluster detection (TPU pods)."""
        if self._initialized:
            return self
        if self.num_processes is not None and self.num_processes > 1:
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address,
                num_processes=self.num_processes,
                process_id=self.process_id)
        elif self.auto_detect:
            jax.distributed.initialize(
                coordinator_address=self.coordinator_address)
        self._initialized = True
        log.info("MultiHostRunner: process %d/%d, %d local / %d global devices",
                 jax.process_index(), jax.process_count(),
                 jax.local_device_count(), jax.device_count())
        return self

    @property
    def is_chief(self) -> bool:
        """Process 0 — the only writer for checkpoints/logs (the driver
        role's one surviving asymmetry)."""
        return jax.process_index() == 0

    def mesh(self):
        """Global data-parallel mesh over every device of every process."""
        if self._mesh is None:
            self.initialize()
            self._mesh = mesh_lib.create_mesh(
                [jax.device_count()], (mesh_lib.DATA_AXIS,), jax.devices())
        return self._mesh

    # -------------------------------------------------------- cluster health
    def start_health(self, on_failure=None
                     ) -> Optional[health_lib.ClusterHealthMonitor]:
        """Start the heartbeat watchdog (idempotent; no-op when the
        plane is disabled or the job is single-process). Process 0
        hosts the beat channel at the coordinator host on
        ``health_config.port`` (default: coordinator port + 1)."""
        if self.health_config is None or jax.process_count() <= 1:
            return None
        if self._monitor is not None:
            return self._monitor
        host, port = self._beat_endpoint()
        if host is None:
            log.warning("cluster health enabled but no coordinator "
                        "address/port to derive the beat channel from; "
                        "set DL4JTPU_HEARTBEAT_PORT — watchdog disabled")
            return None
        transport = health_lib.HttpBeatTransport(
            jax.process_index(), host, port, chief=self.is_chief)
        self._monitor = health_lib.ClusterHealthMonitor(
            jax.process_index(), jax.process_count(), transport,
            config=self.health_config, on_failure=on_failure).start()
        log.info("cluster health watchdog up: beat channel %s "
                 "(interval %.1fs, timeout %.1fs)", transport.url,
                 self.health_config.interval_s, self.health_config.timeout_s)
        return self._monitor

    def stop_health(self) -> None:
        """Stop the watchdog thread and (on the chief) the beat server.
        Call at orderly job shutdown so a fast-exiting chief is not
        misread as lost by peers still finishing up."""
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None

    def _beat_endpoint(self):
        port = self.health_config.port if self.health_config else None
        addr = self.coordinator_address
        if addr and ":" in addr:
            host, _, coord_port = addr.rpartition(":")
            return host, (port if port else int(coord_port) + 1)
        if addr and port:
            return addr, port
        return (None, None) if not port else ("127.0.0.1", port)

    def _timed(self, fn, name: str):
        """Run a blocking collective under the health plane's deadline
        (pass-through when the plane is off): the known blocking points
        fail typed instead of hanging forever."""
        cfg = self.health_config
        if cfg is None or not cfg.barrier_timeout_s:
            return fn()
        return health_lib.timed_collective(
            fn, name=name, timeout_s=cfg.barrier_timeout_s,
            monitor=self._monitor)

    # ------------------------------------------------------------- lockstep
    def _assert_lockstep(self, *values: int):
        """All processes must agree on loop bounds, or SPMD deadlocks
        (the Spark analog: TrainingMaster sizes every split identically)."""
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils
        mine = np.asarray(values, np.int64)
        all_vals = self._timed(
            lambda: multihost_utils.process_allgather(mine), "lockstep")
        if not (all_vals == all_vals[0]).all():
            raise ValueError(
                f"Processes disagree on batch/epoch counts: {all_vals.tolist()}"
                " — every process must feed identically-shaped local "
                "partitions (repartition your data)")

    def barrier(self, name: str = "barrier",
                timeout_s: Optional[float] = None):
        """Cluster barrier. With the health plane armed (or an explicit
        `timeout_s`) the wait is bounded: expiry raises a typed
        :class:`cluster_health.BarrierTimeoutError` (or the watchdog's
        richer PeerLost/Desync diagnosis) instead of wedging forever."""
        if jax.process_count() <= 1:
            return
        from jax.experimental import multihost_utils
        fn = lambda: multihost_utils.sync_global_devices(name)  # noqa: E731
        if timeout_s is not None:
            health_lib.timed_collective(
                fn, name=f"barrier:{name}", timeout_s=timeout_s,
                monitor=self._monitor)
        else:
            self._timed(fn, f"barrier:{name}")

    # ------------------------------------------------------------------- fit
    def fit(self, model, local_features, local_labels=None, *,
            epochs: int = 1, batch_size: int = 32,
            averaging_frequency: int = 1,
            checkpoint_dir: Optional[str] = None,
            checkpoint_every: Optional[int] = None,
            resume: bool = True) -> ParallelWrapper:
        """Train over the global mesh; THIS process contributes
        `local_features/labels` (its partition — the executor's RDD split).
        Global batch per step = batch_size × num_processes.

        Elastic story (beyond the reference, which has none — SURVEY.md
        §5.3): with `checkpoint_dir`, training auto-checkpoints every
        `checkpoint_every` optimizer steps (chief writes, cluster
        barriers) and a RESTARTED job auto-resumes from the newest
        checkpoint — already-trained steps are skipped by replaying the
        (deterministic) data order without stepping, so a preempted run
        reaches the same final parameters as an uninterrupted one
        (tested by killing and restarting a 2-process gloo job).

        Cluster health (docs/robustness.md §cluster-health): with the
        health plane armed (`health=`/`DL4JTPU_HEARTBEAT=1`), a
        heartbeat watchdog runs for the duration of fit — a dead peer
        raises a typed `PeerLostError` (and hard-exits, code 17) instead
        of wedging this process at the next collective, and SIGTERM
        triggers one coordinated grace checkpoint (barrier → chief save
        → barrier) before a clean exit 0; the restart resumes
        bitwise-identically through the replay-skip path above."""
        wrapper = self._wrapper_for(model, averaging_frequency)
        if hasattr(local_features, "num_examples"):     # DataSet
            n = local_features.num_examples()
        elif hasattr(local_features, "shape"):          # array
            n = np.asarray(local_features).shape[0]
        else:                                           # opaque iterator
            n = -1  # caller must guarantee equal batch counts per process
        if n >= 0:
            # n itself must match (not just the batch COUNT): unequal
            # last-batch sizes compile different SPMD programs and hang the
            # cluster at the collective.
            self._assert_lockstep(n, batch_size, epochs)
        else:
            self._assert_lockstep(epochs)
        monitor = self.start_health()
        hook = None
        if monitor is not None:
            hook = monitor.notify_step
            wrapper.step_hooks.append(hook)
        try:
            return self._fit_guarded(wrapper, model, local_features,
                                     local_labels, epochs=epochs,
                                     batch_size=batch_size,
                                     checkpoint_dir=checkpoint_dir,
                                     checkpoint_every=checkpoint_every,
                                     resume=resume, monitor=monitor)
        finally:
            if hook is not None and hook in wrapper.step_hooks:
                wrapper.step_hooks.remove(hook)

    def _fit_guarded(self, wrapper, model, local_features, local_labels, *,
                     epochs, batch_size, checkpoint_dir, checkpoint_every,
                     resume, monitor):
        if checkpoint_dir is None:
            # Delegate the epoch/listener loop to the net's own fit (via
            # the wrapper) so loop semantics exist in exactly one place.
            # No grace handler: there is nowhere to write the checkpoint.
            wrapper.fit(local_features, local_labels, epochs=epochs,
                        batch_size=batch_size)
            return wrapper
        mgr = StepCheckpointManager(checkpoint_dir)
        skip = 0
        if resume:
            restored = mgr.restore_into(model)
            if restored is not None:
                skip = int(model.iteration)
                # the fit loop below re-runs every epoch (replay-skipping
                # trained batches); epoch counting restarts with it so
                # the final epoch equals an uninterrupted run's
                model.epoch = 0
                log.info("resumed from checkpoint step %d", restored)
        self._assert_lockstep(skip)  # all processes see the same files

        def steps_in(ds):
            # optimizer steps one batch will take: tBPTT batches window
            # into ceil(T / fwd_length) steps each (skip counts must be
            # in the same unit as model.iteration)
            from ..nn.conf.builders import BackpropType
            if model.conf.backprop_type != BackpropType.TRUNCATED_BPTT:
                return 1
            feats = ds.features if hasattr(ds, "features") else None
            if feats is None or np.asarray(feats).ndim != 3:
                return 1
            T = np.asarray(feats).shape[1]
            L = model.conf.tbptt_fwd_length
            return -(-T // L)

        remaining = [skip]
        grace_flag = [False]    # set by the SIGTERM handler
        calls = [0]
        cfg = self.health_config
        grace_every = max(1, int(cfg.grace_every)) if cfg else 1

        def grace_poll() -> bool:
            """Cluster-wide agreement on the preemption flag. Called at
            the SAME cadence on every process (replay steps included) so
            the allgather counts always match; any process's flag stops
            the whole cluster at the same step, deterministically."""
            local = grace_flag[0] or (monitor is not None
                                      and monitor.grace_requested())
            if jax.process_count() <= 1:
                return local
            from jax.experimental import multihost_utils
            votes = multihost_utils.process_allgather(
                np.asarray([1 if local else 0], np.int32))
            return bool(np.asarray(votes).any())

        def grace_checkpoint():
            step = int(model.iteration)
            log.info("preemption grace: coordinated checkpoint at step %d",
                     step)
            self.barrier("grace-pre-checkpoint")
            if self.is_chief:
                mgr.save(model, step)
            self.barrier("grace-post-checkpoint")
            health_lib._counter("cluster_grace_checkpoints_total").inc()
            self.last_grace_step = step
            raise health_lib.GraceCheckpointed(step)

        def elastic_step(ds):
            calls[0] += 1
            if calls[0] % grace_every == 0 and grace_poll():
                grace_checkpoint()
            if remaining[0] > 0:
                n = steps_in(ds)  # replay-skip: trained pre-restart
                if n > remaining[0]:
                    raise ValueError(
                        "checkpoint iteration falls inside a tBPTT "
                        "batch's window sequence — checkpoints from a "
                        "different batch/window schedule cannot resume "
                        "this run")
                remaining[0] -= n
                return
            wrapper.fit_batch(ds)
            if monitor is not None:
                # surface a recorded typed failure in the main thread
                # too, while it is still alive to see it
                monitor.check()
            if checkpoint_every and \
                    model.iteration % int(checkpoint_every) == 0:
                self.barrier("pre-checkpoint")
                if self.is_chief:
                    mgr.save(model, int(model.iteration))
                self.barrier("post-checkpoint")

        # SIGTERM → grace flag, checked at the next step boundary.
        # signal.signal only works from the main thread; elsewhere (e.g.
        # a fit driven from a server worker) grace still arms via a
        # peer's flag riding the beat table.
        prev_handler = None
        installed = False
        if threading.current_thread() is threading.main_thread():
            def _on_sigterm(signum, frame):
                grace_flag[0] = True
                if monitor is not None:
                    monitor.request_grace()
            try:
                prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
                installed = True
            except ValueError:   # exotic embeddings: no handler, no grace
                pass
        try:
            model.fit(local_features, local_labels, epochs=epochs,
                      batch_size=batch_size, step_fn=elastic_step,
                      use_async=False)
        except health_lib.GraceCheckpointed as g:
            log.info("grace checkpoint written at step %d — exiting 0 "
                     "for the restarter (resume=True picks it up)", g.step)
            self.stop_health()
            raise SystemExit(0)
        finally:
            if installed:
                signal.signal(signal.SIGTERM, prev_handler)
        wrapper.finalize()
        return wrapper

    # ------------------------------------------------------------ evaluation
    def evaluate(self, model, local_features, local_labels=None, *,
                 batch_size: int = 128):
        """Distributed evaluation: every process evaluates ITS partition
        locally, per-process confusion statistics allgather across the
        cluster, and the merged Evaluation returns everywhere (the
        reference's evaluation flatmap + reduce —
        `spark/impl/multilayer/evaluation/` evaluate() aggregating
        per-partition Evaluation objects via merge)."""
        local = model.evaluate(local_features, local_labels,
                               batch_size=batch_size)
        if jax.process_count() == 1:
            return local
        import pickle

        from jax.experimental import multihost_utils
        blob = np.frombuffer(pickle.dumps(local), np.uint8)
        # fixed-size lockstep transport: allgather needs equal shapes
        size = np.asarray([blob.size], np.int64)
        sizes = multihost_utils.process_allgather(size).reshape(-1)
        cap = int(sizes.max())
        padded = np.zeros(cap, np.uint8)
        padded[:blob.size] = blob
        gathered = multihost_utils.process_allgather(padded)
        merged = None
        for row, n in zip(np.asarray(gathered).reshape(-1, cap), sizes):
            ev = pickle.loads(bytes(row[:int(n)]))
            merged = ev if merged is None else merged.merge(ev)
        return merged

    # --------------------------------------------------------- repartitioning
    @staticmethod
    def balanced_partition(n: int, num_partitions: int, partition: int
                           ) -> slice:
        """Row slice for `partition` under balanced partitioning
        (reference impl/common/repartition/BalancedPartitioner.java:
        each partition gets floor(n/P) elements, the first n%P get one
        more). Use to FIX unbalanced local data instead of being
        rejected by the lockstep guards."""
        if not 0 <= partition < num_partitions:
            raise ValueError(f"partition {partition} not in "
                             f"[0, {num_partitions})")
        base, extra = divmod(n, num_partitions)
        start = partition * base + min(partition, extra)
        return slice(start, start + base + (1 if partition < extra else 0))

    def my_partition(self, *arrays, drop_remainder: bool = True):
        """Balanced-repartition helper bound to THIS process: slice each
        array to this process's share of the global rows. With
        drop_remainder (default) every process gets EXACTLY floor(n/P)
        rows, which is what the SPMD lockstep contract requires — the
        dropped tail (< P rows) is logged."""
        P = jax.process_count()
        p = jax.process_index()
        out = []
        for a in arrays:
            a = np.asarray(a)
            n = a.shape[0]
            if n < P:
                raise ValueError(
                    f"cannot partition {n} rows over {P} processes — "
                    "every process would train on (almost) nothing")
            if drop_remainder:
                per = n // P
                if per * P != n:
                    log.info("my_partition: dropping %d tail rows "
                             "(%d rows over %d processes)",
                             n - per * P, n, P)
                out.append(a[p * per:(p + 1) * per])
            else:
                out.append(a[self.balanced_partition(n, P, p)])
        return out[0] if len(out) == 1 else tuple(out)

    # ------------------------------------------------------------ checkpoint
    def save_checkpoint(self, model, path: str):
        """Chief-only write + cluster barrier (reference: only the Spark
        driver persists, ModelSerializer.java:37-127)."""
        self.barrier("pre-checkpoint")
        if self.is_chief:
            from ..utils.model_serializer import ModelSerializer
            ModelSerializer.write_model(model, path)
        self.barrier("post-checkpoint")

    def materialize_local(self, model):
        """Pull the model's (replicated) trees back to process-local
        arrays so single-process inference/eval works after training."""
        import jax.numpy as jnp
        to_local = lambda t: jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a)), t)
        model.params_tree = to_local(model.params_tree)
        model.opt_state = to_local(model.opt_state)
        model.state_tree = to_local(model.state_tree)
        model._rng = jnp.asarray(np.asarray(model._rng))
        return model
