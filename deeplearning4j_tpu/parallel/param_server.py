"""Asynchronous parameter-server data parallelism.

Reference parity: the third parallelism flavor —
`parallelism/parameterserver/ParameterServerTrainer{,Context}.java:43-66`
swaps ParallelWrapper's DefaultTrainer for workers that PUSH gradients
to / PULL parameters from an Aeron-UDP ParameterServerNode, with no
averaging barrier; `dl4j-spark-parameterserver`'s
ParameterServerTrainingHook plays the same role on Spark workers.

TPU-native redesign: the server is an in-process parameter host pinned
to one device; the transport is shared memory + a lock instead of Aeron
UDP (the reference's media driver is usually in-process too). Worker
threads each own a device, loop pull → jitted gradient step → push with
NO barrier between workers, and the server applies each push through
the model's own updater chain (gradient normalization included) the
moment it arrives. Python threads work here because every hot segment —
device-to-device parameter copies, the jitted gradient computation, the
jitted server update — releases the GIL.

Staleness: pushes carry the parameter version they were computed at.
The server applies a push only if `current - version <= max_staleness`
and DROPS it otherwise (the worker just re-pulls) — bounded-staleness
async SGD. `max_staleness=0` forces every applied gradient to be
computed on the latest parameters (serialized, losing async throughput
but maximally fresh); large values approach unbounded Hogwild. Dropped
counts are reported on the server for observability.
"""
from __future__ import annotations

import logging
import queue
import threading
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..data.iterators import as_iterator
from ..nn.multilayer import MultiLayerNetwork
from ..nn.updaters import normalize_layer_gradients
from ..optimize import metrics as metrics_mod
from ..optimize import resilience
from ..utils import faults

log = logging.getLogger(__name__)


def _worker_failure(errors: list) -> "RuntimeError":
    """Aggregate EVERY collected worker error into one exception message
    (a multi-worker failure losing all but errors[0] made the real root
    cause — often on a different worker — invisible)."""
    msgs = "; ".join(f"[worker error {i}] {type(e).__name__}: {e}"
                     for i, e in enumerate(errors))
    return RuntimeError(
        f"parameter-server worker failed ({len(errors)} error(s)): {msgs}")


def _layer_map(net):
    """(key, layer) pairs addressing the net's params/opt trees: indexed
    tuple for MultiLayerNetwork, name-keyed dict for ComputationGraph —
    the reference ParameterServerTrainer drives any Model."""
    if hasattr(net, "layers"):
        return list(enumerate(net.layers)), tuple
    return ([(name, net.conf.nodes[name].layer)
             for name in net._layer_nodes], dict)


class ParameterServer:
    """In-process parameter host (ParameterServerNode role)."""

    def __init__(self, net, max_staleness: int = 2,
                 device: Optional[jax.Device] = None):
        self._net = net
        self.device = device or jax.local_devices()[0]
        self.max_staleness = int(max_staleness)
        self._lock = threading.Lock()
        self.version = 0
        self.stale_drops = 0
        self.applied = 0
        self.params = jax.device_put(net.params_tree, self.device)
        self.opt_state = jax.device_put(net.opt_state, self.device)
        entries, container = _layer_map(net)

        def apply_update(params, opt_state, iteration, grads):
            new_params, new_opt = {}, {}
            for key, layer in entries:
                g = normalize_layer_gradients(
                    grads[key], layer.gradient_normalization,
                    layer.gradient_normalization_threshold)
                updates, opt_i = layer.updater.update(
                    g, opt_state[key], iteration)
                if layer.frozen:
                    new_params[key] = params[key]
                    new_opt[key] = opt_state[key]
                else:
                    new_params[key] = jax.tree_util.tree_map(
                        lambda p, u: p - u.astype(p.dtype), params[key],
                        updates)
                    new_opt[key] = opt_i
            if container is tuple:
                n = len(entries)
                return (tuple(new_params[i] for i in range(n)),
                        tuple(new_opt[i] for i in range(n)))
            return new_params, new_opt

        # NO buffer donation here: pull() hands out references to the
        # live param buffers, and a donated apply would delete them under
        # a concurrently-computing worker ("Array has been deleted").
        self._apply = jax.jit(apply_update)

    def pull(self, device: Optional[jax.Device] = None):
        """Current (version, params) — params copied to the worker's
        device (the ParameterServerClient.getParams round trip)."""
        with self._lock:
            params, version = self.params, self.version
        if device is not None and device != self.device:
            params = jax.device_put(params, device)
        return version, params

    def push(self, version: int, grads) -> bool:
        """Apply a gradient computed at `version`; False = dropped as
        too stale (worker should re-pull and retry on fresh params)."""
        return self.push_versioned(version, grads)[0]

    def push_versioned(self, version: int, grads):
        """push() that also returns the post-apply server version,
        captured under the SAME lock acquisition — reading
        `server.version` after push() returns can observe a different
        concurrent push's version."""
        pushes = metrics_mod.registry().counter(
            "param_server_pushes_total",
            "Gradient pushes by outcome (applied vs dropped as stale)")
        with self._lock:
            if self.version - version > self.max_staleness:
                self.stale_drops += 1
                pushes.labels(result="stale_drop").inc()
                return False, self.version
            grads = jax.device_put(grads, self.device)
            self.params, self.opt_state = self._apply(
                self.params, self.opt_state,
                jnp.asarray(self.version, jnp.int32), grads)
            self.version += 1
            self.applied += 1
            pushes.labels(result="applied").inc()
            return True, self.version

    def stats(self) -> dict:
        """Consistent (version, applied, stale_drops) snapshot. The three
        counters move together under ``_lock``; reading them attribute-by-
        attribute from another thread (the HTTP stats route) can observe
        a torn triple mid-push — e.g. the new version with the old
        applied count."""
        with self._lock:
            return {"version": self.version, "applied": self.applied,
                    "stale_drops": self.stale_drops}


class ParameterServerTrainer:
    """Async DP fit loop (ParameterServerTrainerContext role): one
    worker thread per device, round-robin minibatch feed, no barrier.
    Drives MultiLayerNetwork and ComputationGraph (single-input)."""

    def __init__(self, net,
                 workers: Optional[int] = None,
                 devices: Optional[List[jax.Device]] = None,
                 max_staleness: int = 2, queue_size: int = 4,
                 max_worker_restarts: int = 2):
        net._check_init()
        states = (net.state_tree.values()
                  if isinstance(net.state_tree, dict) else net.state_tree)
        if any(len(st) for st in states):
            # BN running stats etc. have no well-defined owner under
            # asynchronous updates (whose statistics win?); the sync
            # paths commit state, this one cannot — reject loudly
            raise NotImplementedError(
                "async parameter-server training does not support "
                "stateful layers (e.g. BatchNormalization running "
                "statistics); use ParallelWrapper")
        self.net = net
        devs = devices or jax.local_devices()
        n = workers or len(devs)
        # workers may outnumber devices (thread-level async on one chip,
        # exactly the reference's threads-per-GPU knob)
        self.devices = [devs[i % len(devs)] for i in range(n)]
        self.server = ParameterServer(net, max_staleness=max_staleness)
        self.queue_size = int(queue_size)
        self.losses: List[float] = []
        # shared respawn budget across all workers: a transiently-failing
        # worker loop restarts in place instead of dying permanently, a
        # systematically-failing fleet still surfaces the error
        self.max_worker_restarts = int(max_worker_restarts)
        self._restarts_left = self.max_worker_restarts
        self._restart_lock = threading.Lock()

        # both network classes expose _loss_pure(params, state, DATA...,
        # rng, train); the worker packs DataSets into the right DATA args
        def loss_and_grads(params, state, rng, *data):
            (loss, _), grads = jax.value_and_grad(
                net._loss_pure, has_aux=True)(
                    params, state, *data, rng, True)
            return loss, grads

        self._grad_fn = jax.jit(loss_and_grads)
        self._is_graph = not hasattr(net, "layers")

    def _pack_item(self, item):
        """(x, y, fmask, lmask) → the net's _loss_pure data args."""
        x, y, fmask, lmask = item
        if not self._is_graph:
            return (x, y, fmask, lmask)
        from ..data.dataset import MultiDataSet
        mds = MultiDataSet([np.asarray(x)], [np.asarray(y)],
                           None if fmask is None else [np.asarray(fmask)],
                           None if lmask is None else [np.asarray(lmask)])
        return self.net._pack(mds)

    def _worker(self, wid: int, q: "queue.Queue", errors: list,
                stop: threading.Event):
        """Respawn shell: restarts the worker loop in place on error
        while the shared budget lasts; only then does the worker die and
        surface its error to fit()."""
        attempt = 0
        while True:
            try:
                self._worker_loop(wid, attempt, q, stop)
                return
            except Exception as e:
                with self._restart_lock:
                    allowed = self._restarts_left > 0 and not stop.is_set()
                    if allowed:
                        self._restarts_left -= 1
                if not allowed:
                    # surfaced by fit(); a dead worker must not silently
                    # hang the queue
                    errors.append(e)
                    log.exception("parameter-server worker %d died", wid)
                    return
                attempt += 1
                metrics_mod.registry().counter(
                    "worker_respawns_total",
                    "Parameter-server worker loops respawned after an "
                    "error").inc()
                log.warning("parameter-server worker %d failed "
                            "(%s: %s); respawning (restarts left: %d)",
                            wid, type(e).__name__, e, self._restarts_left)

    def _worker_loop(self, wid: int, attempt: int, q: "queue.Queue",
                     stop: threading.Event):
        dev = self.devices[wid]
        # fresh key stream per (worker, incarnation) — async SGD carries
        # no cross-respawn rng contract
        rng = jax.random.PRNGKey(1000 + wid + 100000 * attempt)
        state = jax.device_put(self.net.state_tree, dev)
        steps = metrics_mod.registry().counter(
            "param_server_worker_steps_total",
            "Applied async-SGD steps per worker thread"
            ).labels(worker=str(wid))
        while not stop.is_set():
            try:
                item = q.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                return
            data = jax.device_put(self._pack_item(item), dev)
            # stale-push redo loop checks stop too: an aborting fit must
            # not leave a worker spinning pull/push forever
            while not stop.is_set():
                faults.fire("ps.pull")
                version, params = self.server.pull(dev)
                rng, sub = jax.random.split(rng)
                loss, grads = self._grad_fn(params, state, sub, *data)
                faults.fire("ps.push")
                if self.server.push(version, grads):
                    self.losses.append(float(loss))
                    steps.inc()
                    break
                # dropped as stale: re-pull fresh params and redo

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 32) -> "ParameterServerTrainer":
        it = as_iterator(data, labels, batch_size)
        q: "queue.Queue" = queue.Queue(maxsize=self.queue_size)
        errors: list = []
        stop = threading.Event()
        threads = [threading.Thread(target=self._worker,
                                    args=(i, q, errors, stop), daemon=True)
                   for i in range(len(self.devices))]
        for t in threads:
            t.start()

        def put_checked(item):
            # bounded put that keeps checking worker health: a plain
            # blocking put deadlocks forever if all workers die with the
            # queue full (nobody left to drain it)
            while True:
                if errors:
                    raise _worker_failure(errors) from errors[0]
                try:
                    q.put(item, timeout=0.2)
                    return
                except queue.Full:
                    continue

        try:
            for _ in range(epochs):
                it.reset()
                for ds in it:
                    put_checked(
                        (np.asarray(ds.features), np.asarray(ds.labels),
                         None if ds.features_mask is None
                         else np.asarray(ds.features_mask),
                         None if ds.labels_mask is None
                         else np.asarray(ds.labels_mask)))
            for _ in threads:
                put_checked(None)  # graceful drain: workers finish the
            for t in threads:      # queue before seeing their sentinel
                t.join()
        finally:
            # Orderly shutdown on BOTH paths (a mid-epoch worker error
            # must not strand surviving daemon threads on the queue):
            # signal abort, drain whatever the feeder left enqueued, then
            # join everyone with a bounded wait.
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            for t in threads:
                t.join(timeout=10.0)
            alive = [t.name for t in threads if t.is_alive()]
            if alive:
                log.warning("parameter-server shutdown: %d worker "
                            "thread(s) still alive after join timeout: "
                            "%s", len(alive), alive)
        if errors:
            raise _worker_failure(errors) from errors[0]
        # commit the server's latest state back into the network
        self.net.params_tree = jax.device_put(
            self.server.params, jax.local_devices()[0])
        self.net.opt_state = jax.device_put(
            self.server.opt_state, jax.local_devices()[0])
        self.net.iteration = self.server.version
        if self.losses:
            self.net.score_value = jnp.asarray(self.losses[-1])
        return self


# ---------------------------------------------------------------------------
# Cross-process transport (the dl4j-spark-parameterserver role)
# ---------------------------------------------------------------------------


class ParameterServerHttpNode:
    """HTTP front for a ParameterServer so workers in OTHER processes /
    hosts push and pull — the reference's Aeron-UDP ParameterServerNode
    plus dl4j-spark-parameterserver's ParameterServerTrainingHook/
    Subscriber role (gradient push + param pull from cluster workers),
    with stdlib HTTP as the wire (the media-driver analog).

    Routes:  GET  /params -> {"version": v, "blob": b64-npz(params)}
             POST /push {"version": v, "blob": b64-npz(grads)}
                        -> {"applied": bool, "version": v'}
             GET  /stats -> {"version", "applied", "stale_drops"}
    """

    def __init__(self, server: ParameterServer, port: int = 0):
        import base64

        from ..utils.http_server import JsonHttpServer
        from ..utils.model_serializer import (_npz_bytes_to_tree,
                                              _tree_to_npz_bytes)
        self.server = server
        self._b64 = base64
        self._to_npz = _tree_to_npz_bytes
        self._from_npz = _npz_bytes_to_tree

        def get_params(_):
            version, params = server.pull()
            blob = self._b64.b64encode(self._to_npz(params)).decode()
            return 200, {"version": version, "blob": blob}

        def post_push(payload):
            grads = self._from_npz(
                self._b64.b64decode(payload["blob"]), server.params)
            applied, version = server.push_versioned(
                int(payload["version"]), grads)
            return 200, {"applied": bool(applied), "version": version}

        def get_stats(_):
            return 200, server.stats()

        self._http = JsonHttpServer(
            get_routes={"/params": get_params, "/stats": get_stats},
            post_routes={"/push": post_push}, port=port)

    def start(self) -> "ParameterServerHttpNode":
        self._http.start()
        return self

    def stop(self):
        self._http.stop()

    @property
    def url(self) -> str:
        return self._http.url


class HttpParameterServerClient:
    """Worker-side pull/push over HTTP (reference ParameterServerClient).
    `template` is a matching params pytree used to decode the wire blobs
    (workers always hold the model, so it is free).

    pull/push retry transient transport failures with exponential
    backoff + jitter under `retry` (a resilience.RetryPolicy; default
    from the DL4JTPU_RETRY_* env knobs — docs/robustness.md). The
    ``ps.pull``/``ps.push`` fault points fire once per ATTEMPT, so
    injected transient faults within the budget are fully absorbed."""

    def __init__(self, url: str, template,
                 retry: Optional[resilience.RetryPolicy] = None):
        import base64

        from ..utils.model_serializer import (_npz_bytes_to_tree,
                                              _tree_to_npz_bytes)
        self.url = url.rstrip("/")
        self._template = template
        self._b64 = base64
        self._to_npz = _tree_to_npz_bytes
        self._from_npz = _npz_bytes_to_tree
        self.retry = retry

    def _get(self, path):
        import json as _json
        import urllib.request
        with urllib.request.urlopen(self.url + path, timeout=60) as r:
            return _json.loads(r.read())

    def pull(self):
        def attempt():
            faults.fire("ps.pull")
            return self._get("/params")
        rec = resilience.retry_call(attempt, edge="ps.pull",
                                    policy=self.retry)
        params = self._from_npz(self._b64.b64decode(rec["blob"]),
                                self._template)
        return int(rec["version"]), params

    def push(self, version: int, grads) -> bool:
        import json as _json
        import urllib.request
        body = _json.dumps({
            "version": int(version),
            "blob": self._b64.b64encode(self._to_npz(grads)).decode(),
        }).encode()
        req = urllib.request.Request(
            self.url + "/push", data=body,
            headers={"Content-Type": "application/json"})

        def attempt():
            faults.fire("ps.push")
            with urllib.request.urlopen(req, timeout=60) as r:
                return bool(_json.loads(r.read())["applied"])
        return resilience.retry_call(attempt, edge="ps.push",
                                     policy=self.retry)

    def stats(self) -> dict:
        return self._get("/stats")


def remote_worker_fit(net, url: str, data,
                      labels=None, *, epochs: int = 1,
                      batch_size: int = 32, seed: int = 0,
                      retry: Optional[resilience.RetryPolicy] = None
                      ) -> int:
    """One remote worker's training loop against an HTTP parameter
    server: pull -> local gradient -> push, retrying dropped (stale)
    pushes on fresh params (the ParameterServerTrainingHook loop a Spark
    executor runs). Transient transport failures back off and retry
    under `retry` (default: env-configured resilience.RetryPolicy).
    Returns the number of applied pushes."""
    net._check_init()
    states = (net.state_tree.values()
              if isinstance(net.state_tree, dict) else net.state_tree)
    if any(len(st) for st in states):
        raise NotImplementedError(
            "async parameter-server training does not support stateful "
            "layers")
    if not hasattr(net, "layers"):
        raise NotImplementedError(
            "remote_worker_fit drives MultiLayerNetwork; use the "
            "in-process ParameterServerTrainer for ComputationGraph")
    client = HttpParameterServerClient(url, net.params_tree, retry=retry)
    rng = jax.random.PRNGKey(seed)

    def loss_and_grads(params, state, rng_, x, y, fmask, lmask):
        (loss, _), grads = jax.value_and_grad(
            net._loss_pure, has_aux=True)(
                params, state, x, y, fmask, lmask, rng_, True)
        return loss, grads

    grad_fn = jax.jit(loss_and_grads)
    it = as_iterator(data, labels, batch_size)
    applied = 0
    for _ in range(epochs):
        it.reset()
        for ds in it:
            x = jnp.asarray(ds.features)
            y = jnp.asarray(ds.labels)
            while True:
                version, params = client.pull()
                rng, sub = jax.random.split(rng)
                _, grads = grad_fn(params, net.state_tree, sub, x, y,
                                   None, None)
                if client.push(version, jax.device_get(grads)):
                    applied += 1
                    break
    return applied
