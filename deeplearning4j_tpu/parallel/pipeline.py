"""PipelineParallelWrapper: GPipe-style microbatched pipeline
parallelism over a mesh "stage" axis (parallel/pipeline.py; round-5
VERDICT item 6 — the one member of the standard parallelism taxonomy
the framework didn't ship).

BEYOND-parity scope (the reference's only strategy is data parallelism,
SURVEY.md §2.4). The TPU-idiomatic formulation is the collective
pipeline from the scaling-book recipe: all S stages run ONE SPMD
program under `shard_map`; each device holds its stage's layer
parameters (stacked with a leading stage axis, sharded over "stage");
activations hop stage→stage+1 with `lax.ppermute` each tick. With M
microbatches the schedule runs M+S-1 ticks: tick t has stage s working
on microbatch t-s, so up to S microbatches are in flight — the GPipe
bubble is the (S-1)/(M+S-1) fraction of ticks a stage idles (it
executes masked compute; this is real GPipe cost, not hidden).

Scope (validated loudly in __init__): the pipelined BODY must be a
contiguous run of IDENTICAL layers (same config → same param
structure/shapes — the homogeneous-transformer-stack shape real TPU
pipelining serves; praxis/t5x pipeline the same way) with n_in == n_out
and no dropout / recurrent state / per-layer gradient normalization,
followed by the output layer, which runs (replicated) on the last
stage. Gradients flow back through the reversed ppermute schedule;
updates apply to the STACKED params in place — elementwise updater math
(Sgd/Adam/...) is per-stage-correct on stacked arrays. Parity with
single-device full-batch training is exact for mean losses because the
M equal microbatch means average to the global mean
(tests/test_pipeline.py).
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib
from ..nn.multilayer import _regularization_score
from ..optimize import metrics as metrics_mod

log = logging.getLogger(__name__)


def pipeline_mesh(stages: Optional[int] = None, devices=None) -> Mesh:
    """A ("stage",) mesh. Default: every device is one stage."""
    devices = list(devices if devices is not None else jax.devices())
    if stages is None:
        stages = len(devices)
    return mesh_lib.create_mesh([stages], (mesh_lib.STAGE_AXIS,), devices)


class PipelineParallelWrapper:
    """Train a MultiLayerNetwork of S*k identical body layers + an
    output layer with the body split into S pipeline stages of k layers
    each, microbatched GPipe-style."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 n_microbatches: int = 4):
        self.model = model
        self.mesh = mesh if mesh is not None else pipeline_mesh()
        if mesh_lib.STAGE_AXIS not in self.mesh.axis_names:
            raise ValueError(
                f"PipelineParallelWrapper needs a mesh with a "
                f"'{mesh_lib.STAGE_AXIS}' axis; got {self.mesh.axis_names}")
        self.stages = int(self.mesh.shape[mesh_lib.STAGE_AXIS])
        self.n_microbatches = int(n_microbatches)
        if self.n_microbatches < 1:
            raise ValueError("n_microbatches must be >= 1")
        self._validate_layers()
        self._placed = False
        self._step = None
        # stacked device state (the wrapper's canonical copy between
        # steps; net.params_tree is refreshed by materialize_local)
        self._body_params = None
        self._body_opt = None
        self._out_params = None
        self._out_opt = None

    # -------------------------------------------------------------- validate
    def _validate_layers(self):
        net = self.model
        if hasattr(net, "_pack"):
            raise NotImplementedError(
                "pipeline parallelism supports MultiLayerNetwork (the "
                "homogeneous-stack shape); ComputationGraph DAGs do not "
                "split into uniform SPMD stages")
        layers = net.layers
        if len(layers) < 2 or not layers[-1].is_output_layer():
            raise ValueError("need >= 1 body layer + an output layer")
        body = layers[:-1]
        if len(body) % self.stages:
            raise ValueError(
                f"{len(body)} body layers do not divide {self.stages} "
                f"stages")
        from ..utils import serde
        ref = serde.to_json(body[0])
        for i, l in enumerate(body[1:], 1):
            if serde.to_json(l) != ref:
                raise ValueError(
                    f"body layer {i} differs from layer 0 — the pipeline "
                    f"body must be IDENTICAL layers (got a heterogeneous "
                    f"stack; use TP/DP/SP for those)")
        # Stateful layers first: they may lack n_in/n_out entirely
        # (BatchNormalization), so this must precede the chaining check.
        import jax.numpy as jnp
        for i, l in enumerate(layers):
            if l.init_state(jnp.float32):
                raise ValueError(
                    f"layer {i} is stateful (non-empty init_state, e.g. "
                    f"batch-norm running statistics); stage_apply drops "
                    f"returned state, so its updates would be silently "
                    f"lost — stateful layers are unsupported under "
                    f"pipeline parallelism")
        l0 = body[0]
        if l0.n_in != l0.n_out:
            raise ValueError(
                f"body layers need n_in == n_out to chain across stages "
                f"(got {l0.n_in}->{l0.n_out})")
        for i, l in enumerate(layers):
            if getattr(l, "dropout_rate", 0):
                raise ValueError(
                    f"layer {i} has dropout; the microbatch schedule "
                    f"cannot reproduce the single-batch dropout draw — "
                    f"disable dropout under pipeline parallelism")
            if l.is_recurrent():
                raise ValueError(
                    f"layer {i} is recurrent; carried state does not "
                    f"split across microbatches")
            from ..nn.updaters import GradientNormalization
            if i < len(layers) - 1 and l.gradient_normalization not in (
                    None, GradientNormalization.NONE):
                raise ValueError(
                    f"body layer {i} uses per-layer gradient "
                    f"normalization, which would mix stages on the "
                    f"stacked gradient")
            if net.conf.preprocessor(i) is not None:
                raise ValueError(
                    f"input preprocessor at layer {i} breaks stage "
                    f"uniformity")
        self.k = len(body) // self.stages

    # ----------------------------------------------------------------- place
    def _stack_body(self, trees):
        """[per-layer subtree] * (S*k) -> per-stage k-tuples stacked on
        a leading stage axis: leaf shape [S, ...]."""
        S, k = self.stages, self.k
        stages = []
        for s in range(S):
            stages.append(tuple(trees[s * k + j] for j in range(k)))
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stages)

    def _stage_sharding(self, tree):
        sh = NamedSharding(self.mesh, P(mesh_lib.STAGE_AXIS))
        return jax.tree_util.tree_map(
            lambda a: mesh_lib.place_global(a, sh, self.mesh), tree)

    def _place_model(self):
        net = self.model
        n_body = len(net.layers) - 1
        self._body_params = self._stage_sharding(
            self._stack_body(list(net.params_tree[:n_body])))
        self._body_opt = self._stage_sharding(
            self._stack_body(list(net.opt_state[:n_body])))
        rep = NamedSharding(self.mesh, P())
        self._out_params = jax.tree_util.tree_map(
            lambda a: mesh_lib.place_global(a, rep, self.mesh),
            net.params_tree[n_body])
        self._out_opt = jax.tree_util.tree_map(
            lambda a: mesh_lib.place_global(a, rep, self.mesh),
            net.opt_state[n_body])
        self._placed = True

    # ------------------------------------------------------------------ step
    def _build_step(self):
        net = self.model
        S, k, M = self.stages, self.k, self.n_microbatches
        axis = mesh_lib.STAGE_AXIS
        template = net.layers[0]
        out_layer = net.layers[-1]
        fwd_perm = [(i, i + 1) for i in range(S - 1)]

        def spmd_loss(body_p, out_p, x_mb, y_mb):
            """Runs inside shard_map: body_p leaves [1, k-subtree...]
            (this stage's slice), x_mb/y_mb [M, mb, ...] replicated."""
            s = jax.lax.axis_index(axis)
            # local slice: k-tuple of per-layer param dicts, leaves [...]
            local = jax.tree_util.tree_map(lambda a: a[0], body_p)

            def stage_apply(h):
                for j in range(k):
                    h, _ = template.forward(local[j], {}, h, train=True,
                                            rng=None, mask=None)
                return h

            buf = jnp.zeros_like(x_mb[0])
            loss_acc = jnp.zeros((), jnp.float32)
            for t in range(M + S - 1):
                # stage 0 consumes microbatch t (clamped; masked later),
                # stages s>0 consume the activation hopped from s-1
                x0 = x_mb[min(t, M - 1)]
                h_in = jnp.where(s == 0, x0, buf)
                act = stage_apply(h_in)
                if t >= S - 1:
                    m = t - (S - 1)  # microbatch completing on stage S-1
                    l = out_layer.compute_score(out_p, act, y_mb[m], None)
                    loss_acc = loss_acc + jnp.where(
                        s == S - 1, l.astype(jnp.float32), 0.0)
                if t < M + S - 2:
                    buf = jax.lax.ppermute(act, axis, fwd_perm)
            # every stage contributed zeros except the last; psum makes
            # the scalar replicated (mean of M equal microbatch means ==
            # the full-batch mean)
            return jax.lax.psum(loss_acc, axis) / M

        from .mesh import shard_map_compat
        smapped = shard_map_compat(
            spmd_loss, self.mesh,
            in_specs=(P(axis), P(), P(), P()),
            out_specs=P())

        def loss_fn(body_p, out_p, x_mb, y_mb):
            loss = smapped(body_p, out_p, x_mb, y_mb)
            # regularization over ALL params on the stacked trees:
            # summing a [S, ...] leaf == summing the S layers' leaves,
            # so the math is identical to the single-device reg term
            reg = _regularization_score([template] * k, list(body_p)) \
                + _regularization_score([out_layer], [out_p])
            return loss + reg

        from ..nn.updaters import normalize_layer_gradients

        def step(body_p, body_o, out_p, out_o, iteration, x_mb, y_mb):
            loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(
                body_p, out_p, x_mb, y_mb)
            g_body, g_out = grads
            if template.frozen:  # transfer-learning freeze honored
                new_bp, new_bo = body_p, body_o
            else:
                upd_b, new_bo = template.updater.update(g_body, body_o,
                                                        iteration)
                new_bp = jax.tree_util.tree_map(
                    lambda p, u: p - u.astype(p.dtype), body_p, upd_b)
            if out_layer.frozen:
                new_op, new_oo = out_p, out_o
            else:
                # per-layer normalization is fine on the (unstacked)
                # output layer — only BODY layers reject it (stacking
                # would mix stages in one norm)
                g_out = normalize_layer_gradients(
                    g_out, out_layer.gradient_normalization,
                    out_layer.gradient_normalization_threshold)
                upd_o, new_oo = out_layer.updater.update(g_out, out_o,
                                                         iteration)
                new_op = jax.tree_util.tree_map(
                    lambda p, u: p - u.astype(p.dtype), out_p, upd_o)
            return new_bp, new_bo, new_op, new_oo, iteration + 1, loss

        sh = lambda t: jax.tree_util.tree_map(lambda a: a.sharding, t)
        out_sh = (sh(self._body_params), sh(self._body_opt),
                  sh(self._out_params), sh(self._out_opt), None, None)
        self._step = jax.jit(step, donate_argnums=(0, 1, 2, 3),
                             out_shardings=out_sh)

    # ------------------------------------------------------------------- fit
    def fit_batch(self, ds) -> None:
        """One GPipe-scheduled optimizer step on one DataSet batch
        (batch must divide n_microbatches; masks unsupported — the
        per-microbatch mean-loss recombination requires uniform
        denominators)."""
        net = self.model
        net._check_init()
        if not self._placed:
            self._place_model()
        if self._step is None:
            self._build_step()
        if ds.features_mask is not None or ds.labels_mask is not None:
            raise NotImplementedError(
                "masks are unsupported under pipeline parallelism "
                "(non-uniform loss denominators break microbatch "
                "recombination)")
        x = jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels)
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(net._dtype)
        n = x.shape[0]
        M = self.n_microbatches
        if n % M:
            raise ValueError(f"batch {n} must divide {M} microbatches")
        x_mb = x.reshape(M, n // M, *x.shape[1:])
        y_mb = y.reshape(M, n // M, *y.shape[1:])
        rep = NamedSharding(self.mesh, P())
        x_mb = mesh_lib.place_global(x_mb, rep, self.mesh)
        y_mb = mesh_lib.place_global(y_mb, rep, self.mesh)
        with self.mesh:
            (self._body_params, self._body_opt, self._out_params,
             self._out_opt, new_iter, loss) = self._step(
                self._body_params, self._body_opt, self._out_params,
                self._out_opt, net._iteration_device(self.mesh), x_mb,
                y_mb)
        net._commit_iteration(new_iter, self.mesh)
        net.score_value = loss
        metrics_mod.registry().counter(
            "pipeline_steps_total",
            "GPipe-scheduled optimizer steps (stage/microbatch-labeled)"
            ).labels(stages=str(self.stages),
                     microbatches=str(self.n_microbatches)).inc()
        metrics_mod.record_train_step(1)
        for lst in net.listeners:
            lst.iteration_done(net, net.iteration)

    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128) -> "PipelineParallelWrapper":
        """Epoch loop. Indivisible batches are rejected UP FRONT (not
        mid-epoch with params already mutated): every batch including
        the tail must divide n_microbatches — pipeline microbatches are
        not zero-weight-padded (the bubble schedule would train pad
        rows for real; repartition instead)."""
        self.model._check_init()
        M = self.n_microbatches
        if batch_size % M:
            raise ValueError(
                f"batch_size {batch_size} must divide {M} microbatches")
        try:
            feats = data.features if hasattr(data, "features") else data
            n = np.shape(feats)[0]
        except Exception:
            n = None  # iterator input: checked per batch
        if n is not None:
            tail = n % batch_size
            if tail and tail % M:
                raise ValueError(
                    f"final batch of {tail} examples does not divide "
                    f"{M} microbatches; choose a batch size so every "
                    f"batch (incl. the tail) divides, or repartition")
            if hasattr(data, "features_mask") and (
                    data.features_mask is not None
                    or data.labels_mask is not None):
                raise NotImplementedError(
                    "masks are unsupported under pipeline parallelism")
        # pad_to_bucket OFF: it synthesizes the labels mask this wrapper
        # rejects, and zero-weight pad rows would train for real in the
        # bubble schedule. Device prefetch OFF: batches are re-placed
        # per-stage inside fit_batch.
        self.model.fit(data, labels, epochs=epochs, batch_size=batch_size,
                       step_fn=self.fit_batch, pad_to_bucket=False,
                       prefetch_to_device=False)
        return self

    # -------------------------------------------------------------- evidence
    def stage_shard_report(self) -> dict:
        """{leaf path: spec} evidence that body params really live
        stage-sharded (tests assert; a replicated run can't fake it)."""
        if not self._placed:
            self._place_model()
        out = {}
        leaves, _ = jax.tree_util.tree_flatten_with_path(self._body_params)
        for path, a in leaves:
            spec = tuple(a.sharding.spec)
            if any(x is not None for x in spec):
                out[jax.tree_util.keystr(path)] = spec
        return out

    def materialize_local(self) -> None:
        """Unstack the stage-sharded params/opt back into the net's
        canonical per-layer trees (replicated host arrays) so save /
        inference / plain fit work; the next fit_batch re-places."""
        net = self.model
        S, k = self.stages, self.k
        body_p = mesh_lib.gather_replicated(self._body_params, self.mesh)
        body_o = mesh_lib.gather_replicated(self._body_opt, self.mesh)
        unstack = lambda tree, s, j: jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a[s])), tree[j])
        new_params = []
        new_opt = []
        for s in range(S):
            for j in range(k):
                new_params.append(unstack(body_p, s, j))
                new_opt.append(unstack(body_o, s, j))
        to_local = lambda t: jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a)), t)
        new_params.append(to_local(self._out_params))
        new_opt.append(to_local(self._out_opt))
        net.params_tree = tuple(new_params)
        net.opt_state = tuple(new_opt)
        self._placed = False
        self._step = None
