"""SequenceParallelWrapper: train attention networks with the TIME axis
sharded over a device mesh (sequence/context parallelism), optionally
combined with data parallelism — the trainable face of the ring-attention
kernel in ops/attention.py.

BEYOND-parity scope (the reference predates attention; its only
long-sequence devices are truncated BPTT + masking, SURVEY.md §5.7). On
TPU the canonical long-context mechanism is ring attention over a mesh
axis: each device holds a time slice of the batch, K/V blocks rotate
around the ring with `ppermute` over ICI, and nothing ever materializes
the full [T, T] score matrix. Everything OUTSIDE the attention layers —
projections, dense layers, the loss — is time-local, so plain GSPMD
sharding of the [batch, time, ...] tensors handles it: XLA inserts the
(cheap, loss-reduction) collectives.

Design: this wrapper re-jits the net's raw train step under the
`sequence_parallel` context, which flips every SelfAttentionLayer from
`dense_attention` to `ring_self_attention` AT TRACE TIME. The net's own
cached jit is untouched, so the same network can keep training
single-device before/after. Gradients flow through the ring (ppermute's
VJP is the inverse permutation); parity with single-device training is
pinned by tests/test_sequence_parallel.py.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib
from ..ops.attention import sequence_parallel

log = logging.getLogger(__name__)


def seq_parallel_mesh(seq_devices: Optional[int] = None,
                      data_devices: int = 1, model_devices: int = 1,
                      devices=None) -> Mesh:
    """A ("data", "seq") mesh — or ("data", "model", "seq") when
    model_devices > 1 (the 3-D DP x TP x SP grid). Default: all devices
    on the seq axis (pure sequence parallelism)."""
    devices = list(devices if devices is not None else jax.devices())
    if seq_devices is None:
        seq_devices = len(devices) // (data_devices * model_devices)
    if model_devices > 1:
        return mesh_lib.create_mesh(
            [data_devices, model_devices, seq_devices],
            (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS, mesh_lib.SEQ_AXIS),
            devices)
    return mesh_lib.create_mesh(
        [data_devices, seq_devices],
        (mesh_lib.DATA_AXIS, mesh_lib.SEQ_AXIS), devices)


class SequenceParallelWrapper:
    """Train a MultiLayerNetwork containing SelfAttentionLayer(s) with
    [batch, time] sharded over a ("data", "seq") mesh. If the mesh ALSO
    carries a >1 "model" axis, parameters shard over it (the
    TensorParallelWrapper rule) and the ring shards attention HEADS over
    it too — full 3-D DP x TP x SP training from one wrapper."""

    def __init__(self, model, mesh: Optional[Mesh] = None):
        self.model = model
        self.mesh = mesh if mesh is not None else seq_parallel_mesh()
        if mesh_lib.SEQ_AXIS not in self.mesh.axis_names:
            raise ValueError(
                f"SequenceParallelWrapper needs a mesh with a "
                f"'{mesh_lib.SEQ_AXIS}' axis; got {self.mesh.axis_names}")
        self.seq_shards = int(self.mesh.shape[mesh_lib.SEQ_AXIS])
        self.data_shards = int(self.mesh.shape.get(mesh_lib.DATA_AXIS, 1))
        self.model_shards = int(self.mesh.shape.get(mesh_lib.MODEL_AXIS, 1))
        self._batch_axis = mesh_lib.DATA_AXIS \
            if mesh_lib.DATA_AXIS in self.mesh.axis_names \
            and self.data_shards > 1 else None
        self._head_axis = mesh_lib.MODEL_AXIS \
            if mesh_lib.MODEL_AXIS in self.mesh.axis_names \
            and self.model_shards > 1 else None
        self._step = None
        self._out_fn = None
        self._placed = False
        self._warned_pad = False
        self._warned_window = False

    def _ctx(self):
        return sequence_parallel(self.mesh, mesh_lib.SEQ_AXIS,
                                 self._batch_axis, self._head_axis)

    def _ensure_step(self):
        if self._step is None:
            # Own jit cache: the ring routing is decided when THIS jit
            # traces (inside _ctx), never touching the net's cached step.
            if self._head_axis is not None:
                # 3-D mode: reuse the tensor-parallel pinned-step helper
                # (params/opt layouts pinned, state unconstrained — see
                # jit_tp_step for why)
                from .tensor import jit_tp_step
                self._step = jit_tp_step(self.model)
            else:
                self._step = jax.jit(self.model._train_step_raw,
                                     donate_argnums=(0, 1, 2))

    def _place_model(self):
        net = self.model
        if self._head_axis is not None:
            # 3-D mode: the shared tensor-parallel placement policy
            from .tensor import place_model_tp
            place_model_tp(net, self.mesh, self.model_shards)
        else:
            net.params_tree = mesh_lib.replicate(self.mesh, net.params_tree)
            net.opt_state = mesh_lib.replicate(self.mesh, net.opt_state)
            net.state_tree = mesh_lib.replicate(self.mesh, net.state_tree)
            net._rng = mesh_lib.replicate(self.mesh, net._rng)
        self._placed = True

    def _shard_bt(self, a, time_sharded: bool, cast_dtype=None):
        """Place [batch, time, ...] (or [batch, ...]) arrays: batch over
        "data" (if the mesh has a >1 data axis), time over "seq"."""
        if a is None:
            return None
        a = jnp.asarray(a)
        if cast_dtype is not None and jnp.issubdtype(a.dtype, jnp.floating):
            a = a.astype(cast_dtype)
        axes = [self._batch_axis]
        if time_sharded and a.ndim >= 2:
            axes.append(mesh_lib.SEQ_AXIS)
        spec = P(*axes) if len(axes) > 1 else P(axes[0])
        # place_global (not raw device_put): on a multi-process mesh
        # device_put cannot address remote devices. Same contract as
        # TensorParallelWrapper._put_batch: every process feeds the
        # IDENTICAL global batch; each slices out its time/batch shards.
        return mesh_lib.place_global(a, NamedSharding(self.mesh, spec),
                                     self.mesh)

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128) -> "SequenceParallelWrapper":
        self.model._check_init()
        # pad_to_bucket OFF: this wrapper owns its tail padding (to the
        # data-axis multiple, not the bucket shape) and places batches
        # under the seq mesh itself, so generic device prefetch is also
        # skipped.
        self.model.fit(data, labels, epochs=epochs, batch_size=batch_size,
                       step_fn=self.fit_batch, pad_to_bucket=False,
                       prefetch_to_device=False)
        return self

    def fit_batch(self, ds) -> None:
        """One globally-synchronous step with batch x time sharded.
        Exactly the net's math: the only difference from single-device
        training is WHERE each time slice lives (+ f32 reassociation in
        the ring's online softmax). Accepts a DataSet for
        MultiLayerNetwork or a (Multi)DataSet for ComputationGraph.

        Delegates to the net's own batch dispatch with a sharded
        do_step (the TensorParallelWrapper / ParallelWrapper contract),
        so recurrent-carry reset and tBPTT windowing can never diverge
        from the single-device path."""
        net = self.model
        net._check_init()
        if not self._placed:
            self._place_model()
        self._ensure_step()
        # np.ndim/np.shape read attributes without materializing
        # device-resident arrays on the host
        if hasattr(net, "_pack"):  # ComputationGraph
            mds = net._coerce(ds)
            self._check_tbptt_windows(
                max((np.shape(f)[1] for f in mds.features
                     if np.ndim(f) == 3), default=0),
                windowing=all(np.ndim(l) == 3 for l in mds.labels))
            net.fit_batch(mds, do_step=self._sp_graph_step)
            return
        self._check_tbptt_windows(
            np.shape(ds.features)[1] if np.ndim(ds.features) == 3 else 0,
            windowing=np.ndim(ds.labels) == 3)
        net._fit_batch(ds, do_step=self._sp_step)

    def _check_tbptt_windows(self, T: int, windowing: bool) -> None:
        """If tBPTT windowing is about to run with a window length that
        doesn't divide the seq axis, EVERY window would fall back to
        dense attention — raise up front rather than silently training
        the whole run without sequence parallelism. (A short FINAL
        window is fine: it alone falls back, warned once.)"""
        from ..nn.conf.builders import BackpropType
        if self.model.conf.backprop_type != BackpropType.TRUNCATED_BPTT \
                or not windowing or not T:
            return
        L = self.model.conf.tbptt_fwd_length
        # the main window length is min(L, T): if IT doesn't divide,
        # every window of the run is dense (a T<=L run has exactly one
        # window of T steps). Only a short FINAL tail may fall back.
        if min(L, T) % self.seq_shards:
            raise ValueError(
                f"tBPTT window length {min(L, T)} "
                f"(min(tbptt_fwd_length={L}, T={T})) does not divide the "
                f"{self.seq_shards}-way seq axis: every tBPTT window "
                f"would fall back to dense attention; choose a window "
                f"length divisible by the seq axis")

    def _time_sharded_ok(self, t: int, windowed: bool) -> bool:
        """Whether a [., t, ...] window can ride the ring. A short final
        tBPTT window that doesn't divide the seq axis falls back to the
        dense path (warn once); a whole-sequence (non-windowed) batch
        raises instead — silent full-dense training is never the answer
        the user asked the wrapper for."""
        if t % self.seq_shards == 0:
            return True
        if not windowed:
            raise ValueError(
                f"time axis {t} must divide the {self.seq_shards}-way seq "
                f"axis")
        if not self._warned_window:
            log.warning(
                "tBPTT window of %d steps does not divide the %d-way seq "
                "axis; this window runs dense (sequence parallelism "
                "inactive for it)", t, self.seq_shards)
            self._warned_window = True
        return False

    def _sp_step(self, x, y, fmask, lmask) -> None:
        """do_step callback for MultiLayerNetwork._fit_batch: shard one
        (possibly tBPTT-windowed) batch over the mesh and commit."""
        net = self.model
        x = jnp.asarray(x)
        y = jnp.asarray(y)
        t = x.shape[1]
        # _fit_tbptt seeds the recurrent carry before each window;
        # the standard-BPTT path clears it — so a non-None carry is the
        # reliable "this is a tBPTT window" signal (conf.backprop_type
        # alone lies when rank-2 labels force the standard fallback).
        windowed = net._rnn_carry is not None
        time_ok = self._time_sharded_ok(t, windowed)
        pad = (-x.shape[0]) % self.data_shards
        if pad:
            # Short final batch (iterator tail): pad by repeating the
            # last example with ZERO label-mask weight — loss and
            # gradients match the unpadded batch exactly (the
            # ParallelWrapper._pad_lmask contract; attention is
            # per-example, so pad rows cannot leak into real rows).
            if not self._warned_pad:
                log.warning(
                    "Batch size %d not divisible by %d data shards; "
                    "padding with zero-loss-weight copies of the tail "
                    "example", x.shape[0], self.data_shards)
                self._warned_pad = True
            from .wrapper import pad_lmask_zero_weight, repeat_tail_rows
            lmask = pad_lmask_zero_weight(lmask, x.shape[0], pad)
            x, y, fmask = (repeat_tail_rows(x, pad),
                           repeat_tail_rows(y, pad),
                           repeat_tail_rows(fmask, pad))
            if windowed:
                # the recurrent carry was seeded at the UNPADDED batch
                # (net._fit_tbptt); pad it the same way or the merged
                # state shape-mismatches the padded window. Later
                # windows re-enter with the carry already padded (the
                # committed state keeps the padded batch), so only
                # unpadded-size leading axes grow.
                n0 = x.shape[0] - pad
                padc = lambda v: repeat_tail_rows(v, pad) \
                    if jnp.asarray(v).ndim and \
                    jnp.asarray(v).shape[0] == n0 else v
                net._rnn_carry = tuple(
                    {k: padc(v) for k, v in c.items()}
                    for c in net._rnn_carry)
        xs = self._shard_bt(x, time_ok, cast_dtype=net._dtype)
        ys = self._shard_bt(y, time_ok and y.ndim >= 3)
        fm = self._shard_bt(fmask, time_ok)
        # a [batch, 1] per-example weight mask has no time axis to shard
        lm = self._shard_bt(lmask, time_ok and lmask is not None and
                            jnp.asarray(lmask).ndim >= 2 and
                            jnp.asarray(lmask).shape[1] == t)
        self._run_sharded(xs, ys, fm, lm)

    def _run_sharded(self, *packed) -> None:
        """Swap in the ring-routed step for one commit (restored after),
        the sequence-parallel context held across the call so the first
        trace (and any retrace) sees it."""
        net = self.model
        orig = net._train_step_fn
        net._train_step_fn = self._step
        try:
            with self._ctx():
                net._run_and_commit(*packed, mesh=self.mesh)
        finally:
            net._train_step_fn = orig

    def _sp_graph_step(self, inputs, labels, fm, lm) -> None:
        """do_step callback for ComputationGraph.fit_batch: every rank-3
        dict entry gets [batch, time] sharded; rank-2 entries (static
        inputs, per-example masks) shard batch only. An indivisible
        tail batch pads with zero-loss-weight copies of the last
        example PER OUTPUT HEAD (the pad_lmask_zero_weight contract,
        symmetric with the MLN path — round-5 VERDICT item 8)."""
        net = self.model
        n = next(iter(inputs.values())).shape[0]
        pad = (-n) % self.data_shards
        if pad:
            if not self._warned_pad:
                log.warning(
                    "Batch size %d not divisible by %d data shards; "
                    "padding with zero-loss-weight copies of the tail "
                    "example on every output head", n, self.data_shards)
                self._warned_pad = True
            from .wrapper import pad_lmask_zero_weight, repeat_tail_rows
            rep = lambda a: repeat_tail_rows(a, pad)
            inputs = {k: rep(v) for k, v in inputs.items()}
            fm = {k: rep(v) for k, v in fm.items()}
            # every output head gets the shared zero-weight pad mask so
            # each head's loss numerator AND normalization match the
            # unpadded batch exactly
            lm = {name: jnp.asarray(
                pad_lmask_zero_weight(lm.get(name), n, pad))
                for name in labels}
            labels = {k: rep(v) for k, v in labels.items()}
            if net._rnn_carry is not None:  # tBPTT window: pad carry too
                padc = lambda v: rep(v) if jnp.asarray(v).ndim and \
                    jnp.asarray(v).shape[0] == n else v
                net._rnn_carry = {
                    name: {k: padc(v) for k, v in c.items()}
                    for name, c in net._rnn_carry.items()}
        t_axes = {a.shape[1] for a in inputs.values()
                  if hasattr(a, "ndim") and a.ndim == 3}
        # non-None carry == graph._fit_tbptt seeded a window (see
        # _sp_step); a short final window falls back to dense with a
        # warning, a whole-sequence indivisible time raises.
        windowed = net._rnn_carry is not None
        shardable = {t for t in t_axes
                     if self._time_sharded_ok(t, windowed)}

        def shard_dict(d, cast=None, is_mask=False):
            # rank-3 tensors carry [batch, time, features]; rank-2 MASK
            # entries carry [batch, time]. A rank-2 non-mask array whose
            # second dim merely EQUALS a sequence length is a feature
            # axis coincidence and must shard batch-only.
            def tsh(v):
                if v is None:
                    return False
                if np.ndim(v) == 3:
                    return np.shape(v)[1] in shardable
                return is_mask and np.ndim(v) == 2 and \
                    np.shape(v)[1] in shardable
            return {k: self._shard_bt(v, tsh(v), cast_dtype=cast)
                    for k, v in d.items()}

        self._run_sharded(shard_dict(inputs, cast=net._dtype),
                          shard_dict(labels), shard_dict(fm, is_mask=True),
                          shard_dict(lm, is_mask=True))

    def outputs(self, *features, features_masks=None):
        """Sequence-parallel ComputationGraph inference over ALL network
        inputs/outputs (time sharded like training; rank-2 static
        inputs shard batch only). Returns outputs in
        conf.network_outputs order — the graph.outputs() contract."""
        net = self.model
        if not hasattr(net, "_pack"):
            raise TypeError("outputs() is the ComputationGraph surface; "
                            "use output() for MultiLayerNetwork")
        net._check_init()
        if not self._placed:
            self._place_model()
        if len(features) == 1 and isinstance(features[0], (list, tuple)):
            features = tuple(features[0])
        if len(features) != len(net.conf.network_inputs):
            raise ValueError(
                f"Graph has {len(net.conf.network_inputs)} inputs, got "
                f"{len(features)}")
        t_axes = {np.shape(f)[1] for f in features if np.ndim(f) == 3}
        for t in t_axes:
            self._time_sharded_ok(t, windowed=False)  # raises if bad
        if self._out_fn is None:
            self._out_fn = jax.jit(
                lambda params, state, inputs, fms:
                net._walk(params, state, inputs, False, None, fms)[0])
        names = net.conf.network_inputs
        inputs = {nm: self._shard_bt(f, np.ndim(f) == 3,
                                     cast_dtype=net._dtype)
                  for nm, f in zip(names, features)}
        fms = {}
        if features_masks is not None:
            for nm, m in zip(names, features_masks):
                if m is not None:
                    fms[nm] = self._shard_bt(
                        m, np.ndim(m) == 2 and np.shape(m)[1] in t_axes)
        with self._ctx(), self.mesh:
            acts = self._out_fn(net.params_tree, net.state_tree, inputs,
                                fms)
        return [np.asarray(acts[nm]) for nm in net.conf.network_outputs]

    def output(self, x, features_mask=None):
        """Sequence-parallel inference through the same ring path (own
        jit so the net's cached forward stays dense). For a
        ComputationGraph, accepts one input or a list of inputs (time
        sharded like training) and returns the FIRST network output."""
        net = self.model
        net._check_init()
        if not self._placed:
            self._place_model()
        if hasattr(net, "_pack"):  # ComputationGraph
            feats = list(x) if isinstance(x, (list, tuple)) else [x]
            masks = None if features_mask is None else (
                list(features_mask) if isinstance(features_mask,
                                                  (list, tuple))
                else [features_mask])
            return self.outputs(*feats, features_masks=masks)[0]
        if self._out_fn is None:
            self._out_fn = jax.jit(
                lambda params, state, xx, fm:
                net._forward_pure(params, state, xx, False, None, fm)[0])
        xs = self._shard_bt(x, True, cast_dtype=net._dtype)
        fm = self._shard_bt(features_mask, True)
        with self._ctx(), self.mesh:
            out = self._out_fn(net.params_tree, net.state_tree, xs, fm)
        return np.asarray(out)
