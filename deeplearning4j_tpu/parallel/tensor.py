"""TensorParallelWrapper: train with parameters sharded over the mesh's
"model" axis (tensor parallelism), optionally combined with data
parallelism — GSPMD-style: annotate the PARAMETER shardings, jit the
same train step, and XLA partitions every matmul and inserts the
all-gather/reduce-scatter collectives (the Megatron recipe, derived by
the compiler instead of hand-written column/row layers).

BEYOND-parity scope: the reference's only strategy is data parallelism
(SURVEY.md §2.4); its parameters always fit one device. On TPU, models
larger than one chip's HBM are the norm and tensor parallelism over ICI
is the first resort ("How to Scale Your Model" recipe: pick a mesh,
annotate shardings, let XLA insert collectives).

Sharding rule (shape-based, uniform across params / updater state): for
every >=1-D floating tensor, shard the LAST dimension divisible by the
model-axis size (features-out for dense/attention/embedding weights —
column-parallel — and the packed 4H gate axis for LSTM, which divides
per-gate when H does). Scalars and indivisible tensors replicate.
Per-layer state (BN running stats) replicates: batch statistics are a
DATA-axis phenomenon.

Numerical parity with single-device training is exact up to f32
reassociation in the partitioned reductions
(tests/test_tensor_parallel.py)."""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as mesh_lib

log = logging.getLogger(__name__)


def tensor_parallel_mesh(model_devices: Optional[int] = None,
                         data_devices: int = 1, devices=None) -> Mesh:
    """A ("data", "model") mesh. Default: all devices on the model
    axis (pure tensor parallelism); data_devices > 1 gives DP x TP."""
    devices = list(devices if devices is not None else jax.devices())
    if model_devices is None:
        model_devices = len(devices) // data_devices
    return mesh_lib.create_mesh(
        [data_devices, model_devices],
        (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS), devices)


def model_param_spec(arr, model_shards: int) -> P:
    """The tensor-parallel sharding rule: shard the LAST dim divisible
    by the model-axis size over "model"; replicate otherwise (shared by
    TensorParallelWrapper and SequenceParallelWrapper's 3-D mode)."""
    shape = np.shape(arr)
    if len(shape) == 0 or not jnp.issubdtype(
            jnp.asarray(arr).dtype, jnp.floating):
        return P()
    for dim in range(len(shape) - 1, -1, -1):
        if shape[dim] >= model_shards and shape[dim] % model_shards == 0:
            spec = [None] * len(shape)
            spec[dim] = mesh_lib.MODEL_AXIS
            return P(*spec)
    return P()


def shard_params_over_model(tree, mesh: Mesh, model_shards: int):
    """Place a param/updater pytree under the model_param_spec rule.
    Multiprocess-safe via mesh_lib.place_global: every process holds the
    same full values (same-seed init or restore) and contributes its
    addressable shards — the model axis may span process boundaries."""
    return jax.tree_util.tree_map(
        lambda a: mesh_lib.place_global(
            a, NamedSharding(mesh, model_param_spec(a, model_shards)),
            mesh), tree)


def place_model_tp(net, mesh: Mesh, model_shards: int) -> None:
    """Tensor-parallel model placement: params/updater state shard over
    "model", layer state and rng replicate (shared by
    TensorParallelWrapper and SequenceParallelWrapper's 3-D mode so the
    placement policy cannot drift between them)."""
    net.params_tree = shard_params_over_model(net.params_tree, mesh,
                                              model_shards)
    net.opt_state = shard_params_over_model(net.opt_state, mesh,
                                            model_shards)
    net.state_tree = mesh_lib.replicate(mesh, net.state_tree)
    net._rng = mesh_lib.replicate(mesh, net._rng)


def jit_tp_step(net):
    """Jit the net's raw train step with ONLY the param/updater output
    shardings pinned (so GSPMD cannot drift the tensor-parallel layout
    step-over-step; donation reuses the buffers in place). State stays
    unconstrained ON PURPOSE: under tBPTT/rnn_time_step the state
    pytree gains recurrent-carry keys, and a pinned sharding tree built
    from the carry-free state_tree would structure-mismatch."""
    sh = lambda t: jax.tree_util.tree_map(lambda a: a.sharding, t)
    out_sh = (sh(net.params_tree), sh(net.opt_state),
              None, None, None, None)
    return jax.jit(net._train_step_raw, donate_argnums=(0, 1, 2),
                   out_shardings=out_sh)


class TensorParallelWrapper:
    """Drop-in TP/DP x TP trainer for MultiLayerNetwork and
    ComputationGraph (conv kernels [kh, kw, in, out] shard out-channels;
    XLA partitions the convolutions the same way it does matmuls)."""

    def __init__(self, model, mesh: Optional[Mesh] = None):
        self.model = model
        self.mesh = mesh if mesh is not None else tensor_parallel_mesh()
        if mesh_lib.MODEL_AXIS not in self.mesh.axis_names:
            raise ValueError(
                f"TensorParallelWrapper needs a mesh with a "
                f"'{mesh_lib.MODEL_AXIS}' axis; got {self.mesh.axis_names}")
        self.model_shards = int(self.mesh.shape[mesh_lib.MODEL_AXIS])
        self.data_shards = int(self.mesh.shape.get(mesh_lib.DATA_AXIS, 1))
        self._batch_axis = mesh_lib.DATA_AXIS \
            if mesh_lib.DATA_AXIS in self.mesh.axis_names \
            and self.data_shards > 1 else None
        self._step = None
        self._placed = False

    # -------------------------------------------------------------- sharding
    def _param_spec(self, arr) -> P:
        return model_param_spec(arr, self.model_shards)

    def _shard_tree(self, tree):
        return shard_params_over_model(tree, self.mesh, self.model_shards)

    def _place_model(self):
        place_model_tp(self.model, self.mesh, self.model_shards)
        self._placed = True

    def _ensure_step(self):
        if self._step is None:
            self._step = jit_tp_step(self.model)

    # ------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128) -> "TensorParallelWrapper":
        self.model._check_init()
        if self.data_shards > 1:
            # Reject an indivisible tail batch UP FRONT, not mid-epoch
            # with params already mutated.
            try:
                feats = data.features if hasattr(data, "features") else data
                if isinstance(feats, (list, tuple)):  # MultiDataSet
                    feats = feats[0]
                n = np.shape(feats)[0]
            except Exception:
                n = None  # iterator input: checked per batch
            if n is not None:
                tail = n % batch_size
                if tail and tail % self.data_shards:
                    raise ValueError(
                        f"final batch of {tail} examples does not divide "
                        f"the {self.data_shards}-way data axis; choose a "
                        f"batch size so every batch (incl. the tail) is "
                        f"divisible, or repartition")
        self.model.fit(data, labels, epochs=epochs, batch_size=batch_size,
                       step_fn=self.fit_batch)
        return self

    def fit_batch(self, ds) -> None:
        """One globally-synchronous step: batch sharded over "data",
        params over "model"; XLA partitions the matmuls/convs and
        inserts the activation collectives. Delegates to the net's own
        batch dispatch so recurrent-carry reset and tBPTT windowing can
        never diverge from the single-device path (the ParallelWrapper
        do_step contract)."""
        net = self.model
        net._check_init()
        if not self._placed:
            self._place_model()
        self._ensure_step()
        if hasattr(net, "_pack"):  # ComputationGraph
            net.fit_batch(net._coerce(ds), do_step=self._tp_graph_step)
            return
        net._fit_batch(ds, do_step=self._tp_step)

    def _put_batch(self, a, cast=None):
        """Place one batch-leading array: batch over "data" (floating
        inputs cast to the net dtype); shared by the MLN and graph
        steps so the placement rule can never diverge between them.
        Multiprocess contract: every process feeds the IDENTICAL global
        batch (place_global slices each process's shards out of it) —
        the per-process-partition convention belongs to the DP
        ParallelWrapper/MultiHostRunner path, not here."""
        if a is None:
            return None
        a = jnp.asarray(a)
        if cast is not None and jnp.issubdtype(a.dtype, jnp.floating):
            a = a.astype(cast)
        return mesh_lib.place_global(
            a, NamedSharding(self.mesh, P(self._batch_axis)), self.mesh)

    def _run_sharded(self, *packed) -> None:
        """Swap in the TP step for one commit (restored afterwards)."""
        net = self.model
        orig = net._train_step_fn
        net._train_step_fn = self._step
        try:
            net._run_and_commit(*packed, mesh=self.mesh)
        finally:
            net._train_step_fn = orig

    def _tp_graph_step(self, inputs, labels, fm, lm) -> None:
        net = self.model
        n = next(iter(inputs.values())).shape[0]
        if n % self.data_shards:
            raise ValueError(
                f"batch {n} must divide the {self.data_shards}-way data "
                f"axis")
        shard = lambda d, cast=None: {k: self._put_batch(v, cast)
                                      for k, v in d.items()}
        self._run_sharded(shard(inputs, cast=net._dtype), shard(labels),
                          shard(fm), shard(lm))

    def _tp_step(self, x, y, fmask, lmask) -> None:
        if np.shape(x)[0] % self.data_shards:
            raise ValueError(
                f"batch {np.shape(x)[0]} must divide the "
                f"{self.data_shards}-way data axis")
        self._run_sharded(self._put_batch(x, cast=self.model._dtype),
                          self._put_batch(y), self._put_batch(fmask),
                          self._put_batch(lmask))

    def materialize_local(self) -> None:
        """All-gather the model-sharded params/updater state back to
        replicated, process-local host arrays, so checkpoint save
        (ModelSerializer → host npz), single-device inference, or plain
        net.fit work afterwards. COLLECTIVE under a multiprocess mesh —
        every process must call in lockstep (the chief-only write
        happens AFTER this gather; parallel/multihost.py
        save_checkpoint contract). Training can resume sharded: the
        next fit_batch re-places (self._placed reset)."""
        net = self.model
        net.params_tree = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a)),
            mesh_lib.gather_replicated(net.params_tree, self.mesh))
        net.opt_state = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a)),
            mesh_lib.gather_replicated(net.opt_state, self.mesh))
        net.state_tree = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a)),
            mesh_lib.gather_replicated(net.state_tree, self.mesh))
        net._rng = jnp.asarray(np.asarray(
            mesh_lib.gather_replicated(net._rng, self.mesh)))
        self._placed = False
        self._step = None  # donated buffers were consumed; re-jit

    def param_shard_report(self) -> dict:
        """{param_path: partition spec} for every sharded (non-replicated)
        parameter — the observable evidence of tensor parallelism (tests
        assert on it so a silently-replicated run can't fake parity)."""
        if not self._placed:
            self._place_model()
        out = {}
        tree = self.model.params_tree
        items = tree.items() if isinstance(tree, dict) else enumerate(tree)
        for lname, pdict in items:
            for pname, arr in pdict.items():
                spec = arr.sharding.spec if hasattr(arr, "sharding") else None
                if spec and any(s is not None for s in spec):
                    out[f"{lname}.{pname}"] = tuple(spec)
        return out
