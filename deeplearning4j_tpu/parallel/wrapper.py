"""ParallelWrapper: synchronous data-parallel training over a device mesh.

Reference parity: parallelism/ParallelWrapper.java:48-264 — replicate the
model across N devices (one trainer thread each, DefaultTrainer.java),
round-robin minibatches, average parameters + updater state every
`averagingFrequency` iterations via Nd4j.averageAndPropagate (:219). The
reference's own test TestCompareParameterAveragingSparkVsSingleMachine
proves averaging at frequency 1 equals large-batch single-machine SGD.

TPU-native redesign: that equivalence is taken as the design license — the
N-replica thread zoo collapses into ONE jitted train step whose batch input
is sharded over the mesh's "data" axis. XLA inserts the gradient allreduce
(psum over ICI) exactly where the reference does a parameter average; params
stay replicated, so there is no separate "propagate" step and no thread
synchronization.

averaging_frequency > 1 (ParallelWrapper.java:417-424; Spark
ParameterAveragingTrainingMaster splits so each worker runs
`averagingFrequency` minibatches between syncs, :346-357) is local SGD:
params/updater-state/layer-state get a leading replica axis sharded over
"data", the per-replica step is the SAME jitted train step vmapped over
that axis (so each device takes independent local steps with zero
cross-device traffic), and every F steps a jitted mean-over-replicas +
re-broadcast performs the parameter average (XLA lowers it to an
allreduce over ICI — the averageAndPropagate analog).
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import mesh as mesh_lib
# The pad primitives live with the data pipeline (data/padding.py) so the
# pad-to-bucket iterator and the DP/SP wrappers share ONE contract; the
# historical names stay importable from here (sequence.py does).
from ..data.padding import pad_lmask_zero_weight, repeat_tail_rows  # noqa: F401
from ..nn.layers.recurrent import RECURRENT_CARRY_KEYS
from ..optimize import metrics as metrics_mod

log = logging.getLogger(__name__)


class ParallelWrapper:
    """Drop-in DP trainer for MultiLayerNetwork / ComputationGraph
    (reference ParallelWrapper.Builder surface, minus the thread zoo)."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 workers: Optional[int] = None,
                 averaging_frequency: int = 1,
                 prefetch_buffer: int = 8):
        self.model = model
        self.mesh = mesh if mesh is not None else \
            mesh_lib.data_parallel_mesh(workers)
        if mesh_lib.DATA_AXIS not in self.mesh.axis_names:
            raise ValueError(
                f"ParallelWrapper needs a mesh with a '{mesh_lib.DATA_AXIS}' "
                f"axis; got axes {self.mesh.axis_names}")
        self.data_shards = int(self.mesh.shape[mesh_lib.DATA_AXIS])
        # Multi-host: every process feeds its LOCAL data partition; the
        # global batch is their concatenation (Spark partition semantics).
        self.multiprocess = mesh_lib.is_multiprocess(self.mesh)
        if self.multiprocess:
            nproc = jax.process_count()
            if self.data_shards % nproc != 0 or self.data_shards < nproc:
                raise ValueError(
                    f"multi-host mesh: data axis size ({self.data_shards}) "
                    f"must be a positive multiple of the process count "
                    f"({nproc}) so every process owns an equal slice")
            self.local_shards = self.data_shards // nproc
        else:
            self.local_shards = self.data_shards
        if int(averaging_frequency) < 1:
            raise ValueError("averaging_frequency must be >= 1")
        self.averaging_frequency = int(averaging_frequency)
        self.prefetch_buffer = prefetch_buffer
        # Called with the model's iteration after every fit_batch — the
        # cluster health plane wires its step-progress watchdog here
        # (parallel/cluster_health.py), and it stays open for listeners
        # that need the wrapper (not net) step granularity.
        self.step_hooks = []
        self._warned_pad = False
        self._placed = False
        # ---- local-SGD (averaging_frequency > 1) machinery ----
        self._stacked = None          # (params, opt, state) with replica axis
        self._stacked_rngs = None
        self._synced_params_ref = None
        self._since_avg = 0
        self._stacked_step = None
        self._jit_helpers = None

    # ------------------------------------------------------------------ build
    @staticmethod
    def builder(model) -> "ParallelWrapperBuilder":
        return ParallelWrapperBuilder(model)

    def _place_model(self):
        """Replicate params/opt/state across the mesh once (the reference
        clones the model per device at zoo creation, ParallelWrapper:460).
        Multi-host, this is the broadcast-NetBroadcastTuple analog: every
        process holds identical values (same-seed init or restore) and the
        assembled global arrays are replicated over all devices."""
        net = self.model
        net.params_tree = mesh_lib.replicate(self.mesh, net.params_tree)
        net.opt_state = mesh_lib.replicate(self.mesh, net.opt_state)
        net.state_tree = mesh_lib.replicate(self.mesh, net.state_tree)
        net._rng = mesh_lib.replicate(self.mesh, net._rng)
        self._placed = True

    def _check_local_divisible(self, n: int):
        """Multi-host SPMD requires every process to compile and run the
        SAME program — per-process zero-weight pad masks could differ
        between processes, so non-divisible local batches are rejected
        (the reference repartitions to balance, BalancedPartitioner)."""
        if n % self.local_shards != 0:
            raise ValueError(
                f"multi-host training requires the per-process batch ({n}) "
                f"to be divisible by the process-local shard count "
                f"({self.local_shards}); repartition your data")

    def _shard_arr(self, a, cast_dtype=None):
        if a is None:
            return None
        if self.multiprocess:
            a = np.asarray(a)
            self._check_local_divisible(a.shape[0])
            if cast_dtype is not None and a.dtype.kind == "f":
                a = a.astype(cast_dtype)
            return mesh_lib.place(a, mesh_lib.batch_sharded(self.mesh),
                                  self.mesh)
        if isinstance(a, jax.Array) and a.shape[0] % self.data_shards == 0:
            # Already device-resident and evenly divisible: reshard
            # device-to-device, never touching the host.
            if cast_dtype is not None and jnp.issubdtype(a.dtype, jnp.floating):
                a = a.astype(cast_dtype)
            return jax.device_put(a, mesh_lib.batch_sharded(self.mesh))
        a = np.asarray(a)
        if cast_dtype is not None and a.dtype.kind == "f":
            a = a.astype(cast_dtype)
        padded, _ = mesh_lib.pad_batch_to_multiple(a, self.data_shards)
        return jax.device_put(padded, mesh_lib.batch_sharded(self.mesh))

    def _pad_lmask(self, lmask, n: int):
        """Zero-weight labels mask covering `pad` appended rows, constructed
        so the LOSS (numerator and normalization) exactly matches
        single-device training on the original batch:
          * no user mask  -> ones (n,1) + zero pad rows; the rank-2 mask
            path divides by sum(mask) = n, the unpadded mean.
          * rank-1 user mask (per-example weights) -> zero-padded and
            scaled by padded_n/n; the rank-1 mean path then yields
            sum(sa*m)/n, the unpadded value (exact by linearity).
          * rank>=2 user mask -> zero pad rows; sum(mask) is unchanged.
        Caveat (hence the warning): pad rows still traverse the FORWARD
        pass, so batch-statistics state (BatchNormalization train-mode
        mean/var and committed running stats) and shape-dependent dropout
        draws include them — use divisible batch sizes for bit-exact
        equivalence on BN/dropout models."""
        pad = (-n) % self.data_shards
        if pad == 0:
            return lmask
        if not self._warned_pad:
            log.warning(
                "Batch size %d not divisible by %d data shards; padding with "
                "zero-loss-weight copies of the tail example. Loss/gradients "
                "match single-device exactly, but BatchNorm batch statistics "
                "and dropout draws include the pad rows — use divisible "
                "batch sizes for bit-exact equivalence", n, self.data_shards)
            self._warned_pad = True
        return pad_lmask_zero_weight(lmask, n, pad)

    # -------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128) -> "ParallelWrapper":
        """Reuses the single-device epoch/listener loop with the sharded
        step substituted, so loop semantics can never diverge."""
        self.model._check_init()
        # Device prefetch stages batches already sharded over the mesh
        # (device_put with the batch NamedSharding on the producer
        # thread); _shard_arr then sees a correctly-sharded jax.Array
        # and passes it through without a host round-trip. Indivisible
        # ragged batches bypass staging (batch_divisor) and take the
        # host-side zero-weight pad path as before. Multi-host meshes
        # keep host feeding: per-process placement happens inside
        # _shard_arr and cannot run on a producer thread safely.
        prefetch = dict(prefetch_to_device=not self.multiprocess,
                        prefetch_sharding=None if self.multiprocess
                        else mesh_lib.batch_sharded(self.mesh),
                        prefetch_divisor=self.data_shards)
        if hasattr(self.model, "_pack"):  # ComputationGraph
            self.model.fit(data, labels, epochs=epochs,
                           batch_size=batch_size, step_fn=self.fit_batch,
                           **prefetch)
        else:
            self.model.fit(data, labels, epochs=epochs, batch_size=batch_size,
                           async_queue_size=self.prefetch_buffer,
                           step_fn=self.fit_batch, **prefetch)
        self.finalize()
        return self

    def fit_batch(self, ds) -> None:
        """One DP step. With averaging_frequency == 1 this is a globally-
        synchronous sharded step (tBPTT windowing included, via the net's
        own dispatch). With frequency > 1 it is one LOCAL step per replica
        (see module docstring). Accepts a DataSet for MultiLayerNetwork or
        a MultiDataSet/DataSet for ComputationGraph."""
        net = self.model
        if self.averaging_frequency > 1:
            self._local_round(ds)
            self._fire_step_hooks()
            return
        metrics_mod.registry().counter(
            "data_parallel_steps_total",
            "ParallelWrapper optimizer steps by mode"
            ).labels(mode="sync", workers=str(self.data_shards)).inc()
        if not self._placed:
            net._check_init()
            self._place_model()
        if hasattr(net, "_pack"):  # ComputationGraph
            # reuse the graph's own dispatch (tBPTT windowing included)
            # with the sharded step substituted — the MLN do_step pattern
            net.fit_batch(net._coerce(ds), do_step=self._sync_graph_step)
            self._fire_step_hooks()
            return
        net._fit_batch(ds, do_step=self._sync_step)
        self._fire_step_hooks()

    def _fire_step_hooks(self):
        """Report the model's iteration to every registered hook. The
        int() here reads a host-side counter (net.iteration is python),
        so no device sync is added to the step path."""
        if not self.step_hooks:
            return
        it = int(self.model.iteration)
        for h in list(self.step_hooks):
            h(it)

    def _sync_graph_step(self, inputs, labels, fm, lm):
        """Sharded analog of ComputationGraph._run_and_commit for one
        (possibly tBPTT-windowed) packed batch."""
        net = self.model
        n = next(iter(inputs.values())).shape[0]
        if self.multiprocess:
            self._check_local_divisible(n)
        elif n % self.data_shards != 0:
            if net._rnn_carry is not None:
                # the recurrent carry is sized to the true batch; padding
                # the data but not the carry would shape-mismatch in jit
                raise ValueError(
                    f"truncated-BPTT batch size {n} must divide the "
                    f"{self.data_shards}-way data mesh")
            lm = {name: self._pad_lmask(lm.get(name), n) for name in labels}
        shard = lambda d: {k: self._shard_arr(v) for k, v in d.items()}
        net._run_and_commit(shard(inputs), shard(labels), shard(fm),
                            shard(lm), mesh=self.mesh)

    def _prep_graph_batch(self, ds):
        """Pack a (Multi)DataSet for the graph and zero-weight any pad rows
        (shared by the sync and local-SGD paths so the padding rule can
        never diverge between them)."""
        net = self.model
        inputs, labels, fm, lm = net._pack(net._coerce(ds))
        n = next(iter(inputs.values())).shape[0]
        if self.multiprocess:
            self._check_local_divisible(n)
        elif n % self.data_shards != 0:
            # Every output head gets a zero-weight mask over pad rows.
            lm = {name: self._pad_lmask(lm.get(name), n) for name in labels}
        return inputs, labels, fm, lm, n

    def _sync_step(self, x, y, fmask, lmask) -> None:
        """Sharded analog of MultiLayerNetwork._do_step: shard the inputs
        over the mesh's data axis, then delegate invoke+commit to the net
        so the commit tail can never diverge from the single-device path."""
        net = self.model
        if self.multiprocess:
            self._check_local_divisible(x.shape[0])
        elif x.shape[0] % self.data_shards != 0:
            if net._rnn_carry is not None:
                raise ValueError(
                    f"truncated-BPTT batch size {x.shape[0]} must divide "
                    f"the {self.data_shards}-way data mesh")
            lmask = self._pad_lmask(lmask, x.shape[0])
        net._run_and_commit(
            self._shard_arr(x, cast_dtype=net._dtype), self._shard_arr(y),
            self._shard_arr(fmask), self._shard_arr(lmask), mesh=self.mesh)

    # ----------------------------------------------------- local SGD (freq>1)
    def _mark_local_step(self):
        """Telemetry for one local-SGD round: every replica took one
        independent step (worker-labeled, the reference's per-trainer
        iteration counters), and the nets' commit paths were bypassed so
        the global iteration counter is bumped here."""
        reg = metrics_mod.registry()
        c = reg.counter("data_parallel_worker_steps_total",
                        "Local-SGD steps per replica (worker-labeled)")
        for w in range(self.data_shards):
            c.labels(worker=str(w)).inc()
        reg.counter("data_parallel_steps_total",
                    "ParallelWrapper optimizer steps by mode"
                    ).labels(mode="local_sgd",
                             workers=str(self.data_shards)).inc()
        metrics_mod.record_train_step(1)

    def _mark_average(self):
        metrics_mod.registry().counter(
            "data_parallel_averages_total",
            "Parameter averages across replicas (averageAndPropagate)"
            ).labels(workers=str(self.data_shards)).inc()

    def _build_local_machinery(self, n_data_args: int):
        """Jitted helpers for the replica-stacked representation."""
        from jax.sharding import NamedSharding, PartitionSpec
        W = self.data_shards
        stacked_sh = NamedSharding(self.mesh, PartitionSpec(mesh_lib.DATA_AXIS))
        tmap = jax.tree_util.tree_map

        # Per-replica local step: the net's own jitted step, vmapped over
        # the replica axis. iteration is shared (in_axes None); params/opt/
        # state/rng/data are per-replica (axis 0, sharded over "data"), so
        # each device computes its replica with no collective ops.
        in_axes = (0, 0, 0, None, 0) + (0,) * n_data_args
        self._stacked_step = jax.jit(jax.vmap(
            self.model._train_step_fn, in_axes=in_axes,
            out_axes=(0, 0, 0, None, 0, 0)))

        def stack(t):  # replicate net trees onto the replica axis
            return tmap(lambda a: jnp.broadcast_to(a[None], (W,) + a.shape), t)

        def avg_one(a):
            m = jnp.mean(a, axis=0) if jnp.issubdtype(a.dtype, jnp.floating) \
                else a[0]
            return jnp.broadcast_to(m[None], a.shape)

        def avg(t):  # averageAndPropagate: mean over replicas, re-broadcast
            return tmap(avg_one, t)

        def _avg_keep(st):
            return {k: (v if k in RECURRENT_CARRY_KEYS else avg_one(v))
                    for k, v in st.items()}

        def avg_keep_carry(t):
            # tBPTT variant: params/opt/BN-stats average, but each
            # replica's recurrent carry (h/c) belongs to ITS data shard
            # and must never be averaged across replicas. State is a
            # tuple of dicts for MultiLayerNetwork, a dict of dicts for
            # ComputationGraph.
            params, opt, state = t
            if isinstance(state, dict):
                state = {name: _avg_keep(st) for name, st in state.items()}
            else:
                state = tuple(_avg_keep(st) for st in state)
            return tmap(avg_one, params), tmap(avg_one, opt), state

        def _strip(st):
            return {k: v for k, v in st.items()
                    if k not in RECURRENT_CARRY_KEYS}

        def strip_carry(state):
            if isinstance(state, dict):
                return {name: _strip(st) for name, st in state.items()}
            return tuple(_strip(st) for st in state)

        def take0(t):  # replicas are equal post-average; unstack view
            return tmap(lambda a: a[0], t)

        # take0 outputs replicate so they stay addressable on every process
        # (replica 0's device may be remote under multi-host).
        self._jit_helpers = {
            "stack": jax.jit(stack, out_shardings=stacked_sh),
            "avg": jax.jit(avg, out_shardings=stacked_sh),
            "avg_keep_carry": jax.jit(avg_keep_carry,
                                      out_shardings=stacked_sh),
            "strip_carry": jax.jit(strip_carry, out_shardings=stacked_sh),
            "take0": jax.jit(take0,
                             out_shardings=mesh_lib.replicated(self.mesh)),
            "split_rngs": jax.jit(lambda k: jax.random.split(k, W),
                                  out_shardings=stacked_sh),
        }

    def _ensure_stacked(self, n_data_args: int):
        net = self.model
        if self._stacked is not None:
            # Restack if the net's params were swapped behind our back
            # (checkpoint restore, direct net.fit, transfer surgery...):
            # the cached replica stack would silently discard them.
            if net.params_tree is self._synced_params_ref:
                return
            self._stacked = None
        if self._stacked_step is None:
            self._build_local_machinery(n_data_args)
        if not self._placed:
            self._place_model()  # stack-jit inputs must be mesh-global
        h = self._jit_helpers
        self._stacked = h["stack"]((net.params_tree, net.opt_state,
                                    self._net_state_tree()))
        self._synced_params_ref = net.params_tree
        self._stacked_rngs = h["split_rngs"](net._rng)
        self._since_avg = 0

    def _net_state_tree(self):
        net = self.model
        return net._merged_state() if hasattr(net, "_merged_state") \
            else net.state_tree

    def _stack_data(self, a, n: int):
        """Pad (repeating the tail row) + reshape (n,...) → (W, n/W, ...).
        Device-resident arrays are padded/reshaped with jnp ops so they
        never round-trip through host memory."""
        if a is None:
            return None
        W = self.data_shards
        if self.multiprocess:
            # Local rows → (local_shards, chunk, ...); the global replica
            # axis (W rows) is assembled across processes.
            a = np.asarray(a)
            self._check_local_divisible(a.shape[0])
            stacked = a.reshape((self.local_shards, -1) + a.shape[1:])
            return mesh_lib.place(stacked, mesh_lib.batch_sharded(self.mesh),
                                  self.mesh)
        if isinstance(a, jax.Array):
            pad = (-a.shape[0]) % W
            if pad:
                a = jnp.concatenate(
                    [a, jnp.broadcast_to(a[-1:], (pad,) + a.shape[1:])], 0)
            stacked = a.reshape((W, -1) + a.shape[1:])
        else:
            a = np.asarray(a)
            padded, _ = mesh_lib.pad_batch_to_multiple(a, W)
            stacked = padded.reshape((W, -1) + padded.shape[1:])
        return jax.device_put(stacked, mesh_lib.batch_sharded(self.mesh))

    def _local_round(self, ds) -> None:
        """One local step on every replica; average every F-th round.
        Mapping to the reference: each replica plays one DefaultTrainer /
        Spark worker, its shard of this batch is the worker's minibatch,
        and F rounds between averages = averagingFrequency iterations
        (ParallelWrapper.java:417-424)."""
        net = self.model
        net._check_init()
        if hasattr(net, "_pack"):  # ComputationGraph
            from ..nn.conf.builders import BackpropType
            mds = net._coerce(ds)
            if net.conf.backprop_type == BackpropType.TRUNCATED_BPTT:
                # np.ndim reads metadata — no d2h copy of device batches
                if any(np.ndim(f) == 3 for f in mds.features) and \
                        all(np.ndim(l) == 3 for l in mds.labels):
                    self._local_round_tbptt_graph(mds)
                    return
                # mirror the single-device warn-once fallback
                # (graph.py fit_batch): rank-2 labels run standard BPTT
                if not getattr(net, "_warned_tbptt_labels", False):
                    log.warning(
                        "Truncated BPTT requires rank-3 features and "
                        "labels; using standard BPTT")
                    net._warned_tbptt_labels = True
            inputs, labels, fm, lm, n = self._prep_graph_batch(ds)
            data = tuple({k: self._stack_data(v, n) for k, v in d.items()}
                         for d in (inputs, labels, fm, lm))
        else:
            from ..nn.conf.builders import BackpropType
            if net.conf.backprop_type == BackpropType.TRUNCATED_BPTT and \
                    np.ndim(ds.features) == 3 and \
                    np.ndim(ds.labels) == 3:
                self._local_round_tbptt(ds)
                return
            x, y = ds.features, ds.labels
            fmask, lmask = ds.features_mask, ds.labels_mask
            n = np.shape(x)[0]
            if self.multiprocess:
                self._check_local_divisible(n)
            elif n % self.data_shards != 0:
                lmask = self._pad_lmask(lmask, n)
            x = np.asarray(x)
            if x.dtype.kind == "f":
                x = x.astype(np.dtype(net._dtype))
            data = tuple(self._stack_data(a, n)
                         for a in (x, y, fmask, lmask))
        self._ensure_stacked(len(data))
        params, opt, state = self._stacked
        with self.mesh:
            (params, opt, state, _, self._stacked_rngs,
             losses) = self._stacked_step(
                params, opt, state, jnp.asarray(net.iteration, jnp.int32),
                self._stacked_rngs, *data)
        self._stacked = (params, opt, state)
        self._since_avg += 1
        net.iteration += 1
        net.score_value = jnp.mean(losses)
        self._mark_local_step()
        if self._since_avg >= self.averaging_frequency:
            self._stacked = self._jit_helpers["avg"](self._stacked)
            self._since_avg = 0
            self._mark_average()
        # Sync the canonical trees every round (post-average they hold the
        # averaged values; mid-window, replica 0's — the per-worker view a
        # reference listener would see), so Checkpoint/Evaluative listeners
        # never observe parameters stale by a whole averaging window.
        self._sync_net_from_stacked()
        for lst in net.listeners:
            lst.iteration_done(net, net.iteration)

    def _local_round_tbptt(self, ds) -> None:
        """Local SGD over a truncated-BPTT batch (MultiLayerNetwork):
        every replica runs the SAME window schedule on its shard of the
        batch, with the recurrent carry riding the replica-stacked state
        between windows — one optimizer step per window per replica,
        averaging every F windows (matching how a reference worker would
        count its tBPTT iterations)."""
        net = self.model
        x = np.asarray(ds.features)
        n = x.shape[0]
        if self.multiprocess:
            self._check_local_divisible(n)
        elif n % self.data_shards != 0:
            raise ValueError(
                f"truncated-BPTT batch size {n} must divide the "
                f"{self.data_shards}-way data mesh")
        chunk = (n // self.local_shards if self.multiprocess
                 else n // self.data_shards)
        # seed the carry at per-replica chunk size, then (re)stack the
        # state so every replica starts this batch with zero h/c
        net.rnn_clear_previous_state()
        net._seed_recurrent_states(chunk)
        self._ensure_stacked(4)
        params, opt, _ = self._stacked
        with self.mesh:
            state = self._jit_helpers["stack"](net._merged_state())
        self._stacked = (params, opt, state)
        T = x.shape[1]
        L = net.conf.tbptt_fwd_length
        y = np.asarray(ds.labels)
        fmask = None if ds.features_mask is None \
            else np.asarray(ds.features_mask)
        lmask = None if ds.labels_mask is None \
            else np.asarray(ds.labels_mask)
        xc = x.astype(np.dtype(net._dtype)) if x.dtype.kind == "f" else x
        for start in range(0, T, L):
            end = min(start + L, T)
            data = tuple(
                self._stack_data(None if a is None else a[:, start:end], n)
                for a in (xc, y, fmask, lmask))
            params, opt, state = self._stacked
            with self.mesh:
                (params, opt, state, _, self._stacked_rngs,
                 losses) = self._stacked_step(
                    params, opt, state,
                    jnp.asarray(net.iteration, jnp.int32),
                    self._stacked_rngs, *data)
            self._stacked = (params, opt, state)
            self._since_avg += 1
            net.iteration += 1
            net.score_value = jnp.mean(losses)
            self._mark_local_step()
            if self._since_avg >= self.averaging_frequency:
                self._stacked = self._jit_helpers["avg_keep_carry"](
                    self._stacked)
                self._since_avg = 0
                self._mark_average()
            self._sync_net_from_stacked()
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration)
        # batch over: drop the carry (net + next batch reseeds the stack)
        net.rnn_clear_previous_state()
        params, opt, state = self._stacked
        with self.mesh:
            self._stacked = (params, opt,
                             self._jit_helpers["strip_carry"](state))

    def _local_round_tbptt_graph(self, mds) -> None:
        """Local SGD over a truncated-BPTT batch for ComputationGraph —
        the _local_round_tbptt analog (reference behavior: Spark workers
        train tBPTT graphs between averages,
        ParameterAveragingTrainingMaster.java:346-357). Every replica
        runs the SAME window schedule on its shard with the recurrent
        carry riding the replica-stacked state; one optimizer step per
        window per replica; params/opt/non-carry state average every F
        windows. Window slicing mirrors ComputationGraph._fit_tbptt
        (rank-2 static inputs pass whole into every window)."""
        net = self.model
        n = np.shape(mds.features[0])[0]
        if self.multiprocess:
            self._check_local_divisible(n)
        elif n % self.data_shards != 0:
            raise ValueError(
                f"truncated-BPTT batch size {n} must divide the "
                f"{self.data_shards}-way data mesh")
        chunk = (n // self.local_shards if self.multiprocess
                 else n // self.data_shards)
        # Seed a CHUNK-sized carry and stack it per replica before
        # handing control to the graph's own window loop (each replica's
        # carry covers its shard of the batch).
        net.rnn_clear_previous_state()
        net._seed_recurrent_states(chunk)
        self._ensure_stacked(4)
        params, opt, _ = self._stacked
        with self.mesh:
            state = self._jit_helpers["stack"](net._merged_state())
        self._stacked = (params, opt, state)
        net.rnn_clear_previous_state()

        def window_step(inputs, labels, fm, lm):
            # one stacked local step for this window across all replicas
            data = tuple({k: self._stack_data(v, n) for k, v in d.items()}
                         for d in (inputs, labels, fm, lm))
            params, opt, state = self._stacked
            with self.mesh:
                (params, opt, state, _, self._stacked_rngs,
                 losses) = self._stacked_step(
                    params, opt, state,
                    jnp.asarray(net.iteration, jnp.int32),
                    self._stacked_rngs, *data)
            self._stacked = (params, opt, state)
            self._since_avg += 1
            net.iteration += 1
            net.score_value = jnp.mean(losses)
            self._mark_local_step()
            if self._since_avg >= self.averaging_frequency:
                self._stacked = self._jit_helpers["avg_keep_carry"](
                    self._stacked)
                self._since_avg = 0
                self._mark_average()
            self._sync_net_from_stacked()
            for lst in net.listeners:
                lst.iteration_done(net, net.iteration)

        # Reuse the graph's OWN window slicing (_fit_tbptt's documented
        # do_step contract) so the schedule can never drift from the
        # single-device path. Its batch-sized net-carry seeding is
        # irrelevant here (window_step reads only the stacked state) and
        # it clears the net carry when the batch ends.
        net._fit_tbptt(mds, do_step=window_step)
        # batch over: drop the carry (next batch reseeds the stack)
        params, opt, state = self._stacked
        with self.mesh:
            self._stacked = (params, opt,
                             self._jit_helpers["strip_carry"](state))

    def _sync_net_from_stacked(self):
        net = self.model
        (params, opt, state), rng = self._jit_helpers["take0"](
            (self._stacked, self._stacked_rngs))
        net.params_tree, net.opt_state = params, opt
        if hasattr(net, "_commit_state"):
            net._commit_state(state)
        else:
            net.state_tree = state
        net._rng = rng
        self._synced_params_ref = net.params_tree

    def _average_and_sync(self):
        """Average params/updater-state/layer-state across replicas and
        refresh the net's canonical (unstacked) trees."""
        self._stacked = self._jit_helpers["avg"](self._stacked)
        self._since_avg = 0
        self._mark_average()
        self._sync_net_from_stacked()

    def finalize(self):
        """Flush pending local steps: average if mid-window and sync the
        net. The reference averages once more when fit() drains
        (ParallelWrapper.java:231-263)."""
        if self._stacked is not None and self._since_avg > 0:
            self._average_and_sync()

    # --------------------------------------------------------------- shutdown
    def shutdown(self):
        """Reference ParallelWrapper.shutdown(): averages any pending local
        window, then forgets placement. No threads were harmed in this
        design."""
        self.finalize()
        self._placed = False
        self._stacked = None
        self._stacked_rngs = None


class ParallelWrapperBuilder:
    """Fluent builder mirroring reference ParallelWrapper.Builder."""

    def __init__(self, model):
        self._model = model
        self._workers = None
        self._avg_freq = 1
        self._prefetch = 8
        self._mesh = None

    def workers(self, n: int):
        self._workers = int(n)
        return self

    def averaging_frequency(self, n: int):
        self._avg_freq = int(n)
        return self

    def prefetch_buffer(self, n: int):
        self._prefetch = int(n)
        return self

    def mesh(self, m: Mesh):
        self._mesh = m
        return self

    def build(self) -> ParallelWrapper:
        return ParallelWrapper(self._model, mesh=self._mesh,
                               workers=self._workers,
                               averaging_frequency=self._avg_freq,
                               prefetch_buffer=self._prefetch)
