"""ParallelWrapper: synchronous data-parallel training over a device mesh.

Reference parity: parallelism/ParallelWrapper.java:48-264 — replicate the
model across N devices (one trainer thread each, DefaultTrainer.java),
round-robin minibatches, average parameters + updater state every
`averagingFrequency` iterations via Nd4j.averageAndPropagate (:219). The
reference's own test TestCompareParameterAveragingSparkVsSingleMachine
proves averaging at frequency 1 equals large-batch single-machine SGD.

TPU-native redesign: that equivalence is taken as the design license — the
N-replica thread zoo collapses into ONE jitted train step whose batch input
is sharded over the mesh's "data" axis. XLA inserts the gradient allreduce
(psum over ICI) exactly where the reference does a parameter average; params
stay replicated, so there is no separate "propagate" step and no thread
synchronization. averaging_frequency > 1 (local SGD, reference behavioral
parity for infrequent averaging) is not implemented yet and is rejected
loudly rather than silently ignored.
"""
from __future__ import annotations

import logging
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from . import mesh as mesh_lib

log = logging.getLogger(__name__)


class ParallelWrapper:
    """Drop-in DP trainer for MultiLayerNetwork / ComputationGraph
    (reference ParallelWrapper.Builder surface, minus the thread zoo)."""

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 workers: Optional[int] = None,
                 averaging_frequency: int = 1,
                 prefetch_buffer: int = 8):
        self.model = model
        self.mesh = mesh if mesh is not None else \
            mesh_lib.data_parallel_mesh(workers)
        if mesh_lib.DATA_AXIS not in self.mesh.axis_names:
            raise ValueError(
                f"ParallelWrapper needs a mesh with a '{mesh_lib.DATA_AXIS}' "
                f"axis; got axes {self.mesh.axis_names}")
        self.data_shards = int(self.mesh.shape[mesh_lib.DATA_AXIS])
        if int(averaging_frequency) != 1:
            raise NotImplementedError(
                "averaging_frequency > 1 (local SGD) is not implemented yet; "
                "synchronous DP (frequency 1) is the reference-equivalent "
                "default per TestCompareParameterAveragingSparkVsSingleMachine")
        self.averaging_frequency = 1
        self.prefetch_buffer = prefetch_buffer
        self._warned_pad = False
        self._placed = False

    # ------------------------------------------------------------------ build
    @staticmethod
    def builder(model) -> "ParallelWrapperBuilder":
        return ParallelWrapperBuilder(model)

    def _place_model(self):
        """Replicate params/opt/state across the mesh once (the reference
        clones the model per device at zoo creation, ParallelWrapper:460)."""
        net = self.model
        net.params_tree = mesh_lib.replicate(self.mesh, net.params_tree)
        net.opt_state = mesh_lib.replicate(self.mesh, net.opt_state)
        net.state_tree = mesh_lib.replicate(self.mesh, net.state_tree)
        self._placed = True

    def _shard_arr(self, a, cast_dtype=None):
        if a is None:
            return None
        if isinstance(a, jax.Array) and a.shape[0] % self.data_shards == 0:
            # Already device-resident and evenly divisible: reshard
            # device-to-device, never touching the host.
            if cast_dtype is not None and jnp.issubdtype(a.dtype, jnp.floating):
                a = a.astype(cast_dtype)
            return jax.device_put(a, mesh_lib.batch_sharded(self.mesh))
        a = np.asarray(a)
        if cast_dtype is not None and a.dtype.kind == "f":
            a = a.astype(cast_dtype)
        padded, _ = mesh_lib.pad_batch_to_multiple(a, self.data_shards)
        return jax.device_put(padded, mesh_lib.batch_sharded(self.mesh))

    def _pad_lmask(self, lmask, n: int):
        """Zero-weight labels mask covering `pad` appended rows, constructed
        so the LOSS (numerator and normalization) exactly matches
        single-device training on the original batch:
          * no user mask  -> ones (n,1) + zero pad rows; the rank-2 mask
            path divides by sum(mask) = n, the unpadded mean.
          * rank-1 user mask (per-example weights) -> zero-padded and
            scaled by padded_n/n; the rank-1 mean path then yields
            sum(sa*m)/n, the unpadded value (exact by linearity).
          * rank>=2 user mask -> zero pad rows; sum(mask) is unchanged.
        Caveat (hence the warning): pad rows still traverse the FORWARD
        pass, so batch-statistics state (BatchNormalization train-mode
        mean/var and committed running stats) and shape-dependent dropout
        draws include them — use divisible batch sizes for bit-exact
        equivalence on BN/dropout models."""
        pad = (-n) % self.data_shards
        if pad == 0:
            return lmask
        if not self._warned_pad:
            log.warning(
                "Batch size %d not divisible by %d data shards; padding with "
                "zero-loss-weight copies of the tail example. Loss/gradients "
                "match single-device exactly, but BatchNorm batch statistics "
                "and dropout draws include the pad rows — use divisible "
                "batch sizes for bit-exact equivalence", n, self.data_shards)
            self._warned_pad = True
        if lmask is None:
            m = np.ones((n, 1), np.float32)
        else:
            m = np.asarray(lmask, np.float32)
        zeros = np.zeros((pad,) + m.shape[1:], m.dtype)
        out = np.concatenate([m, zeros], axis=0)
        if out.ndim == 1:
            # Rank-1 masks take the mean-over-batch loss path; rescale so
            # mean over padded_n equals the unpadded mean over n.
            out = out * (out.shape[0] / float(n))
        return out

    # -------------------------------------------------------------------- fit
    def fit(self, data, labels=None, *, epochs: int = 1,
            batch_size: int = 128) -> "ParallelWrapper":
        """Reuses the single-device epoch/listener loop with the sharded
        step substituted, so loop semantics can never diverge."""
        self.model._check_init()
        if hasattr(self.model, "_pack"):  # ComputationGraph
            self.model.fit(data, labels, epochs=epochs,
                           batch_size=batch_size, step_fn=self.fit_batch)
        else:
            self.model.fit(data, labels, epochs=epochs, batch_size=batch_size,
                           async_queue_size=self.prefetch_buffer,
                           step_fn=self.fit_batch)
        return self

    def fit_batch(self, ds) -> None:
        """One globally-synchronous DP step (tBPTT windowing included, via
        the net's own dispatch with our sharded step substituted). Accepts a
        DataSet for MultiLayerNetwork or a MultiDataSet/DataSet for
        ComputationGraph."""
        net = self.model
        if not self._placed:
            net._check_init()
            self._place_model()
        if hasattr(net, "_pack"):  # ComputationGraph
            inputs, labels, fm, lm = net._pack(net._coerce(ds))
            n = next(iter(inputs.values())).shape[0]
            if n % self.data_shards != 0:
                # Every output head gets a zero-weight mask over pad rows.
                lm = {name: self._pad_lmask(lm.get(name), n)
                      for name in labels}
            shard = lambda d: {k: self._shard_arr(v) for k, v in d.items()}
            net._run_and_commit(shard(inputs), shard(labels), shard(fm),
                                shard(lm), mesh=self.mesh)
            return
        net._fit_batch(ds, do_step=self._sync_step)

    def _sync_step(self, x, y, fmask, lmask) -> None:
        """Sharded analog of MultiLayerNetwork._do_step: shard the inputs
        over the mesh's data axis, then delegate invoke+commit to the net
        so the commit tail can never diverge from the single-device path."""
        net = self.model
        if x.shape[0] % self.data_shards != 0:
            lmask = self._pad_lmask(lmask, x.shape[0])
        net._run_and_commit(
            self._shard_arr(x, cast_dtype=net._dtype), self._shard_arr(y),
            self._shard_arr(fmask), self._shard_arr(lmask), mesh=self.mesh)

    # --------------------------------------------------------------- shutdown
    def shutdown(self):
        """Reference ParallelWrapper.shutdown(): nothing to tear down here —
        no threads were harmed in this design."""
        self._placed = False


class ParallelWrapperBuilder:
    """Fluent builder mirroring reference ParallelWrapper.Builder."""

    def __init__(self, model):
        self._model = model
        self._workers = None
        self._avg_freq = 1
        self._prefetch = 8
        self._mesh = None

    def workers(self, n: int):
        self._workers = int(n)
        return self

    def averaging_frequency(self, n: int):
        self._avg_freq = int(n)
        return self

    def prefetch_buffer(self, n: int):
        self._prefetch = int(n)
        return self

    def mesh(self, m: Mesh):
        self._mesh = m
        return self

    def build(self) -> ParallelWrapper:
        return ParallelWrapper(self._model, mesh=self._mesh,
                               workers=self._workers,
                               averaging_frequency=self._avg_freq,
                               prefetch_buffer=self._prefetch)
