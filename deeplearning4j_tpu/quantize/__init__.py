"""Post-training quantization for the serving plane (docs/design.md
"Quantized serving"): spec-driven int8/bf16 param-tree transforms plus
the dequant-free quantized forward helpers the layers dispatch to."""
from .quantize import (  # noqa: F401
    MODES, QUANT_SCALE, QUANT_WEIGHT, QUANT_ZERO, AlreadyQuantizedError,
    QuantSpec, dense_qforward, dequantize_tree, embedding_qlookup,
    matmul_any, quantize_tree, sidecar_scales, tree_precision,
)
