"""Post-training quantization of inference param trees.

Jacob et al. (CVPR 2018) style per-channel weight quantization, plus a
bf16 cast path, behind one spec interface (docs/design.md "Quantized
serving"):

* ``quantize_tree(params, spec)`` / ``dequantize_tree(qparams)`` — pure
  functions over the nested param tree. Quantization is a *deployment*
  decision made at swap time (serving/model_pool.swap(quantize=...)),
  never a training-time flag: training trees stay fp32 and never see
  this module.
* int8 mode: dense-shaped subtrees (dicts whose keys are exactly the
  core ``W``/``b`` pair with a 2-D float weight — DenseLayer, the
  output heads, EmbeddingLayer) get symmetric per-output-channel int8:
  ``W_scale[n] = max_k |W[k, n]| / 127``, ``W_q = round(W / scale)``
  stored TRANSPOSED as s8 [n_out, n_in] so every output channel is a
  unit-stride row (the layout ops.pallas_kernels.quant_matmul and the
  native VNNI kernel consume directly — the forward never dequantizes
  the weights). Optional asymmetric zero-points (``spec.zero_point``)
  add an s32 ``W_zp`` per channel. Every other float leaf with ndim >= 2
  (conv 4-D kernels, attention projections, recurrent W/RW — shapes
  where int8 loses or the kernel doesn't reach) casts to bf16; biases
  and 1-D stats stay fp32 so epilogues keep full precision.
* bf16 mode: all float leaves with ndim >= 2 cast to bf16, rest
  untouched — the low-risk arm (Kalamkar et al., 2019).

Reserved keys ``W_q``/``W_scale``/``W_zp`` replace ``W`` in quantized
dicts; re-quantizing a quantized tree raises the typed
``AlreadyQuantizedError`` (idempotence is a bug here — it would stack
scales silently). ``sidecar_scales`` extracts the scale/zero-point
sidecar as its own tree for checkpoint/audit surfaces.

The quantized *forward* helpers live here too (``dense_qforward``,
``embedding_qlookup``, ``matmul_any``): int8 matmul with an fp32
bias/activation epilogue and dynamic symmetric per-row activation
quantization — activations are quantized on the fly inside the jitted
forward (one abs-max per row), so no calibration pass is needed.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Union

import jax
import jax.numpy as jnp

from ..ops import pallas_kernels

__all__ = [
    "QuantSpec", "AlreadyQuantizedError", "MODES",
    "QUANT_WEIGHT", "QUANT_SCALE", "QUANT_ZERO",
    "quantize_tree", "dequantize_tree", "sidecar_scales",
    "tree_precision", "dense_qforward", "embedding_qlookup",
    "matmul_any",
]

# Cross-file trace surface (analysis/boundaries.py): dense_qforward is
# jitted by the serving layers that build quantized forwards, so the
# JL0xx/JL2xx purity rules must treat it as a traced root here.
__traced__ = ("dense_qforward",)

#: reserved keys a quantized dense dict carries instead of ``W``
QUANT_WEIGHT = "W_q"
QUANT_SCALE = "W_scale"
QUANT_ZERO = "W_zp"

MODES = ("int8", "bf16")

_DENSE_KEYS = {"W", "b"}


class AlreadyQuantizedError(TypeError):
    """Raised when quantize_tree sees a tree that already carries
    quantized leaves — re-quantization is never idempotent (int8 of
    int8 stacks scales; bf16 of bf16 silently halves mantissa twice),
    so it is a typed error, not a no-op."""


@dataclass(frozen=True)
class QuantSpec:
    """What to do to a param tree. ``mode`` picks the arm; zero-points
    are optional (symmetric per-channel is the default — zero-centered
    weight distributions waste <1 bit of range on it and the forward
    stays correction-free)."""
    mode: str = "int8"
    zero_point: bool = False

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(
                f"QuantSpec.mode must be one of {MODES}, got {self.mode!r}")

    @staticmethod
    def coerce(spec: Union["QuantSpec", str]) -> "QuantSpec":
        if isinstance(spec, QuantSpec):
            return spec
        return QuantSpec(mode=str(spec))


def _is_float(leaf) -> bool:
    return (hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating))


def _check_not_quantized(leaf) -> None:
    if not hasattr(leaf, "dtype"):
        return
    if leaf.dtype == jnp.int8 or leaf.dtype == jnp.bfloat16:
        raise AlreadyQuantizedError(
            f"leaf dtype {leaf.dtype} is already quantized; "
            "dequantize_tree first")


def _quantize_dense(d: Dict[str, Any], spec: QuantSpec) -> Dict[str, Any]:
    w = d["W"]
    if spec.zero_point:
        wmax = jnp.max(w, axis=0)
        wmin = jnp.min(w, axis=0)
        span = jnp.maximum(wmax - wmin, 1e-12)
        scale = (span / 254.0).astype(jnp.float32)
        # center of the range maps to q=0; 254 codes cover the span so
        # rounding never clips
        zp = jnp.round((wmax + wmin) / (2.0 * scale)).astype(jnp.int32)
        q = jnp.clip(jnp.round(w / scale) - zp, -127, 127)
        out = {QUANT_WEIGHT: q.astype(jnp.int8).T,
               QUANT_SCALE: scale, QUANT_ZERO: zp}
    else:
        amax = jnp.max(jnp.abs(w), axis=0)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(w / scale), -127, 127)
        out = {QUANT_WEIGHT: q.astype(jnp.int8).T, QUANT_SCALE: scale}
    if "b" in d:
        out["b"] = d["b"]
    return out


def quantize_tree(params, spec: Union[QuantSpec, str] = "int8"):
    """Quantize an inference param tree per ``spec``. Pure: returns a
    new tree, input untouched. Raises AlreadyQuantizedError on any
    already-quantized material anywhere in the tree."""
    spec = QuantSpec.coerce(spec)

    def walk(node):
        if isinstance(node, dict):
            if QUANT_WEIGHT in node or QUANT_SCALE in node:
                raise AlreadyQuantizedError(
                    "tree already carries W_q/W_scale sidecar keys; "
                    "dequantize_tree first")
            if (spec.mode == "int8" and set(node) <= _DENSE_KEYS
                    and "W" in node and _is_float(node["W"])
                    and node["W"].ndim == 2):
                _check_not_quantized(node["W"])
                return _quantize_dense(node, spec)
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        _check_not_quantized(node)
        if _is_float(node) and node.ndim >= 2:
            return node.astype(jnp.bfloat16)
        return node

    return walk(params)


def dequantize_tree(qparams):
    """Reconstruct an fp32 tree from a quantized one (the rollback /
    audit path). Exact inverse of the cast for bf16 mantissa bits;
    within scale/2 per element for int8 (the property the round-trip
    tests pin)."""

    def walk(node):
        if isinstance(node, dict):
            if QUANT_WEIGHT in node:
                q = node[QUANT_WEIGHT].astype(jnp.float32)
                if QUANT_ZERO in node:
                    q = q + node[QUANT_ZERO].astype(jnp.float32)[:, None]
                w = (q * node[QUANT_SCALE][:, None]).T
                out = {"W": w}
                if "b" in node:
                    out["b"] = node["b"]
                return out
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        if hasattr(node, "dtype") and node.dtype == jnp.bfloat16:
            return node.astype(jnp.float32)
        return node

    return walk(qparams)


def sidecar_scales(qparams):
    """The scale/zero-point sidecar as its own tree (same dict shape,
    quantized dicts reduced to their W_scale/W_zp entries) — the
    checkpoint-audit surface the spec format documents."""

    def walk(node):
        if isinstance(node, dict):
            if QUANT_WEIGHT in node:
                out = {QUANT_SCALE: node[QUANT_SCALE]}
                if QUANT_ZERO in node:
                    out[QUANT_ZERO] = node[QUANT_ZERO]
                return out
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v) for v in node)
        return None

    return walk(qparams)


def tree_precision(params) -> str:
    """Classify a param tree's serving precision: 'int8' if any int8
    weight leaf, else 'bf16' if any bf16 leaf, else 'fp32' — the label
    the swap plane stamps on metrics and traces."""
    has_bf16 = False
    for leaf in jax.tree_util.tree_leaves(params):
        dt = getattr(leaf, "dtype", None)
        if dt == jnp.int8:
            return "int8"
        if dt == jnp.bfloat16:
            has_bf16 = True
    return "bf16" if has_bf16 else "fp32"


# ---------------------------------------------------------------------------
# Quantized forwards (called from layer code at trace time; the branch
# is a Python dict-key check, so fp32 training graphs are untouched)
# ---------------------------------------------------------------------------

def matmul_any(x, w, b=None):
    """x @ w (+ b) with an fp32 epilogue whatever the weight dtype: the
    bf16 arm casts the activations down for the product and back up
    before bias, keeping the bias add and activation at full precision;
    fp32 weights take the exact original ops."""
    if w.dtype == jnp.bfloat16:
        y = (x.astype(jnp.bfloat16) @ w).astype(jnp.float32)
    else:
        y = x @ w
    return y if b is None else y + b


def dense_qforward(params, x):
    """Dense pre-activation from an int8-quantized dict: dynamic
    symmetric per-row activation quantization, dequant-free int8
    matmul, fp32 scale+bias epilogue.

      x_scale[b] = max_k |x[b,k]| / 127      (on the fly, per request)
      acc[b,n]   = sum_k x_q[b,k] * W_q[n,k]  (exact int32)
      out[b,n]   = acc * x_scale[b] * W_scale[n] + bias[n]

    With zero-points, ``W[k,n] = (W_q[n,k] + zp[n]) * scale[n]`` adds
    the correction ``zp[n] * sum_k x_q[b,k]`` to the accumulator — one
    row-sum, still integer-exact."""
    w_q = params[QUANT_WEIGHT]
    scale = params[QUANT_SCALE]
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    x_scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    x_q = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
    acc = pallas_kernels.quant_matmul(x_q, w_q)
    # Dict-key membership is pytree *structure*, static at trace time —
    # not a tracer-value branch.
    if QUANT_ZERO in params:  # jaxlint: disable=JL005
        rowsum = jnp.sum(x_q.astype(jnp.int32), axis=-1, keepdims=True)
        acc = acc + params[QUANT_ZERO][None, :] * rowsum
    out = acc.astype(jnp.float32) * (x_scale * scale[None, :])
    b = params.get("b")
    return out if b is None else out + b


def embedding_qlookup(params, idx):
    """Embedding rows from an int8 table: gather columns of the
    transposed W_q, dequantize just the gathered slice (per-channel
    scale), fp32 bias. Weight memory stays int8 end to end."""
    w_q = params[QUANT_WEIGHT]          # [n_out, vocab]
    cols = jnp.take(w_q, idx, axis=1).astype(jnp.float32)
    if QUANT_ZERO in params:
        cols = cols + params[QUANT_ZERO].astype(jnp.float32)[:, None]
    out = (cols * params[QUANT_SCALE][:, None]).T
    b = params.get("b")
    return out if b is None else out + b
