"""Serving: k-NN REST server (reference
deeplearning4j-nearestneighbor-server, SURVEY.md §2.11)."""
from .nearest_neighbor import NearestNeighbor, NearestNeighborsServer
