"""Serving: k-NN REST server (reference
deeplearning4j-nearestneighbor-server, SURVEY.md §2.11)."""
from .keras_server import KerasBackendServer
from .nearest_neighbor import NearestNeighbor, NearestNeighborsServer
