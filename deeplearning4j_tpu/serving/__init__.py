"""Serving: the gateway control plane (continuous batching, SLO
shedding, per-model circuit breakers, checkpoint-gated hot-swap with a
canary gate, priority-tier WFQ scheduling across co-resident models and
fused cross-model batching — docs/serving.md) plus the k-NN and
Keras-backend REST facades (reference
deeplearning4j-nearestneighbor-server, SURVEY.md §2.11), all on the
shared utils/http_server core. The per-request flight recorder
(serving/flight_recorder.py — phase-attributed tail latency,
slow-request exemplars, GET /debug/requests + /trace) is exported as
the `flight_recorder` submodule; the serving control loop
(serving/autotuner.py — windowed SLO verdicts + the auditable
hill-climbing AutoTuner behind GET /debug/tuner) as `autotuner`. The
autoregressive decode plane (serving/decode.py — token-granularity
continuous batching over a paged KV cache, POST /generate,
docs/serving.md §decode) is exported as `decode`. The replica
federation plane (serving/federation.py — multi-replica serving behind
a routing front-end with heartbeat-driven membership, typed
exactly-once failover and rolling zero-traffic deploys, docs/serving.md
§"Replica federation") is exported as `federation`."""
from . import autotuner, decode, federation, flight_recorder
from .autotuner import AutoTuner, Knob, SLOMonitor
from .breaker import BreakerOpenError, CircuitBreaker
from .federation import (FederationFrontEnd, ReplicaLostError,
                         ReplicaServer, serve_replica, spawn_replica)
from .decode import (DecodeEngine, PagedKVCache, RecurrentAdapter,
                     TransformerAdapter, TransformerDecoder,
                     naive_generate)
from .flight_recorder import RequestTrace
from .gateway import ServingGateway
from .keras_server import KerasBackendServer
from .model_pool import FusedModelGroup, ModelEntry, ModelPool, SwapError
from .nearest_neighbor import NearestNeighbor, NearestNeighborsServer
from .scheduler import DeviceScheduler, TierShedError
