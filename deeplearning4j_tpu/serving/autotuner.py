"""Self-tuning serving: the SLO watchtower + auditable AutoTuner
(docs/observability.md §"The serving control loop").

PR 14's flight recorder attributes tail latency to seven phases and
PR 13's reconfigure seam changes every serving knob live — but the
telemetry was read by nobody. This module closes the loop:

* :class:`SLOMonitor` scrapes the PR-2 registry on a cadence and turns
  it into *windowed* per-tier verdicts: p99 over the last N seconds
  (Histogram.quantile ring, NOT lifetime buckets) vs the scheduler's
  ``serving_tier_slo_ms``, shed rate from counter deltas between ticks,
  and the dominant flight-recorder phase — the hint that picks WHICH
  knob to move. Injectable clock throughout, fake-clock testable like
  the breaker and the cluster watchdog.

* :class:`AutoTuner` hill-climbs ONE knob at a time through the
  existing actuators (``ModelPool.reconfigure`` /
  ``reconfigure_scheduler`` — the same seam ``POST /config`` drives)
  inside hard per-knob guardrails. Every decision is appended to
  ``autotune_ledger.jsonl`` with a scoreboard-style strict schema
  (unknown fields and kinds REJECTED): the knob, old→new, the windowed
  evidence that motivated the move, the observed outcome after a settle
  window, and the revert when the move regressed. The tuner FREEZES —
  reverting every knob to the last known-good snapshot — on
  breaker-open, canary rejection, or a hard SLO breach
  (p99 ≥ ``breach_freeze_factor`` × SLO: a *mild* breach is the
  hill-climb signal, a hard one is an incident the tuner must not
  chase), and thaws only after ``freeze_cooldown_s`` of continuous
  health.

A gateway without a tuner attached runs today's serving path bitwise:
nothing here touches admission or dispatch — the monitor reads the
scrape surface, the tuner writes through the reconfigure seam.

Metric families (pre-registered by ``register_metrics()``, bench
``--once`` pattern): ``serving_tuner_moves_total{knob,outcome}``
(applied/kept/reverted/neutral/refused), ``serving_tuner_frozen``,
``serving_tuner_state`` (0=watching, 1=settling, 2=frozen),
``serving_tuner_reverts_total``,
``serving_tuner_freezes_total{reason}``,
``serving_tuner_errors_total``, and the monitor's per-tier
``serving_slo_verdict{tier}``.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..optimize.metrics import registry
from .scheduler import DEFAULT_TIER_SLO_MS, TIERS

__all__ = [
    "SLOMonitor", "AutoTuner", "Knob", "TierVerdict", "MonitorReport",
    "default_knobs", "register_metrics", "validate_entry", "append_entry",
    "read_ledger", "default_ledger_path", "LEDGER_SCHEMA_VERSION",
    "MOVE_OUTCOMES", "FREEZE_REASONS",
]

# ---------------------------------------------------------------------------
# Ledger: append-only jsonl, strict schema (optimize/scoreboard.py idiom)
# ---------------------------------------------------------------------------
LEDGER_SCHEMA_VERSION = 1
LEDGER_ENV = "DL4JTPU_AUTOTUNE_LEDGER"

# Terminal outcomes of an applied move after its settle window.
MOVE_OUTCOMES = ("kept", "reverted", "neutral")
# Typed freeze triggers — every freeze is one of these, counted in
# serving_tuner_freezes_total{reason}.
FREEZE_REASONS = ("breaker_open", "canary_rejected", "slo_breach", "manual")

_NUM = (int, float)
# Required fields per row, common first. Unknown kinds and unknown
# fields are REJECTED (scoreboard strictness): the ledger is an audit
# artifact — a row that doesn't parse against the schema is a bug, not
# a forward-compat extension point.
_COMMON_FIELDS: Dict[str, Any] = {
    "schema": int, "ts": _NUM, "seq": int, "kind": str}
_KIND_FIELDS: Dict[str, Dict[str, Any]] = {
    "move": {"knob": str, "old": _NUM, "new": _NUM, "direction": int,
             "evidence": dict},
    "outcome": {"ref": int, "knob": str, "outcome": str, "old": _NUM,
                "new": _NUM, "before_score": _NUM, "after_score": _NUM,
                "reverted": bool, "evidence": dict},
    "refusal": {"knob": str, "candidate": _NUM, "lo": _NUM, "hi": _NUM,
                "reason": str},
    "freeze": {"reason": str, "evidence": dict, "restored": dict},
    "unfreeze": {"healthy_s": _NUM},
}


def default_ledger_path() -> str:
    """$DL4JTPU_AUTOTUNE_LEDGER, else <repo root>/autotune_ledger.jsonl
    (beside BENCH_ledger.jsonl — the serving counterpart of the bench
    scoreboard's audit trail)."""
    env = os.environ.get(LEDGER_ENV)
    if env:
        return env
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, "autotune_ledger.jsonl")


def validate_entry(entry: Any) -> List[str]:
    """Schema problems for one ledger row ([] = valid). Strict: unknown
    kind, unknown field, missing field, wrong type, out-of-vocabulary
    outcome/reason all reject."""
    if not isinstance(entry, dict):
        return ["entry is not a dict"]
    problems: List[str] = []
    kind = entry.get("kind")
    if kind not in _KIND_FIELDS:
        problems.append(f"unknown kind {kind!r}; one of "
                        f"{tuple(_KIND_FIELDS)}")
        return problems
    want = dict(_COMMON_FIELDS)
    want.update(_KIND_FIELDS[kind])
    for field, typ in want.items():
        if field not in entry:
            problems.append(f"missing field {field!r}")
        elif not isinstance(entry[field], typ):
            problems.append(
                f"field {field!r} has type {type(entry[field]).__name__}")
    for field in entry:
        if field not in want:
            problems.append(f"unknown field {field!r} for kind {kind!r}")
    if not problems:
        if entry["schema"] != LEDGER_SCHEMA_VERSION:
            problems.append(f"schema {entry['schema']!r} != "
                            f"{LEDGER_SCHEMA_VERSION}")
        if kind == "outcome" and entry["outcome"] not in MOVE_OUTCOMES:
            problems.append(f"outcome {entry['outcome']!r}; one of "
                            f"{MOVE_OUTCOMES}")
        if kind == "freeze" and entry["reason"] not in FREEZE_REASONS:
            problems.append(f"freeze reason {entry['reason']!r}; one of "
                            f"{FREEZE_REASONS}")
    return problems


def append_entry(entry: Dict[str, Any],
                 path: Optional[str] = None) -> Dict[str, Any]:
    """Validate + append one row (flush + fsync: a row either fully
    lands or tears, and read_ledger tolerates the tear). Raises
    ValueError on a schema-invalid row — the writer's bug, caught
    loudly, never a silently-corrupt audit trail."""
    problems = validate_entry(entry)
    if problems:
        raise ValueError("invalid autotune ledger row: "
                         + "; ".join(problems))
    path = path or default_ledger_path()
    line = json.dumps(entry, sort_keys=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())
    return entry


def read_ledger(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """All parseable rows, in file order. Torn/corrupt lines (a crash
    mid-append) are skipped, never fatal — scoreboard semantics."""
    path = path or default_ledger_path()
    rows: List[Dict[str, Any]] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except (ValueError, TypeError):
                    continue  # torn tail line
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        return []
    return rows


# ---------------------------------------------------------------------------
# Monitor: windowed per-tier verdicts
# ---------------------------------------------------------------------------
class TierVerdict:
    """Windowed judgment of one priority tier against its SLO."""

    __slots__ = ("tier", "p99_ms", "slo_ms", "requests", "shed_rate",
                 "top_phase", "breach")

    def __init__(self, tier: str, p99_ms: float, slo_ms: float, *,
                 requests: int = 0, shed_rate: float = 0.0,
                 top_phase: Optional[str] = None,
                 breach: Optional[bool] = None):
        self.tier = tier
        self.p99_ms = float(p99_ms)
        self.slo_ms = float(slo_ms)
        self.requests = int(requests)
        self.shed_rate = float(shed_rate)
        self.top_phase = top_phase
        self.breach = (self.p99_ms > self.slo_ms) if breach is None \
            else bool(breach)

    @property
    def ratio(self) -> float:
        """p99 / SLO — >1.0 is a breach; the hill-climb's per-tier
        badness term."""
        return self.p99_ms / self.slo_ms if self.slo_ms > 0 else 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {"tier": self.tier, "p99_ms": round(self.p99_ms, 3),
                "slo_ms": round(self.slo_ms, 3),
                "requests": self.requests,
                "shed_rate": round(self.shed_rate, 4),
                "top_phase": self.top_phase, "breach": self.breach}


class MonitorReport:
    """One monitor tick: per-tier verdicts + pool-level health signals
    (open breakers, canary rejections since the previous tick)."""

    def __init__(self, ts: float, verdicts: Dict[str, TierVerdict], *,
                 breakers_open=(), canary_rejections: int = 0,
                 min_samples: int = 1):
        self.ts = float(ts)
        self.verdicts = dict(verdicts)
        self.breakers_open = list(breakers_open)
        self.canary_rejections = int(canary_rejections)
        self.min_samples = int(min_samples)

    def sampled(self) -> List[TierVerdict]:
        """Verdicts with enough windowed traffic to act on — a tier
        with 2 requests has no p99 worth chasing."""
        return [v for v in self.verdicts.values()
                if v.requests >= self.min_samples]

    @property
    def score(self) -> float:
        """Scalar badness the hill-climb minimizes: worst windowed
        p99/SLO ratio across sampled tiers, plus a 2× shed-rate
        penalty (shedding half the traffic to make the p99 is not a
        win)."""
        s = self.sampled()
        ratio = max((v.ratio for v in s), default=0.0)
        shed = max((v.shed_rate for v in s), default=0.0)
        return ratio + 2.0 * shed

    @property
    def worst(self) -> Optional[TierVerdict]:
        s = self.sampled()
        if not s:
            return None
        return max(s, key=lambda v: v.ratio + 2.0 * v.shed_rate)

    @property
    def healthy(self) -> bool:
        return (not self.breakers_open
                and self.canary_rejections == 0
                and not any(v.breach for v in self.sampled())
                and max((v.shed_rate for v in self.sampled()),
                        default=0.0) < 0.01)

    def evidence(self) -> Dict[str, Any]:
        """The windowed facts a ledger row records as the motivation
        for a decision."""
        return {"ts": round(self.ts, 3),
                "score": round(self.score, 4),
                "tiers": {t: v.as_dict() for t, v in self.verdicts.items()},
                "breakers_open": list(self.breakers_open),
                "canary_rejections": self.canary_rejections}


class SLOMonitor:
    """Scrapes the registry into windowed per-tier verdicts on demand.

    ``window_s`` bounds every quantile/rate to the recent past —
    verdicts answer "how is serving NOW", not "since process start".
    ``clock`` is injectable (breaker/cluster-watchdog pattern): tests
    drive tick() with a fake clock paired with explicit ``t=``-stamped
    histogram observations. Shed rates and canary-rejection counts are
    deltas between consecutive ticks (zero on the first tick — no
    baseline yet)."""

    def __init__(self, pool, *, window_s: float = 30.0,
                 min_samples: int = 5,
                 clock: Callable[[], float] = time.monotonic):
        self.pool = pool
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self._clock = clock
        # Window floor: the registry rings are process-global but this
        # monitor is not — observations stamped before it existed (an
        # earlier gateway/bench arm in the same process) never count.
        self._born = float(clock())
        self._lock = threading.Lock()
        self._last: Optional[Dict[str, float]] = None
        self._verdict_g = registry().gauge(
            "serving_slo_verdict",
            "Windowed per-tier SLO verdict (1 = p99 over budget)")

    # ------------------------------------------------------------ helpers
    def _tier_slos(self) -> Dict[str, float]:
        sch = self.pool.scheduler
        if sch is not None:
            return dict(sch.tier_slo_ms)
        return dict(DEFAULT_TIER_SLO_MS)

    def _model_tiers(self) -> Dict[str, str]:
        return {e.name: e.tier for e in self.pool.entries()}

    # --------------------------------------------------------------- tick
    def tick(self) -> MonitorReport:
        now = self._clock()
        window = min(self.window_s, max(0.0, now - self._born))
        reg = registry()
        tiers_of = self._model_tiers()
        slos = self._tier_slos()

        # Windowed latency values per tier. When a scheduler labels the
        # pool, requests land in BOTH model- and tier-labeled children —
        # use only the tier cells then (folding both would double-count);
        # an untiered pool folds model cells through the tier map.
        lat = reg.histogram("serving_latency_ms")
        vals: Dict[str, List[float]] = {}
        cells = lat.items()
        tier_cells = [(labels["tier"], child) for labels, child in cells
                      if "tier" in labels]
        if tier_cells:
            for t, child in tier_cells:
                vals.setdefault(t, []).extend(
                    child.window_values(window, now=now))
        else:
            for labels, child in cells:
                t = tiers_of.get(labels.get("model"))
                if t is not None:
                    vals.setdefault(t, []).extend(
                        child.window_values(window, now=now))

        # Per-tier request/shed deltas since the previous tick.
        cur: Dict[str, float] = {}
        for labels, child in reg.counter("serving_requests_total").items():
            t = tiers_of.get(labels.get("model"))
            if t is not None:
                cur[f"req:{t}"] = cur.get(f"req:{t}", 0.0) + child.value()
        for labels, child in reg.counter("serving_shed_total").items():
            t = tiers_of.get(labels.get("model"))
            if t is not None:
                cur[f"shed:{t}"] = cur.get(f"shed:{t}", 0.0) + child.value()
        cur["canary"] = reg.counter("serving_swaps_total").total(
            outcome="canary_rejected")
        with self._lock:
            prev = self._last
            self._last = cur

        def _delta(key: str) -> float:
            if prev is None:
                return 0.0
            return max(0.0, cur.get(key, 0.0) - prev.get(key, 0.0))

        # Phase attribution: the dominant windowed flight-recorder phase
        # per tier (absent unless the recorder is enabled).
        phase_ms: Dict[str, Dict[str, float]] = {}
        for labels, child in reg.histogram("serving_phase_ms").items():
            t, p = labels.get("tier"), labels.get("phase")
            if t is None or p is None:
                continue
            tot = sum(child.window_values(window, now=now))
            if tot > 0:
                d = phase_ms.setdefault(t, {})
                d[p] = d.get(p, 0.0) + tot

        verdicts: Dict[str, TierVerdict] = {}
        for tier in sorted(set(tiers_of.values()) | set(vals)):
            tvals = sorted(vals.get(tier, ()))
            n = len(tvals)
            p99 = 0.0
            if n:
                p99 = tvals[min(n - 1, int(round(0.99 * (n - 1))))]
            req_d = _delta(f"req:{tier}")
            shed_d = _delta(f"shed:{tier}")
            shed_rate = shed_d / req_d if req_d > 0 else 0.0
            phases = phase_ms.get(tier)
            top = max(phases, key=phases.get) if phases else None
            slo = float(slos.get(tier, DEFAULT_TIER_SLO_MS["standard"]))
            v = TierVerdict(tier, p99, slo, requests=n,
                            shed_rate=min(1.0, shed_rate), top_phase=top)
            verdicts[tier] = v
            if n >= self.min_samples:
                self._verdict_g.labels(tier=tier).set(
                    1.0 if v.breach else 0.0)

        breakers_open = [e.name for e in self.pool.entries()
                         if e.breaker is not None
                         and e.breaker.state != "closed"]
        return MonitorReport(now, verdicts, breakers_open=breakers_open,
                             canary_rejections=int(_delta("canary")),
                             min_samples=self.min_samples)


# ---------------------------------------------------------------------------
# Knobs
# ---------------------------------------------------------------------------
class Knob:
    """One tunable serving parameter: read/apply closures over the
    reconfigure seam, HARD guardrails [lo, hi], and the hill-climb step
    rule (``mode="add"``: cur ± step; ``mode="mul"``: cur ×/÷ step).
    ``direction`` is the current climb direction (+1 up / -1 down) —
    flipped by the tuner on reverts and guardrail refusals. ``tier``
    tags which tier the knob most affects (phase-hint routing)."""

    def __init__(self, name: str, get: Callable[[], float],
                 set: Callable[[float], Any], *, lo: float, hi: float,
                 step: float, mode: str = "mul", integer: bool = False,
                 direction: int = -1, tier: Optional[str] = None):
        if mode not in ("mul", "add"):
            raise ValueError(f"knob mode {mode!r}; one of ('mul', 'add')")
        if mode == "mul" and step <= 1.0:
            raise ValueError("multiplicative step must be > 1.0")
        if mode == "add" and step <= 0.0:
            raise ValueError("additive step must be > 0.0")
        if float(lo) > float(hi):
            raise ValueError(f"knob {name!r}: lo > hi")
        self.name = name
        self._get = get
        self._set = set
        self.lo = float(lo)
        self.hi = float(hi)
        self.step = float(step)
        self.mode = mode
        self.integer = bool(integer)
        self.direction = 1 if int(direction) >= 0 else -1
        self.tier = tier

    def get(self) -> float:
        return float(self._get())

    def apply(self, value: float) -> None:
        self._set(int(value) if self.integer else float(value))

    def propose(self) -> Tuple[Optional[float], float, float]:
        """(new, raw, cur): the next step in the current direction.
        `raw` is the unclamped candidate, `new` is raw clamped to the
        guardrails and rounded for integer knobs — None when the clamp
        lands back on the current value (pinned at a rail: a refusal,
        never a silent out-of-range move)."""
        cur = self.get()
        if self.mode == "mul":
            raw = cur * self.step if self.direction > 0 else cur / self.step
        else:
            raw = cur + self.step * self.direction
        new = min(self.hi, max(self.lo, raw))
        if self.integer:
            new = float(int(round(new)))
        if abs(new - cur) < 1e-12:
            return None, raw, cur
        return new, raw, cur


def default_knobs(pool) -> List[Knob]:
    """The standing knob table (docs/observability.md §"The serving
    control loop"): per-entry collector linger + WFQ weight + circuit
    breaker threshold/cooldown, scheduler quantum + shed depth — each
    actuated through the same reconfigure seam POST /config drives,
    inside hard guardrails. The breaker rails are deliberately tight:
    a threshold below 2 turns any single transient blip into an
    outage, above 32 the breaker stops protecting anything; a cooldown
    under 1 s thrashes probes, over 120 s parks a recovered model in
    fast-fail. Fused-group members are skipped (reconfigure refuses
    them); weight/scheduler knobs exist only when the pool runs a
    DeviceScheduler; breaker knobs only for entries that carry one."""
    knobs: List[Knob] = []
    sch = pool.scheduler
    for e in pool.entries():
        if e.group is not None:
            continue
        nm = e.name
        knobs.append(Knob(
            f"linger_ms:{nm}",
            get=lambda _e=e: _e.engine.batch_timeout_ms,
            set=lambda v, _p=pool, _n=nm: _p.reconfigure(
                _n, batch_timeout_ms=v),
            lo=0.0, hi=20.0, step=2.0, mode="add", direction=-1,
            tier=e.tier))
        if getattr(e, "breaker", None) is not None:
            knobs.append(Knob(
                f"breaker_threshold:{nm}",
                get=lambda _e=e: _e.breaker.failure_threshold,
                set=lambda v, _p=pool, _n=nm: _p.reconfigure(
                    _n, breaker_threshold=v),
                lo=2, hi=32, step=2.0, mode="mul", integer=True,
                direction=1, tier=e.tier))
            knobs.append(Knob(
                f"breaker_reset_s:{nm}",
                get=lambda _e=e: _e.breaker.reset_timeout_s,
                set=lambda v, _p=pool, _n=nm: _p.reconfigure(
                    _n, breaker_reset_s=v),
                lo=1.0, hi=120.0, step=2.0, mode="mul",
                direction=-1, tier=e.tier))
        if sch is not None:
            knobs.append(Knob(
                f"weight:{nm}",
                get=lambda _e=e: _e.weight,
                set=lambda v, _p=pool, _n=nm: _p.reconfigure(_n, weight=v),
                lo=0.25, hi=16.0, step=2.0, mode="mul", direction=1,
                tier=e.tier))
    if sch is not None:
        knobs.append(Knob(
            "quantum",
            get=lambda _s=sch: _s.quantum,
            set=lambda v, _p=pool: _p.reconfigure_scheduler(quantum=v),
            lo=0.25, hi=8.0, step=1.5, mode="mul", direction=-1))
        knobs.append(Knob(
            "shed_depth",
            get=lambda _s=sch: _s.shed_depth,
            set=lambda v, _p=pool: _p.reconfigure_scheduler(shed_depth=v),
            lo=2, hi=64, step=2.0, mode="mul", integer=True, direction=-1))
    return knobs


# ---------------------------------------------------------------------------
# Tuner
# ---------------------------------------------------------------------------
WATCHING, SETTLING, FROZEN = "watching", "settling", "frozen"
_STATE_VALUES = {WATCHING: 0, SETTLING: 1, FROZEN: 2}


def register_metrics() -> None:
    """Pre-register the tuner families at 0 (bench --once pattern) so a
    snapshot distinguishes 'tuner never moved' from 'tuner never ran'."""
    reg = registry()
    reg.counter("serving_tuner_moves_total",
                "AutoTuner knob decisions by outcome "
                "(applied/kept/reverted/neutral/refused)")
    reg.counter("serving_tuner_reverts_total",
                "Moves reverted after the settle window regressed the "
                "windowed score")
    reg.counter("serving_tuner_freezes_total",
                "Tuner freezes by typed trigger (breaker_open/"
                "canary_rejected/slo_breach/manual)")
    reg.counter("serving_tuner_errors_total",
                "Control-loop ticks that raised (swallowed: the tuner "
                "must never take down serving)")
    g = reg.gauge("serving_tuner_frozen",
                  "1 while the AutoTuner is frozen at the last "
                  "known-good config")
    if not g._touched():
        g.set(0.0)
    sg = reg.gauge("serving_tuner_state",
                   "AutoTuner state (0=watching, 1=settling, 2=frozen)")
    if not sg._touched():
        sg.set(0.0)
    reg.gauge("serving_slo_verdict",
              "Windowed per-tier SLO verdict (1 = p99 over budget)")


class AutoTuner:
    """Hill-climbs one serving knob at a time against the monitor's
    windowed score, with every decision ledgered and revertible.

    State machine per tick():

    * any state → **frozen** on a typed trigger (breaker open, canary
      rejection since last tick, hard SLO breach): every knob reverts
      to the last known-good snapshot, the freeze is ledgered and
      counted. Frozen thaws only after ``freeze_cooldown_s`` of
      continuously healthy ticks.
    * **settling** (a move in flight): after ``settle_ticks`` ticks the
      move's outcome is judged against the score it was applied at —
      improved ≥ ``tolerance`` → *kept* (snapshot becomes known-good);
      regressed ≥ ``tolerance`` → *reverted* (the EXACT old value is
      restored — bitwise — and the knob's climb direction flips);
      else *neutral*.
    * **watching** + unhealthy verdicts → apply ONE move: the knob is
      picked by the worst tier's dominant phase (queue_wait → its
      linger, sched_wait → quantum/its weight), else round-robin; a
      step that would leave the guardrails is ledgered as a *refusal*
      (and the direction flips), never applied.

    The clock is injectable; tick() can be driven manually (fake-clock
    tests) or by start()'s daemon thread every ``interval_s``."""

    def __init__(self, pool, monitor: Optional[SLOMonitor] = None, *,
                 knobs: Optional[List[Knob]] = None,
                 ledger_path: Optional[str] = None,
                 interval_s: float = 5.0, settle_ticks: int = 2,
                 tolerance: float = 0.05,
                 breach_freeze_factor: float = 3.0,
                 freeze_cooldown_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic):
        self.pool = pool
        self._clock = clock
        self.monitor = monitor if monitor is not None \
            else SLOMonitor(pool, clock=clock)
        self.knobs = list(knobs) if knobs is not None \
            else default_knobs(pool)
        if not self.knobs:
            raise ValueError("AutoTuner needs at least one knob")
        self.ledger_path = ledger_path or default_ledger_path()
        self.interval_s = float(interval_s)
        self.settle_ticks = int(settle_ticks)
        self.tolerance = float(tolerance)
        self.breach_freeze_factor = float(breach_freeze_factor)
        self.freeze_cooldown_s = float(freeze_cooldown_s)
        self._lock = threading.RLock()
        self._state = WATCHING
        self._frozen_reason: Optional[str] = None
        self._healthy_since: Optional[float] = None
        self._seq = 0
        self._pending: Optional[Dict[str, Any]] = None
        self._known_good = self._snapshot()
        self._trail: "collections.deque" = collections.deque(maxlen=256)
        self._rotation = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        register_metrics()
        reg = registry()
        self._moves_c = reg.counter("serving_tuner_moves_total")
        self._reverts_c = reg.counter("serving_tuner_reverts_total")
        self._freezes_c = reg.counter("serving_tuner_freezes_total")
        self._errors_c = reg.counter("serving_tuner_errors_total")
        self._frozen_g = reg.gauge("serving_tuner_frozen")
        self._state_g = reg.gauge("serving_tuner_state")
        self._frozen_g.set(0.0)
        self._state_g.set(0.0)

    # ----------------------------------------------------------- internals
    def _snapshot(self) -> Dict[str, float]:
        return {k.name: k.get() for k in self.knobs}

    def _emit(self, kind: str, **fields) -> Dict[str, Any]:
        """Build, trail, and append one ledger row. ValueError (a
        schema bug) propagates loudly; OSError (unwritable ledger) must
        never take down the control loop — the in-memory trail still
        records the decision."""
        with self._lock:
            self._seq += 1
            entry: Dict[str, Any] = {
                "schema": LEDGER_SCHEMA_VERSION,
                "ts": round(float(self._clock()), 3),
                "seq": self._seq, "kind": kind}
            entry.update(fields)
            self._trail.append(entry)
        try:
            append_entry(entry, self.ledger_path)
        except OSError:
            pass
        return entry

    def _set_state(self, state: str) -> None:
        self._state = state
        self._state_g.set(float(_STATE_VALUES[state]))
        self._frozen_g.set(1.0 if state == FROZEN else 0.0)

    # ---------------------------------------------------------------- tick
    def tick(self) -> MonitorReport:
        """One control-loop step: scrape → verdicts → (freeze |
        settle-evaluate | move). Safe to call manually alongside a
        running thread (the state machine is lock-guarded)."""
        report = self.monitor.tick()
        with self._lock:
            self._tick_locked(report)
        return report

    def _tick_locked(self, report: MonitorReport) -> None:
        reason = self._freeze_reason(report)
        if self._state == FROZEN:
            if reason is not None:
                self._healthy_since = None
                return
            if self._healthy_since is None:
                self._healthy_since = report.ts
            elif report.ts - self._healthy_since >= self.freeze_cooldown_s:
                self._unfreeze_locked(report.ts - self._healthy_since)
            return
        if reason is not None:
            self._freeze_locked(reason, report)
            return
        if self._pending is not None:
            self._pending["ticks_left"] -= 1
            if self._pending["ticks_left"] > 0:
                return
            self._resolve_pending_locked(report)
            return
        if report.healthy:
            # A healthy steady state IS the known-good config.
            self._known_good = self._snapshot()
            return
        self._try_move_locked(report)

    def _freeze_reason(self, report: MonitorReport) -> Optional[str]:
        if report.breakers_open:
            return "breaker_open"
        if report.canary_rejections > 0:
            return "canary_rejected"
        for v in report.sampled():
            # A mild breach is the tuning signal; a HARD breach
            # (factor× over budget) is an incident — stop tuning.
            if v.slo_ms > 0 and \
                    v.p99_ms >= self.breach_freeze_factor * v.slo_ms:
                return "slo_breach"
        return None

    def _freeze_locked(self, reason: str, report: MonitorReport) -> None:
        restored: Dict[str, float] = {}
        for k in self.knobs:
            good = self._known_good.get(k.name)
            if good is None:
                continue
            try:
                if k.get() != good:
                    k.apply(good)
                    restored[k.name] = good
            except Exception:
                self._errors_c.inc()  # actuator down mid-incident
        self._pending = None
        self._frozen_reason = reason
        self._healthy_since = None
        self._set_state(FROZEN)
        self._freezes_c.labels(reason=reason).inc()
        self._emit("freeze", reason=reason, evidence=report.evidence(),
                   restored=restored)

    def _unfreeze_locked(self, healthy_s: float) -> None:
        self._frozen_reason = None
        self._healthy_since = None
        self._set_state(WATCHING)
        self._emit("unfreeze", healthy_s=round(float(healthy_s), 3))

    def unfreeze(self) -> None:
        """Operator override: thaw now instead of waiting out the
        cooldown (the freeze itself stays ledgered)."""
        with self._lock:
            if self._state == FROZEN:
                self._unfreeze_locked(0.0)

    def _resolve_pending_locked(self, report: MonitorReport) -> None:
        p = self._pending
        self._pending = None
        knob: Knob = p["knob"]
        before, after = p["before_score"], report.score
        reverted = False
        if after <= before * (1.0 - self.tolerance):
            outcome = "kept"
            self._known_good = self._snapshot()
        elif after >= before * (1.0 + self.tolerance):
            outcome = "reverted"
            reverted = True
            try:
                knob.apply(p["old"])  # exact prior value — bitwise
            except Exception:
                self._errors_c.inc()
            knob.direction = -knob.direction
            self._reverts_c.inc()
        else:
            outcome = "neutral"
        self._set_state(WATCHING)
        self._moves_c.labels(knob=knob.name, outcome=outcome).inc()
        self._emit("outcome", ref=p["seq"], knob=knob.name,
                   outcome=outcome, old=p["old"], new=p["new"],
                   before_score=round(before, 4),
                   after_score=round(after, 4), reverted=reverted,
                   evidence=report.evidence())

    def _pick_knob_locked(self, report: MonitorReport) -> Optional[Knob]:
        # _locked suffix: only ever called from _try_move_locked, with
        # the tuner lock held — _rotation is guarded by the caller.
        worst = report.worst
        if worst is not None and worst.top_phase:
            prefs: List[Knob] = []
            if worst.top_phase == "queue_wait":
                prefs = [k for k in self.knobs
                         if k.name.startswith("linger_ms:")]
            elif worst.top_phase == "sched_wait":
                prefs = [k for k in self.knobs if k.name == "quantum"
                         or k.name.startswith("weight:")]
            prefs = [k for k in prefs if k.tier in (None, worst.tier)]
            if prefs:
                k = prefs[self._rotation % len(prefs)]
                self._rotation += 1
                return k
        if not self.knobs:
            return None
        k = self.knobs[self._rotation % len(self.knobs)]
        self._rotation += 1
        return k

    def _try_move_locked(self, report: MonitorReport) -> None:
        knob = self._pick_knob_locked(report)
        if knob is None:
            return
        new, raw, cur = knob.propose()
        if new is None:
            self._moves_c.labels(knob=knob.name, outcome="refused").inc()
            self._emit("refusal", knob=knob.name, candidate=float(raw),
                       lo=knob.lo, hi=knob.hi, reason="guardrail")
            knob.direction = -knob.direction
            return
        try:
            knob.apply(new)
        except Exception as e:
            self._moves_c.labels(knob=knob.name, outcome="refused").inc()
            self._emit("refusal", knob=knob.name, candidate=float(new),
                       lo=knob.lo, hi=knob.hi,
                       reason=f"actuator rejected: {e}")
            return
        self._moves_c.labels(knob=knob.name, outcome="applied").inc()
        entry = self._emit("move", knob=knob.name, old=cur,
                           new=float(new), direction=knob.direction,
                           evidence=report.evidence())
        self._pending = {"seq": entry["seq"], "knob": knob, "old": cur,
                         "new": float(new),
                         "before_score": report.score,
                         "ticks_left": self.settle_ticks}
        self._set_state(SETTLING)

    # ----------------------------------------------------------- lifecycle
    def start(self, interval_s: Optional[float] = None) -> "AutoTuner":
        """Run tick() every interval_s on a daemon thread (set the
        interval BEFORE start — it is read by the running loop)."""
        if interval_s is not None:
            self.interval_s = float(interval_s)
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="serving-autotuner", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # The control loop must never take down serving.
                self._errors_c.inc()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # ----------------------------------------------------------- introspect
    def trail(self, n: int = 50) -> List[Dict[str, Any]]:
        """The last n decision rows (in-memory mirror of the ledger)."""
        with self._lock:
            return [dict(e) for e in list(self._trail)[-int(n):]]

    def describe(self) -> Dict[str, Any]:
        """GET /debug/tuner body: state, knob table with guardrails,
        known-good snapshot, pending move, recent decision trail."""
        with self._lock:
            pending = None
            if self._pending is not None:
                pending = {"knob": self._pending["knob"].name,
                           "old": self._pending["old"],
                           "new": self._pending["new"],
                           "ticks_left": self._pending["ticks_left"]}
            return {
                "state": self._state,
                "frozen_reason": self._frozen_reason,
                "interval_s": self.interval_s,
                "settle_ticks": self.settle_ticks,
                "tolerance": self.tolerance,
                "breach_freeze_factor": self.breach_freeze_factor,
                "freeze_cooldown_s": self.freeze_cooldown_s,
                "window_s": getattr(self.monitor, "window_s", None),
                "ledger_path": self.ledger_path,
                "knobs": [{"name": k.name, "value": k.get(),
                           "lo": k.lo, "hi": k.hi, "step": k.step,
                           "mode": k.mode, "direction": k.direction,
                           "tier": k.tier} for k in self.knobs],
                "known_good": dict(self._known_good),
                "pending": pending,
                "trail": [dict(e) for e in list(self._trail)[-50:]],
            }
