"""Per-model circuit breaker for the serving path (docs/serving.md).

State machine (the classic Nygard breaker, shaped for coalesced-forward
serving):

    CLOSED ──(N consecutive batch failures, or ONE non-finite-output
              trip)──► OPEN ──(reset_timeout_s elapsed)──► HALF_OPEN
    HALF_OPEN ──(probe forward succeeds)──► CLOSED
    HALF_OPEN ──(probe forward fails)────► OPEN (cooldown restarts)

While OPEN the gateway fast-fails ``/predict`` with a distinct 503
``breaker_open`` status instead of queuing requests against a model
that cannot answer them — the queue slots and forward capacity go to
healthy models, and ``/health`` reports the deployment degraded.
HALF_OPEN admits ONE probe request at a time (a probe that dies before
reaching a forward — shed, queue-full — releases its slot after
``probe_timeout_s`` so the breaker can never wedge half-open).

Outcomes are recorded from the engine's batch hooks (ModelPool wires
``on_batch``/``on_batch_error``), so a breaker sees exactly what the
coalesced forwards did — including the instant trip when a forward
returns NaN/Inf rows under ``check_finite``.

Metrics: ``serving_breaker_state{model}`` gauge (0=closed, 1=open,
2=half_open), ``serving_breaker_transitions_total{model,to}`` counter,
and ``serving_batch_failures_total{model}`` (bumped by the pool's
failure hook, pre-registered here so every scrape carries the family).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..optimize.metrics import registry

__all__ = ["BreakerOpenError", "CircuitBreaker", "CLOSED", "OPEN",
           "HALF_OPEN", "STATE_VALUES", "register_metrics"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# Gauge encoding for serving_breaker_state (documented in
# docs/observability.md — alert on value == 1).
STATE_VALUES = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}


class BreakerOpenError(RuntimeError):
    """Fast-fail: the model's circuit breaker is open (or half-open with
    a probe already in flight) — the request was rejected without
    taking a queue slot. Maps to HTTP 503 ``breaker_open``."""


def register_metrics() -> None:
    """Pre-register the breaker/chaos metric families so a snapshot
    (bench.py --once) records serving resilience activity — including
    its absence — before any breaker exists."""
    reg = registry()
    reg.gauge("serving_breaker_state",
              "Circuit breaker state per model (0=closed, 1=open, "
              "2=half_open)")
    reg.counter("serving_breaker_transitions_total",
                "Breaker state transitions by target state")
    reg.counter("serving_batch_failures_total",
                "Coalesced forwards that raised or returned non-finite "
                "outputs")


class CircuitBreaker:
    """Thread-safe three-state breaker guarding one served model.

    `failure_threshold` consecutive batch failures open it; a
    NonFiniteOutputError (``record_failure(trip=True)``) opens it
    immediately. After `reset_timeout_s` the next `allow()` admits one
    half-open probe; its outcome recloses or reopens the breaker.
    `clock` is injectable for deterministic tests."""

    def __init__(self, model: str = "", *, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 probe_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.model = model
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        # A half-open probe that never produces an outcome (it was shed
        # before reaching a forward) frees its slot after this long.
        self.probe_timeout_s = float(
            reset_timeout_s if probe_timeout_s is None else probe_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_started: Optional[float] = None
        reg = registry()
        self._state_g = reg.gauge(
            "serving_breaker_state",
            "Circuit breaker state per model (0=closed, 1=open, "
            "2=half_open)").labels(model=model)
        self._trans_c = reg.counter(
            "serving_breaker_transitions_total",
            "Breaker state transitions by target state")
        self._state_g.set(STATE_VALUES[CLOSED])

    # ------------------------------------------------------------- state
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def consecutive_failures(self) -> int:
        with self._lock:
            return self._consecutive

    def _transition(self, to: str) -> None:
        # callers hold self._lock
        self._state = to
        self._state_g.set(STATE_VALUES[to])
        self._trans_c.labels(model=self.model, to=to).inc()

    # ---------------------------------------------------------- decisions
    def allow(self) -> bool:
        """Admission decision for one request. CLOSED always admits;
        OPEN fast-fails until the cooldown elapses, then flips to
        HALF_OPEN and admits one probe; HALF_OPEN admits a new probe
        only when none is in flight (or the last one timed out)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN:
                if now - self._opened_at < self.reset_timeout_s:
                    return False
                self._transition(HALF_OPEN)
                self._probe_started = now
                return True
            # HALF_OPEN
            if (self._probe_started is not None and
                    now - self._probe_started < self.probe_timeout_s):
                return False
            self._probe_started = now
            return True

    def record_success(self) -> None:
        """A forward served rows: reset the failure run; a half-open
        probe success recloses the breaker."""
        with self._lock:
            self._consecutive = 0
            self._probe_started = None
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self, *, trip: bool = False) -> None:
        """A forward failed. `trip=True` (non-finite outputs) opens the
        breaker immediately; otherwise `failure_threshold` consecutive
        failures open it. A half-open probe failure reopens it."""
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN:
                self._probe_started = None
                self._opened_at = self._clock()
                self._transition(OPEN)
            elif self._state == CLOSED and (
                    trip or self._consecutive >= self.failure_threshold):
                self._opened_at = self._clock()
                self._transition(OPEN)
            # already OPEN: a straggler failure from a forward that was
            # in flight when the breaker opened changes nothing.

    def reconfigure(self, *, failure_threshold: Optional[int] = None,
                    reset_timeout_s: Optional[float] = None) -> dict:
        """Live knob set — the ``POST /config`` / ``pool.reconfigure`` /
        AutoTuner actuator seam (docs/serving.md). Validates BOTH values
        before mutating either, so an invalid request changes nothing.
        Takes effect on the next decision: a raised threshold does not
        retroactively reclose an open breaker, a shortened cooldown is
        honored by the next ``allow()``."""
        ft = rt = None
        if failure_threshold is not None:
            ft = int(failure_threshold)
            if ft < 1:
                raise ValueError("failure_threshold must be >= 1")
        if reset_timeout_s is not None:
            rt = float(reset_timeout_s)
            if rt <= 0:
                raise ValueError("reset_timeout_s must be > 0")
        with self._lock:
            if ft is not None:
                self.failure_threshold = ft
            if rt is not None:
                self.reset_timeout_s = rt
        return self.describe()

    def describe(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive,
                    "failure_threshold": self.failure_threshold,
                    "reset_timeout_s": self.reset_timeout_s}
