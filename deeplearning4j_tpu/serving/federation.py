"""Replica federation: multi-replica serving behind one routing
front-end (docs/serving.md §"Replica federation").

The serving plane's scale-out + survival layer. N replica processes —
each running the FULL gateway stack (ServingGateway over a ModelPool)
on its own port, spawned the way the multihost harness spawns workers —
sit behind a :class:`FederationFrontEnd` that owns routing, membership,
failover, and rolling deploys. Same model code, one replica to many
(the "same code, 8 chips to 6000" theme): a replica never knows it is
federated.

Membership rides the PR-9 heartbeat plane (parallel/cluster_health.py):
every replica publishes ``kind="replica"`` beats carrying its URL and
its gateway's admission load (``queue_depth`` / EWMA ``est_wait_s`` —
ServingGateway.load()) into the front-end's chief-stamped beat table
(the same InProcessBeatTransport + beat_ages staleness rule the
training watchdog evaluates). Per-replica state machine::

    (first beat) ──────────────────────────▶ JOINING   (not routable)
    JOINING ──beat with warmed=True────────▶ HEALTHY   (routable)
    HEALTHY ──POST /swap steering──────────▶ DRAINING  (not routable,
                                                        beats fresh)
    DRAINING ──swap leg done───────────────▶ HEALTHY
    any ──beats dark past timeout_s,
          or a connection-dead dispatch────▶ DEAD      (evicted)
    DEAD ──fresh beat (recovered /
           replacement replica)────────────▶ JOINING   (rejoins; takes
                                                        traffic again
                                                        only once its
                                                        beats say
                                                        warmed — zero
                                                        dropped
                                                        requests)

Dispatch is weighted least-loaded: each replica's score is
``(1 + frontend_inflight + queue_depth) * (1 + est_wait_s) / weight``
(the front-end's own in-flight count is the freshest term; the scraped
gauges catch load the front-end didn't route). Lowest score wins.

Failover is typed and exactly-once. A request on a replica that dies
mid-flight fails with :class:`ReplicaLostError` — a subclass of the
serving chain's ServerClosedError, so every existing handler that
understands "the server went away" already understands "the replica
went away" — and a **predict** request is retried on a sibling AT MOST
ONCE. The retry is deduplicated by request id: the in-flight record
carries a claim bit, and every failure path (the dispatch thread's
connection error, the eviction sweep) goes through the same
claim-or-wait gate, so two concurrent failover signals can never
double-dispatch the retry. A **generate** request is NEVER retried
mid-decode (a sibling has no KV state for it — a silent regenerate
could emit a divergent continuation): it fails typed with
``tokens_so_far`` attached. The full semantics, including the one
honest caveat (a falsely-evicted replica may still complete the
original forward after the sibling retry — pure inference, no side
effects, and the client sees exactly one response), are in
docs/serving.md.

Rolling zero-traffic deploys: ``POST /swap`` on the front-end runs the
pool's existing checkpoint-gated canary swap on ONE replica first —
after steering traffic away (DRAINING) and waiting for its in-flight
count to reach zero, so the replica's pause window contains no
federation traffic — then promotes the rest one at a time the same
way. A canary rejection aborts the roll with the canary's params
already rolled back bitwise by the replica's own swap protocol, and
every other replica untouched.

Chaos hooks (utils/faults.py): ``route.dispatch`` fires before every
dispatch leg, ``replica.beat`` before every replica beat publish —
both env-armable in subprocesses (DL4JTPU_FAULT_ROUTE_DISPATCH /
DL4JTPU_FAULT_REPLICA_BEAT).

Metrics: ``serving_replicas{state}`` population gauge,
``serving_replica_evictions_total{reason}``,
``serving_failover_retries_total{outcome}``
(ok / failed / no_sibling / decode_suppressed), and
``serving_replica_dispatch_total{replica}``.

Run a replica from the command line (the multihost worker pattern —
this is what spawn_replica() execs)::

    python -m deeplearning4j_tpu.serving.federation \
        --replica-id 0 --frontend http://127.0.0.1:8000 \
        [--port 0] [--builder pkg.mod:fn] [--interval-s 0.5]
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import urllib.error
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..optimize.metrics import registry
from ..parallel.cluster_health import (KIND_REPLICA, HealthConfig,
                                       InProcessBeatTransport, beat_ages)
from ..parallel.inference import ServerClosedError
from ..utils import faults
from ..utils.http_server import JsonHttpServer, json_request

log = logging.getLogger(__name__)

__all__ = ["ReplicaLostError", "FederationFrontEnd", "ReplicaServer",
           "serve_replica", "spawn_replica", "default_builder",
           "register_metrics", "JOINING", "HEALTHY", "DRAINING", "DEAD"]

# Replica membership states (docs/serving.md §"Replica federation").
JOINING = "joining"
HEALTHY = "healthy"
DRAINING = "draining"
DEAD = "dead"
STATES = (JOINING, HEALTHY, DRAINING, DEAD)


class ReplicaLostError(ServerClosedError):
    """The replica holding this request died (beats dark past
    timeout_s, or its socket went away mid-request) — a member of the
    serving typed-error chain via ServerClosedError, so it maps to the
    same 503 family every client already handles. ``replica`` names
    the lost member; ``tokens_so_far`` carries a decode request's
    partial progress (always present, possibly empty — decode is never
    retried mid-stream, the client decides whether to resume)."""

    transient = True  # retryable, like faults.FaultInjected

    def __init__(self, message: str, *, replica: Optional[int] = None,
                 tokens_so_far: Optional[List[Any]] = None):
        super().__init__(message)
        self.replica = replica
        self.tokens_so_far = list(tokens_so_far or [])


_HELP = {
    "serving_replicas":
        "Federation replica population by membership state",
    "serving_replica_evictions_total":
        "Replicas evicted from the federation, by reason "
        "(beat_timeout | dispatch)",
    "serving_failover_retries_total":
        "Failover outcomes for requests whose replica died mid-flight "
        "(ok | failed | no_sibling | decode_suppressed)",
    "serving_replica_dispatch_total":
        "Requests dispatched to each replica (retry legs included)",
}


def register_metrics() -> None:
    """Pre-register the federation families at 0 (bench --once
    pattern) so scrapes and the scoreboard distinguish 'no federation
    activity' from 'no federation'. The population gauge is touched at
    every state so a snapshot always carries the full state axis."""
    reg = registry()
    g = reg.gauge("serving_replicas", _HELP["serving_replicas"])
    for state in STATES:
        g.touch(state=state)
    for name in ("serving_replica_evictions_total",
                 "serving_failover_retries_total",
                 "serving_replica_dispatch_total"):
        reg.counter(name, _HELP[name])


def _http_transport(url: str, payload: Optional[dict],
                    timeout: float) -> Tuple[int, dict]:
    """Default dispatch transport: one JSON POST (GET when payload is
    None). A non-2xx reply from a LIVE replica is not a transport
    failure — its typed body passes through verbatim so the client
    sees exactly the status the replica chose. Connection-level
    errors (refused/reset/timeout) propagate for the caller to
    convert into ReplicaLostError."""
    try:
        return 200, json_request(url, payload, timeout=timeout)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode())
        except Exception:
            body = {"status": "error", "error": f"HTTP {e.code}"}
        return e.code, body


class _Replica:
    """One membership record; every field mutates under the
    front-end's lock."""

    __slots__ = ("id", "url", "state", "weight", "warmed", "queue_depth",
                 "est_wait_s", "inflight", "dispatched", "evictions")

    def __init__(self, rid: int, url: str, weight: float = 1.0):
        self.id = int(rid)
        self.url = str(url)
        self.state = JOINING
        self.weight = float(weight)
        self.warmed = False
        self.queue_depth = 0
        self.est_wait_s = 0.0
        self.inflight: Set["_Request"] = set()
        self.dispatched = 0
        self.evictions = 0

    def describe(self, age: Optional[float] = None) -> Dict[str, Any]:
        d = {"id": self.id, "url": self.url, "state": self.state,
             "weight": self.weight, "warmed": self.warmed,
             "queue_depth": self.queue_depth,
             "est_wait_s": self.est_wait_s,
             "inflight": len(self.inflight),
             "dispatched": self.dispatched}
        if age is not None:
            d["beat_age_s"] = round(age, 3)
        return d


class _Request:
    """One in-flight request record — the exactly-once unit.

    ``retried`` is the failover claim bit: every failure path calls
    :meth:`FederationFrontEnd._fail_over`, which atomically
    claims-or-waits on it, so at most ONE retry leg is ever
    dispatched for this request id. ``settled`` is the client-outcome
    bit: the first writer wins, every later writer discards its
    result, so the client sees exactly one response even when the
    original forward and the retry race to completion."""

    __slots__ = ("rid", "kind", "payload", "tried", "retried",
                 "settled", "status", "body", "error", "done")

    def __init__(self, rid: str, kind: str, payload: dict):
        self.rid = rid
        self.kind = kind
        self.payload = payload
        self.tried: Set[int] = set()
        self.retried = False
        self.settled = False
        self.status = 0
        self.body: dict = {}
        self.error: Optional[Exception] = None
        self.done = threading.Event()


class FederationFrontEnd(JsonHttpServer):
    """The routing front-end: membership, weighted least-loaded
    dispatch, typed exactly-once failover, rolling swap, config
    fan-out (see module docstring).

    ``health`` reuses the heartbeat plane's HealthConfig — only
    ``interval_s`` (eviction-sweep cadence) and ``timeout_s``
    (beats-dark eviction threshold) apply here. ``transport`` and
    ``clock`` are injectable for deterministic tests: transport is
    ``fn(url, payload_or_None, timeout_s) -> (status, body)`` raising
    OSError/URLError on a dead peer."""

    def __init__(self, *, port: int = 0, pool_size: int = 8,
                 health: Optional[HealthConfig] = None,
                 request_timeout_s: float = 30.0,
                 swap_timeout_s: float = 120.0,
                 drain_timeout_s: float = 10.0,
                 transport: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(
            get_routes={"/health": self._health_route,
                        "/replicas": self._replicas_route,
                        "/stats": self._stats_route},
            post_routes={"/predict": self._predict_route,
                         "/generate": self._generate_route,
                         "/swap": self._swap_route,
                         "/config": self._config_route,
                         "/beat": self._beat_route},
            port=port, pool_size=pool_size, expose_metrics=True)
        self.health = health or HealthConfig(interval_s=0.5,
                                             timeout_s=10.0)
        self.request_timeout_s = float(request_timeout_s)
        self.swap_timeout_s = float(swap_timeout_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._transport = transport or _http_transport
        self._clock = clock
        self._lock = threading.RLock()
        self._replicas: Dict[int, _Replica] = {}
        # The PR-9 beat table, verbatim: replicas POST /beat into it,
        # the sweep evaluates it with the same beat_ages rule the
        # training watchdog uses.
        self._beats = InProcessBeatTransport(clock)
        self._rid_counter = 0
        self._requests = {"predict": 0, "generate": 0}
        self._swap_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._sweeper: Optional[threading.Thread] = None
        register_metrics()
        reg = registry()
        self._pop_g = reg.gauge("serving_replicas",
                                _HELP["serving_replicas"])
        self._evict_c = reg.counter("serving_replica_evictions_total",
                                    _HELP["serving_replica_evictions_total"])
        self._retry_c = reg.counter("serving_failover_retries_total",
                                    _HELP["serving_failover_retries_total"])
        self._dispatch_c = reg.counter(
            "serving_replica_dispatch_total",
            _HELP["serving_replica_dispatch_total"])

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "FederationFrontEnd":
        super().start()
        self._stop_evt.clear()
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         daemon=True,
                                         name="federation-sweep")
        self._sweeper.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._sweeper
        self._sweeper = None
        if t is not None:
            t.join(timeout=5)
        super().stop()

    def _sweep_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.poll_once()
            except Exception:
                log.exception("federation sweep error (continuing)")
            self._stop_evt.wait(self.health.interval_s)

    # ----------------------------------------------------------- membership
    def _beat_route(self, payload: dict):
        """POST /beat — replica membership heartbeat. First beat from
        an unknown id registers it JOINING; a beat from a DEAD member
        is the rejoin path (recovered or replacement process — back to
        JOINING, routable again only once warmed). Load gauges ride
        every beat."""
        try:
            rid = int(payload["process_id"])
            url = str(payload["url"])
        except (KeyError, TypeError, ValueError):
            return 400, {"status": "error",
                         "error": "beat needs process_id and url"}
        self._beats.publish(payload)
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                rep = self._replicas[rid] = _Replica(
                    rid, url, weight=float(payload.get("weight", 1.0)))
            rep.url = url
            if "weight" in payload:
                rep.weight = float(payload["weight"])
            rep.queue_depth = int(payload.get("queue_depth", 0))
            rep.est_wait_s = float(payload.get("est_wait_s", 0.0))
            rep.warmed = bool(payload.get("warmed", False))
            if rep.state == DEAD:
                rep.state = JOINING
            if rep.state == JOINING and rep.warmed:
                rep.state = HEALTHY
            self._refresh_population()
        return 200, {"ok": True, "state": rep.state,
                     "now": self._clock()}

    def poll_once(self) -> List[int]:
        """One eviction sweep over the beat table (the loop body;
        callable directly with a fake clock in tests). Returns the ids
        evicted this pass."""
        ages = beat_ages(self._beats.table())
        stale: List[_Replica] = []
        with self._lock:
            for rep in self._replicas.values():
                if rep.state == DEAD:
                    continue
                age = ages.get(str(rep.id))
                if age is not None and age > self.health.timeout_s:
                    stale.append(rep)
        for rep in stale:
            self._evict(rep, reason="beat_timeout")
        return [r.id for r in stale]

    def _evict(self, rep: _Replica, *, reason: str) -> None:
        """Remove a replica from the routable set and fail its
        in-flight requests typed — each through the same exactly-once
        failover gate the dispatch threads use, so a request whose
        connection error races this sweep still produces ONE retry and
        ONE client response."""
        with self._lock:
            if rep.state == DEAD:
                return
            rep.state = DEAD
            rep.warmed = False
            rep.evictions += 1
            inflight = list(rep.inflight)
            rep.inflight.clear()
            self._refresh_population()
        self._evict_c.labels(reason=reason).inc()
        log.warning("federation: evicted replica %d (%s), "
                    "%d in-flight to fail over", rep.id, reason,
                    len(inflight))
        for req in inflight:
            threading.Thread(
                target=self._fail_over, args=(req, rep),
                kwargs={"cause": ReplicaLostError(
                    f"replica {rep.id} evicted ({reason})",
                    replica=rep.id)},
                daemon=True, name=f"federation-failover-{req.rid}",
            ).start()

    def _refresh_population(self) -> None:
        # caller holds self._lock
        counts = {s: 0 for s in STATES}
        for rep in self._replicas.values():
            counts[rep.state] += 1
        for state, n in counts.items():
            self._pop_g.labels(state=state).set(float(n))

    def wait_for_replicas(self, n: int, timeout: float = 60.0) -> bool:
        """Block until `n` replicas are HEALTHY (bench/test
        convenience). Wall-clock bound, not fake-clock driven."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                healthy = sum(1 for r in self._replicas.values()
                              if r.state == HEALTHY)
            if healthy >= n:
                return True
            time.sleep(0.05)
        return False

    # ------------------------------------------------------------- dispatch
    def _pick(self, exclude: Set[int] = frozenset()) -> _Replica:
        """Weighted least-loaded choice among HEALTHY members:
        score = (1 + inflight + queue_depth) * (1 + est_wait_s) / weight,
        lowest wins (ties: lowest id, deterministic). Raises
        ReplicaLostError when no routable replica exists."""
        with self._lock:
            best: Optional[_Replica] = None
            best_score = float("inf")
            for rep in sorted(self._replicas.values(),
                              key=lambda r: r.id):
                if rep.state != HEALTHY or rep.id in exclude:
                    continue
                score = ((1.0 + len(rep.inflight) + rep.queue_depth)
                         * (1.0 + rep.est_wait_s) / rep.weight)
                if score < best_score:
                    best, best_score = rep, score
            if best is None:
                raise ReplicaLostError(
                    "no healthy replica available"
                    + (f" (excluding {sorted(exclude)})" if exclude
                       else ""))
            return best

    def _next_rid(self) -> str:
        with self._lock:
            self._rid_counter += 1
            return f"fe-{os.getpid()}-{self._rid_counter}"

    def _post_once(self, rep: _Replica, req: _Request) -> Tuple[int, dict]:
        """One dispatch leg: the route.dispatch chaos point, the
        per-replica counter, then the transport call. Raises
        FaultInjected (dropped leg) or OSError/URLError (dead
        replica)."""
        faults.fire("route.dispatch")
        self._dispatch_c.labels(replica=str(rep.id)).inc()
        with self._lock:
            rep.dispatched += 1
        return self._transport(rep.url + "/" + req.kind, req.payload,
                               self.request_timeout_s)

    def _settle(self, req: _Request, status: int, body: dict,
                error: Optional[Exception] = None) -> bool:
        """First writer wins; the client sees exactly one outcome."""
        with self._lock:
            if req.settled:
                return False
            req.settled = True
            req.status, req.body, req.error = status, body, error
        req.done.set()
        return True

    def _track(self, rep: _Replica, req: _Request) -> None:
        with self._lock:
            req.tried.add(rep.id)
            if rep.state != DEAD:
                rep.inflight.add(req)

    def _untrack(self, rep: _Replica, req: _Request) -> None:
        with self._lock:
            rep.inflight.discard(req)

    def _lost_body(self, err: ReplicaLostError, req: _Request) -> dict:
        body = {"status": "unavailable", "reason": "replica_lost",
                "error": str(err), "request_id": req.rid}
        if req.kind == "generate":
            body["tokens_so_far"] = err.tokens_so_far
        return body

    def dispatch(self, kind: str, payload: dict) -> Tuple[int, dict]:
        """Route one request (in-process entry point; the HTTP routes
        are thin wrappers). Returns (status, body) — replica-typed
        statuses pass through verbatim; a lost replica yields a typed
        503 ``replica_lost`` after the exactly-once failover gate."""
        payload = dict(payload)
        rid = str(payload.get("request_id") or self._next_rid())
        payload["request_id"] = rid
        req = _Request(rid, kind, payload)
        with self._lock:
            self._requests[kind] = self._requests.get(kind, 0) + 1
        rep = self._pick()  # ReplicaLostError propagates to the route
        self._track(rep, req)
        try:
            status, body = self._post_once(rep, req)
        except faults.FaultInjected as e:
            # A dropped ROUTE leg, not a dead replica: failover without
            # evicting the member.
            self._untrack(rep, req)
            return self._fail_over(req, rep, cause=e)
        except (OSError, urllib.error.URLError) as e:
            self._untrack(rep, req)
            self._evict(rep, reason="dispatch")
            return self._fail_over(req, rep, cause=e)
        self._untrack(rep, req)
        if self._settle(req, status, body):
            return status, body
        # The eviction sweep failed this request over while the
        # original forward was still completing; the settled outcome
        # is the client's answer (exactly one response).
        req.done.wait(timeout=self.request_timeout_s + 5.0)
        return req.status, req.body

    def _fail_over(self, req: _Request, from_rep: _Replica, *,
                   cause: Exception) -> Tuple[int, dict]:
        """The exactly-once failover gate. Atomically claims the
        request's single retry; a caller that loses the claim waits
        for the winner's outcome instead of dispatching again. predict
        retries on the least-loaded sibling; generate fails typed with
        tokens_so_far (never retried mid-stream)."""
        with self._lock:
            claimed = not req.retried
            req.retried = True
        if not claimed:
            req.done.wait(timeout=self.request_timeout_s + 5.0)
            if not req.done.is_set():
                err = ReplicaLostError(
                    f"request {req.rid}: failover outcome never "
                    f"arrived after replica {from_rep.id} was lost",
                    replica=from_rep.id)
                self._settle(req, 503, self._lost_body(err, req), err)
            return req.status, req.body
        if req.kind != "predict":
            self._retry_c.labels(outcome="decode_suppressed").inc()
            err = ReplicaLostError(
                f"replica {from_rep.id} lost mid-decode ({cause}); "
                "decode requests are never retried on a sibling — "
                "resume from tokens_so_far", replica=from_rep.id,
                tokens_so_far=[])
            self._settle(req, 503, self._lost_body(err, req), err)
            return req.status, req.body
        try:
            sib = self._pick(exclude=set(req.tried))
        except ReplicaLostError as e:
            self._retry_c.labels(outcome="no_sibling").inc()
            err = ReplicaLostError(
                f"replica {from_rep.id} lost ({cause}) and {e}",
                replica=from_rep.id)
            self._settle(req, 503, self._lost_body(err, req), err)
            return req.status, req.body
        self._track(sib, req)
        try:
            status, body = self._post_once(sib, req)
        except (faults.FaultInjected, OSError,
                urllib.error.URLError) as e:
            self._untrack(sib, req)
            if not isinstance(e, faults.FaultInjected):
                self._evict(sib, reason="dispatch")
            self._retry_c.labels(outcome="failed").inc()
            err = ReplicaLostError(
                f"replica {from_rep.id} lost ({cause}); retry on "
                f"sibling {sib.id} also failed ({e})", replica=sib.id)
            self._settle(req, 503, self._lost_body(err, req), err)
        else:
            self._untrack(sib, req)
            self._retry_c.labels(outcome="ok").inc()
            self._settle(req, status, body)
        return req.status, req.body

    # ---------------------------------------------------------- HTTP routes
    def _predict_route(self, req: dict):
        try:
            return self.dispatch("predict", req)
        except ReplicaLostError as e:
            return 503, {"status": "unavailable", "reason": "replica_lost",
                         "error": str(e)}

    def _generate_route(self, req: dict):
        try:
            return self.dispatch("generate", req)
        except ReplicaLostError as e:
            return 503, {"status": "unavailable", "reason": "replica_lost",
                         "error": str(e), "tokens_so_far": e.tokens_so_far}

    def _health_route(self, _):
        with self._lock:
            counts = {s: 0 for s in STATES}
            for rep in self._replicas.values():
                counts[rep.state] += 1
        healthy = counts[HEALTHY]
        status = ("ok" if healthy and healthy == sum(counts.values())
                  else "degraded" if healthy else "down")
        return 200, {"status": status, "replicas": counts}

    def _replicas_route(self, _):
        ages = beat_ages(self._beats.table())
        with self._lock:
            reps = [r.describe(ages.get(str(r.id)))
                    for r in sorted(self._replicas.values(),
                                    key=lambda r: r.id)]
        return 200, {"replicas": reps, "now": self._clock()}

    def _stats_route(self, _):
        ages = beat_ages(self._beats.table())
        with self._lock:
            reps = [r.describe(ages.get(str(r.id)))
                    for r in sorted(self._replicas.values(),
                                    key=lambda r: r.id)]
            requests = dict(self._requests)
        return 200, {
            "replicas": reps, "requests": requests,
            "evictions": int(self._evict_c.total()),
            "failover_retries": int(self._retry_c.total()),
            "timeout_s": self.health.timeout_s,
            "interval_s": self.health.interval_s}

    # ---------------------------------------------------------- rolling swap
    def _swap_route(self, req: dict):
        """POST /swap — rolling checkpoint deploy across the fleet.
        Canary on ONE replica (traffic steered away first, its own
        golden-batch gate decides), then promote the rest one at a
        time the same way. Any rejection aborts the roll: the failing
        replica's params are already rolled back bitwise by its own
        swap protocol, later replicas are untouched, earlier ones keep
        the new checkpoint (reported, so the operator can re-roll or
        roll back)."""
        if not self._swap_lock.acquire(blocking=False):
            return 409, {"status": "swap_failed",
                         "error": "another rolling swap is in progress"}
        try:
            with self._lock:
                targets = sorted(
                    (r for r in self._replicas.values()
                     if r.state == HEALTHY), key=lambda r: r.id)
            if not targets:
                return 503, {"status": "unavailable",
                             "reason": "replica_lost",
                             "error": "no healthy replica to swap"}
            swapped: List[int] = []
            results: Dict[str, Any] = {}
            for i, rep in enumerate(targets):
                stage = "canary" if i == 0 else "promote"
                out = self._swap_one(rep, req, stage)
                if out is not None:  # typed abort
                    out["swapped"] = swapped
                    return 409, out
                swapped.append(rep.id)
                results[str(rep.id)] = {"stage": stage, "ok": True}
            return 200, {"status": "ok", "canary": targets[0].id,
                         "swapped": swapped, "replicas": results}
        finally:
            self._swap_lock.release()

    def _swap_one(self, rep: _Replica, req: dict,
                  stage: str) -> Optional[dict]:
        """One zero-traffic swap leg: steer away, drain, swap,
        restore. Returns None on success, a typed abort body on
        failure (with the replica back HEALTHY when it is alive and
        bitwise-rolled-back, DEAD when it died mid-swap)."""
        with self._lock:
            if rep.state != HEALTHY:
                return {"status": "swap_failed", "stage": stage,
                        "replica": rep.id,
                        "error": f"replica {rep.id} left the healthy "
                                 f"set mid-roll ({rep.state})"}
            rep.state = DRAINING
            self._refresh_population()
        try:
            if not self._wait_drained(rep):
                return {"status": "swap_failed", "stage": stage,
                        "replica": rep.id,
                        "error": f"replica {rep.id} still had in-flight "
                                 f"requests after {self.drain_timeout_s}s "
                                 "drain window"}
            try:
                status, body = self._transport(
                    rep.url + "/swap", req, self.swap_timeout_s)
            except (OSError, urllib.error.URLError) as e:
                self._evict(rep, reason="dispatch")
                return {"status": "swap_failed", "stage": stage,
                        "replica": rep.id,
                        "error": f"replica {rep.id} died mid-swap: {e}"}
            if status != 200:
                return {"status": "swap_failed", "stage": stage,
                        "replica": rep.id, "detail": body,
                        "error": body.get("error",
                                          f"replica swap HTTP {status}")}
            return None
        finally:
            with self._lock:
                if rep.state == DRAINING:
                    rep.state = HEALTHY
                self._refresh_population()

    def _wait_drained(self, rep: _Replica) -> bool:
        deadline = time.monotonic() + self.drain_timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if not rep.inflight:
                    return True
            time.sleep(0.01)
        with self._lock:
            return not rep.inflight

    # --------------------------------------------------------------- config
    def _config_route(self, req: dict):
        """POST /config — fan the reconfiguration out to every live
        replica (the fleet must stay homogeneous, or least-loaded
        routing would chase config skew). ``replica`` (an id) targets
        one member instead. Response carries each replica's verdict;
        the worst status wins."""
        req = dict(req)
        target = req.pop("replica", None)
        with self._lock:
            reps = sorted((r for r in self._replicas.values()
                           if r.state in (HEALTHY, DRAINING, JOINING)),
                          key=lambda r: r.id)
            if target is not None:
                reps = [r for r in reps if r.id == int(target)]
        if not reps:
            return 503, {"status": "unavailable", "reason": "replica_lost",
                         "error": "no live replica to configure"
                         if target is None else
                         f"no live replica with id {target}"}
        worst = 200
        per: Dict[str, Any] = {}
        for rep in reps:
            try:
                status, body = self._transport(
                    rep.url + "/config", req, self.request_timeout_s)
            except (OSError, urllib.error.URLError) as e:
                status, body = 503, {"status": "error", "error": str(e)}
            per[str(rep.id)] = {"code": status, **body}
            if status != 200 and worst == 200:
                worst = status
        return worst, {"status": "ok" if worst == 200 else "error",
                       "replicas": per}


# ---------------------------------------------------------------------------
# Replica side
# ---------------------------------------------------------------------------

class ReplicaServer:
    """The replica-side beat publisher: a daemon thread that samples
    the local gateway's admission load (ServingGateway.load()) and
    POSTs a ``kind="replica"`` beat to the front-end every
    ``interval_s``. The gateway itself is untouched — a replica is a
    plain single-process gateway plus this thread. ``mark_warmed()``
    flips the beat's ``warmed`` bit, which is what admits the replica
    to the routable set (call it after warmup so a joining replica
    never takes traffic it would have to compile for)."""

    def __init__(self, gateway, *, replica_id: int, frontend_url: str,
                 interval_s: float = 0.5, weight: float = 1.0,
                 beat_timeout_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 transport: Optional[Callable] = None):
        self.gateway = gateway
        self.replica_id = int(replica_id)
        self.frontend_url = frontend_url.rstrip("/")
        self.interval_s = float(interval_s)
        self.weight = float(weight)
        self.beat_timeout_s = float(beat_timeout_s)
        self._clock = clock
        self._transport = transport
        self._warmed = False
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.beat_failures = 0

    def mark_warmed(self) -> None:
        self._warmed = True

    def beat_once(self) -> None:
        """One beat publish. The ``replica.beat`` chaos point fires
        first: ``fail:`` suppresses the beat (the replica goes dark —
        the eviction drill), ``delay:`` slows the channel."""
        faults.fire("replica.beat")
        beat = {"process_id": self.replica_id, "kind": KIND_REPLICA,
                "url": self.gateway.url, "warmed": self._warmed,
                "weight": self.weight, "send_ts": self._clock()}
        beat.update(self.gateway.load())
        if self._transport is not None:
            self._transport(self.frontend_url + "/beat", beat,
                            self.beat_timeout_s)
        else:
            json_request(self.frontend_url + "/beat", beat,
                         timeout=self.beat_timeout_s)

    def _loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                self.beat_once()
            except Exception as e:  # never kill the publisher
                # single writer: only this beat thread ever bumps it
                self.beat_failures += 1  # jaxlint: atomic
                log.debug("replica %d beat failed: %s",
                          self.replica_id, e)
            self._stop_evt.wait(self.interval_s)

    def start(self) -> "ReplicaServer":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"replica-beat-{self.replica_id}")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=5)


def default_builder(gateway) -> None:
    """The stock replica model: a deterministic tiny MLP (fixed seed —
    every replica of a fleet built this way serves bitwise-identical
    params, the homogeneity least-loaded routing assumes). Geometry
    and engine knobs come from the environment so a PARENT process
    (bench, smoke, tests) shapes the fleet without a custom builder:

        DL4JTPU_REPLICA_N_IN / _HIDDEN / _N_OUT   model geometry
        DL4JTPU_REPLICA_BATCH_LIMIT               rows per forward (the
                                                  per-replica "device
                                                  budget")
        DL4JTPU_REPLICA_BATCH_TIMEOUT_MS          collector linger
        DL4JTPU_REPLICA_QUEUE_LIMIT               admission queue bound
        DL4JTPU_REPLICA_CKPT_DIR                  checkpoint dir (arms
                                                  hot-swap)
        DL4JTPU_REPLICA_CANARY_DRIFT              canary max drift
    """
    from .. import (Adam, DenseLayer, InputType, MultiLayerNetwork,
                    NeuralNetConfiguration, OutputLayer, WeightInit)
    env = os.environ.get
    n_in = int(env("DL4JTPU_REPLICA_N_IN", "16"))
    hidden = int(env("DL4JTPU_REPLICA_HIDDEN", "32"))
    n_out = int(env("DL4JTPU_REPLICA_N_OUT", "4"))
    conf = (NeuralNetConfiguration.builder().seed(42)
            .updater(Adam(1e-3)).weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    net = MultiLayerNetwork(conf).init()
    kw: Dict[str, Any] = dict(
        batch_limit=int(env("DL4JTPU_REPLICA_BATCH_LIMIT", "4")),
        batch_timeout_ms=float(
            env("DL4JTPU_REPLICA_BATCH_TIMEOUT_MS", "10.0")),
        queue_limit=int(env("DL4JTPU_REPLICA_QUEUE_LIMIT", "256")))
    ckpt_dir = env("DL4JTPU_REPLICA_CKPT_DIR")
    if ckpt_dir:
        kw["checkpoints"] = ckpt_dir
    drift = env("DL4JTPU_REPLICA_CANARY_DRIFT")
    if drift:
        kw["canary_max_drift"] = float(drift)
    gateway.add_model("default", net, **kw)


def serve_replica(build: Callable, *, replica_id: int,
                  frontend_url: str, port: int = 0,
                  interval_s: float = 0.5, weight: float = 1.0,
                  warmup: bool = True, gateway_kw: Optional[dict] = None):
    """Stand up one replica: a full ServingGateway on its own port
    (``build(gateway)`` registers the models), warmed BEFORE the beat
    says so — a joining replica becomes routable only once its
    compiles are behind it. Returns (gateway, replica_server), both
    started."""
    from .gateway import ServingGateway
    gw = ServingGateway(port=port, **(gateway_kw or {}))
    build(gw)
    gw.start()
    rs = ReplicaServer(gw, replica_id=replica_id,
                       frontend_url=frontend_url,
                       interval_s=interval_s, weight=weight)
    rs.start()  # beat unwarmed immediately: membership sees JOINING
    if warmup:
        gw.warmup()
    rs.mark_warmed()
    return gw, rs


def spawn_replica(replica_id: int, frontend_url: str, *,
                  builder: Optional[str] = None, port: int = 0,
                  interval_s: float = 0.5, env: Optional[dict] = None):
    """Spawn a replica SUBPROCESS running this module's main (the
    multihost harness pattern — tests/bench SIGKILL the handle for
    chaos). `builder` is a ``pkg.mod:fn`` import path (default: the
    stock demo builder); `env` overlays the child environment (e.g.
    JAX_PLATFORMS=cpu, DL4JTPU_REPLICA_* geometry, DL4JTPU_FAULT_*
    chaos arming). Readiness is observed through the front-end's beat
    table (wait_for_replicas), not stdout."""
    import subprocess
    # -c instead of -m: the parent has usually already imported
    # serving.federation, and runpy warns when re-executing a module
    # that is live in sys.modules.
    cmd = [sys.executable, "-c",
           "import sys; from deeplearning4j_tpu.serving.federation "
           "import main; sys.exit(main(sys.argv[1:]))",
           "--replica-id", str(int(replica_id)),
           "--frontend", frontend_url,
           "--port", str(int(port)),
           "--interval-s", str(float(interval_s))]
    if builder:
        cmd += ["--builder", builder]
    child_env = dict(os.environ)
    child_env.update(env or {})
    return subprocess.Popen(cmd, env=child_env)


def _resolve_builder(spec: str) -> Callable:
    import importlib
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise SystemExit(f"--builder {spec!r} must be 'pkg.mod:fn'")
    return getattr(importlib.import_module(mod_name), fn_name)


def main(argv: Optional[List[str]] = None) -> int:
    """Replica process entry point (see module docstring)."""
    import argparse
    import signal as _signal
    p = argparse.ArgumentParser(
        description="deeplearning4j_tpu federation replica")
    p.add_argument("--replica-id", type=int, required=True)
    p.add_argument("--frontend", required=True,
                   help="front-end base URL (http://host:port)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--builder",
                   default="deeplearning4j_tpu.serving.federation"
                           ":default_builder")
    p.add_argument("--interval-s", type=float, default=0.5)
    args = p.parse_args(argv)
    build = _resolve_builder(args.builder)
    gw, rs = serve_replica(build, replica_id=args.replica_id,
                           frontend_url=args.frontend, port=args.port,
                           interval_s=args.interval_s)
    print(f"REPLICA_READY {args.replica_id} {gw.port}", flush=True)
    stop = threading.Event()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    rs.stop()
    gw.pool.shutdown()
    gw.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
