"""Per-request flight recorder for the serving plane: phase-attributed
tail latency, slow-request exemplars, and Chrome-trace serving spans.

A request crosses six layers on its way through the gateway — admission,
WFQ scheduler arbitration, coalesced/packed collection, (fused) forward,
device fence, unslice — and the aggregate families in `ModelPool` can
say a tier's p99 breached its SLO but not *which phase ate the budget*.
This module closes that gap with a Dapper-style trace that costs one
small object per request and zero host syncs:

* `RequestTrace` holds a `perf_counter` origin plus an append-only list
  of **cut-point marks** `(phase, t)`. A mark means "this phase ended
  now"; the phase's start is the previous mark (or the origin). Phases
  are therefore contiguous, monotonic, non-overlapping, and sum to the
  traced wall time *by construction* — no per-phase begin/end pairing
  to get wrong under retries.
* The recorder is process-global and OFF by default. Disabled,
  `new_trace()` returns None and every downstream touch point is one
  `is None` branch: the untraced serving path stays bitwise- and
  compile-count-identical.
* `complete()` runs once per request at response time, off the engine's
  forward lock: it folds the marks into `serving_phase_ms` histograms,
  emits retroactive `tracing.add_span` events (cat="serve") into the
  bounded ring `export_trace_events()` already serves, and — for
  requests that breached their tier SLO, errored, or were shed —
  captures the full timeline + context into a bounded exemplar ring
  surfaced at `GET /debug/requests` and linked from the histogram
  exposition via OpenMetrics-style exemplar comments.

Phase taxonomy (docs/observability.md §"Request flight recorder"):

  admission   gateway entry → engine handoff (breaker/tier/SLO checks)
  queue_wait  collector queue: linger + any prior batch's execution
  pack        batch assembly: concatenate/pad or varlen splice+mask
  sched_wait  engine lock + DeviceScheduler slot wait (incl. swap pause)
  dispatch    slot grant → forward call (host-side submit bookkeeping)
  device      the forward itself + recorder's np.asarray result fence
  prefill     decode only: packed segment-masked prompt forward + KV fill
  decode_step decode only: the iteration-level token loop (per-step marks
              aggregate — the phase sum stays cut-point exact)
  unpack      per-request scatter/unslice + member transform

One-shot requests walk ONESHOT_PHASES; decode requests route device
time through `prefill`/`decode_step` instead of `device`.

`device` opens at the forward CALL, not at a mid-forward fence: on an
async backend the enqueue cost belongs with the computation it enqueues,
and the serving plane deliberately never inserts extra syncs — so a fat
`dispatch` always means host-side submit overhead, by definition.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..optimize import tracing
from ..optimize.metrics import registry

__all__ = [
    "RequestTrace", "PHASES", "ONESHOT_PHASES", "enable", "disable",
    "is_enabled", "clear", "new_trace", "complete", "exemplars",
    "register_metrics", "maybe_enable_from_env", "DEFAULT_EXEMPLAR_RING",
    "ENV_FLAG",
]

#: The full phase taxonomy in path order. Error/shed paths legitimately
#: stop early (a breaker fast-fail has only `admission`); one-shot
#: requests never mark `prefill`/`decode_step` (see ONESHOT_PHASES) and
#: decode requests never mark the one-shot `pack`..`device` window.
PHASES = ("admission", "queue_wait", "pack", "sched_wait", "dispatch",
          "device", "prefill", "decode_step", "unpack")

#: The seven phases every fully-served ONE-SHOT request decomposes into
#: — what `ParallelInference.output()` walks end to end.
ONESHOT_PHASES = ("admission", "queue_wait", "pack", "sched_wait",
                  "dispatch", "device", "unpack")

DEFAULT_EXEMPLAR_RING = 64
ENV_FLAG = "DL4JTPU_FLIGHT_RECORDER"

# Phase durations are small (sub-ms linger to ~SLO); reuse the serving
# latency bucket geometry but extend downward for the fast phases.
PHASE_BUCKETS_MS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
                    50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0)

_lock = threading.Lock()
_enabled = False
_owns_tracing = False  # did enable() turn the span ring on itself?
_exemplars: deque = deque(maxlen=DEFAULT_EXEMPLAR_RING)
_ids = itertools.count(1)

_PHASE_HELP = ("Per-request phase attribution (flight recorder): where "
               "a request's wall latency went")
# complete() runs per response: cache the labeled histogram children so
# the steady state pays one dict read instead of a sorted label-key
# build + registry lock per phase (the registry is a process-global
# singleton, so cached children can never go stale). Plain dict
# get/set — last-writer-wins races just re-do one cheap lookup.
_hist_cache: Dict[Tuple[str, str, str], Any] = {}
_SPAN_NAMES = {p: "serve/" + p for p in PHASES}


def _phase_hist(model: str, tier: str, phase: str):
    key = (model, tier, phase)
    child = _hist_cache.get(key)
    if child is None:
        child = registry().histogram(
            "serving_phase_ms", _PHASE_HELP,
            buckets=PHASE_BUCKETS_MS).labels(
                model=model, tier=tier, phase=phase)
        _hist_cache[key] = child
    return child


class RequestTrace:
    """One request's phase timeline: a perf_counter origin and an
    append-only list of cut-point marks. Allocated at gateway admission,
    threaded through the engine on the `_Request`, finalized by
    `complete()` at response time. The hot path only ever calls
    `mark()` (a perf_counter read + list append) and writes `ctx` keys —
    no locks, no syncs, no allocation beyond this object."""

    __slots__ = ("rid", "model", "tier", "t0", "marks", "ctx")

    def __init__(self, rid: int, model: str, tier: str):
        self.rid = rid
        self.model = model
        self.tier = tier
        self.t0 = time.perf_counter()
        self.marks: List[Tuple[str, float]] = []
        self.ctx: Dict[str, Any] = {}

    def mark(self, phase: str, t: Optional[float] = None) -> None:
        """Record that `phase` ended now (or at perf_counter `t`). The
        phase's start is implicitly the previous mark — repeated marks
        of the same phase (solo-retry attempts) just add segments."""
        self.marks.append(
            (phase, time.perf_counter() if t is None else t))

    def segments(self) -> List[Tuple[str, float, float]]:
        """[(phase, abs_start_s, dur_s)] — contiguous by construction."""
        out = []
        prev = self.t0
        for phase, t in self.marks:
            out.append((phase, prev, max(0.0, t - prev)))
            prev = t
        return out

    def phase_ms(self) -> Dict[str, float]:
        """Total ms per phase (segments of one phase aggregate)."""
        out: Dict[str, float] = {}
        for phase, _, dur in self.segments():
            out[phase] = out.get(phase, 0.0) + dur * 1000.0
        return out

    def summary(self) -> Dict[str, Any]:
        """JSON-ready timeline for response embedding / exemplars."""
        return {
            "id": self.rid,
            "model": self.model,
            "tier": self.tier,
            "phases": [
                {"phase": p,
                 "start_ms": round((s - self.t0) * 1000.0, 4),
                 "ms": round(d * 1000.0, 4)}
                for p, s, d in self.segments()],
            "context": dict(self.ctx),
        }


# ---------------------------------------------------------------------------
# Recorder lifecycle
# ---------------------------------------------------------------------------
def enable(exemplar_ring: int = DEFAULT_EXEMPLAR_RING) -> None:
    """Turn the recorder on. Also enables the span ring (fence_every=0:
    serving never wants the training loop's sampled device fence) if the
    caller hasn't already, and remembers that it did so `disable()`
    restores the prior tracing state."""
    global _enabled, _owns_tracing, _exemplars
    with _lock:
        _exemplars = deque(_exemplars, maxlen=max(1, int(exemplar_ring)))
        if not tracing.is_enabled():
            tracing.enable(fence_every=0)
            _owns_tracing = True
        _enabled = True


def disable() -> None:
    global _enabled, _owns_tracing
    with _lock:
        _enabled = False
        if _owns_tracing:
            tracing.disable()
            _owns_tracing = False


def is_enabled() -> bool:
    return _enabled


def clear() -> None:
    _exemplars.clear()


def maybe_enable_from_env() -> bool:
    """Arm from the environment (`DL4JTPU_FLIGHT_RECORDER=1` or `=N` for
    an N-deep exemplar ring) — the gateway calls this at construction so
    an operator can trace a misbehaving deployment without a code
    change. Returns whether the recorder is enabled afterwards."""
    spec = os.environ.get(ENV_FLAG, "").strip()
    if spec and spec != "0":
        try:
            n = int(spec)
        except ValueError:
            n = DEFAULT_EXEMPLAR_RING
        enable(exemplar_ring=n if n > 1 else DEFAULT_EXEMPLAR_RING)
    return _enabled


# ---------------------------------------------------------------------------
# Per-request API (gateway-facing)
# ---------------------------------------------------------------------------
def new_trace(model: str, tier: str = "standard"
              ) -> Optional[RequestTrace]:
    """Allocate a trace at admission; None when the recorder is off (the
    single branch the disabled path pays)."""
    if not _enabled:
        return None
    return RequestTrace(next(_ids), model, tier)


def complete(trace: Optional[RequestTrace], status: str,
             wall_ms: float, slo_ms: Optional[float] = None,
             want_summary: bool = False) -> Optional[Dict[str, Any]]:
    """Finalize a trace at response time: fold marks into the
    `serving_phase_ms` histograms, emit retroactive serving spans, and
    capture an exemplar when the request breached its SLO, errored, or
    was shed. The JSON-ready summary is built only when an exemplar is
    captured or the caller asks (`want_summary` — the HTTP /predict
    embed); healthy in-process requests skip it. Returns the summary
    when built, else None."""
    if trace is None:
        return None
    segs = trace.segments()
    phase_ms: Dict[str, float] = {}
    for phase, _, dur in segs:
        phase_ms[phase] = phase_ms.get(phase, 0.0) + dur * 1000.0
    model, tier = trace.model, trace.tier
    for phase, ms in phase_ms.items():
        _phase_hist(model, tier, phase).observe(ms)
    if tracing.is_enabled():
        names = _SPAN_NAMES
        tracing.add_spans(
            [(names.get(phase) or "serve/" + phase, start, dur)
             for phase, start, dur in segs],
            cat="serve", model=model, rid=trace.rid)
    slow = slo_ms is not None and wall_ms > slo_ms
    capture = status != "ok" or slow
    if not (capture or want_summary):
        return None
    summary = trace.summary()
    summary["status"] = status
    summary["wall_ms"] = round(float(wall_ms), 4)
    if slo_ms is not None:
        summary["slo_ms"] = float(slo_ms)
    if capture:
        _exemplars.append(summary)  # deque.append is atomic
        # link the scrape surface to the exemplar store: the slowest
        # phase carries this request's id in the exposition comment
        if phase_ms:
            worst = max(phase_ms, key=phase_ms.get)
            _phase_hist(model, tier, worst).exemplar(
                str(trace.rid), phase_ms[worst])
    return summary


def exemplars(model: Optional[str] = None, tier: Optional[str] = None
              ) -> List[Dict[str, Any]]:
    """Captured slow/errored/shed request timelines, newest last,
    optionally filtered (the `GET /debug/requests?model=&tier=`
    surface)."""
    out = list(_exemplars)
    if model:
        out = [e for e in out if e.get("model") == model]
    if tier:
        out = [e for e in out if e.get("tier") == tier]
    return out


def register_metrics() -> None:
    """Pre-register the recorder's families so a scrape distinguishes
    'recorder never fired' from 'families absent'."""
    reg = registry()
    reg.histogram("serving_phase_ms", _PHASE_HELP,
                  buckets=PHASE_BUCKETS_MS)
    reg.counter(
        "serving_slo_breach_total",
        "Requests whose wall latency exceeded their tier's "
        "serving_tier_slo_ms, counted at response time")
