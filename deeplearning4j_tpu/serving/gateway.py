"""Serving gateway: admission, continuous batching, SLO shedding,
per-model routing, and checkpoint-gated hot-swap over one HTTP surface.

The request lifecycle (docs/serving.md):

    POST /predict ──► route (ModelPool) ──► ADMISSION
        │  deadline hopeless (EWMA wait estimate) ──► SHED 503
        │  queue full ──────────────────────────────► SHED 429
        ▼
    continuous-batching engine (ParallelInference): concurrent
    requests coalesce into ONE forward padded to the shared pow2
    bucket (data/padding.next_pow2_bucket), served by the AOT
    executables warmup() built — steady state compiles NOTHING.
        │  deadline passed while queued ──► SHED 503 (late)
        ▼
    row slices scattered back ──► 200 {"predictions", "model",
                                       "version", "latency_ms"}

Adapted from continuous batching (Orca, OSDI '22 — requests join the
next forward, no epoch barriers) and SLO-aware adaptive shedding
(Clipper, NSDI '17 — reject early what cannot make its deadline),
re-shaped for the static-shape XLA world: the batch axis quantizes to
power-of-two buckets so the executable set is finite and precompiled.

Resilience (docs/serving.md): each model's circuit breaker
(serving/breaker.py) sits in front of admission — open state fast-fails
/predict with a distinct 503 `breaker_open` status, and `/health`
reports `degraded` while any breaker is not closed. Forward failures
surface as typed 5xx statuses (`batch_failed` / `nonfinite`), never
hangs.

Multi-model scale (docs/serving.md §multi-model): when the pool carries
a DeviceScheduler, admission adds a TIER check — a lower-tier request is
shed with a typed 503 `tier_shed` while a strictly-higher tier's queue
is saturated — and per-tier latency rides
`serving_latency_ms{tier=...}` histograms plus scrape-time
`serving_tier_p99_ms{tier}` gauges (judged against the scheduler's
`serving_tier_slo_ms{tier}`). Fused-group members route exactly like
ordinary models: `/predict` carries the member name, the entry's
transform slices its columns out of the shared fused forward.

Generative entries (docs/serving.md §decode): a model registered via
`add_decode_model` serves POST /generate through a DecodeEngine —
token-granularity continuous batching over a paged KV cache — behind
the SAME admission sequence (breaker → tier shed → deadline estimate)
and the same typed error surface, plus two decode-specific statuses:
429 `queue_full` when the KV cache itself is exhausted
(KVCacheExhaustedError) and 500 `batch_failed` for a mid-generation
step failure (DecodeStepError — batchmates keep generating).

Endpoints: POST /predict, POST /generate, POST /swap, POST /config (live
reconfiguration: per-entry tier/weight/packed-admission/
batch_timeout_ms plus scheduler-level quantum/shed_depth/
starvation_budget/tier_slo_ms, typed 400s on unknown or invalid
knobs), GET /health, GET /models, GET /stats, GET /metrics (Prometheus
exposition — scrape surface shared with UIServer,
docs/observability.md), plus the flight-recorder surfaces
GET /debug/requests?model=&tier= (slow-request exemplars) and
GET /trace (Chrome trace export of serving spans) — both 404 until
`serving.flight_recorder.enable()` (or DL4JTPU_FLIGHT_RECORDER=1) arms
the recorder — and GET /debug/tuner (the AutoTuner decision trail,
404 until `attach_tuner()` arms the serving control loop,
docs/observability.md §"The serving control loop"). Metrics:
`serving_requests_total{model,status}`, `serving_admitted_total`,
`serving_shed_total{model,reason}`, `serving_swaps_total{model,outcome,precision}`,
`serving_queue_depth{model}`, `serving_batch_failures_total{model}`,
`serving_breaker_state{model}`,
`serving_breaker_transitions_total{model,to}`,
`serving_slo_breach_total{model,tier}` (always on — a transient SLO
breach between scrapes is invisible to the p99 gauges),
`serving_latency_ms{model}` histogram plus scrape-time
`serving_latency_p50_ms`/`serving_latency_p99_ms` gauges (computed
from the histogram's windowed ring — ONE percentile definition shared
with /stats and the SLO monitor), with the recorder enabled
`serving_phase_ms{model,tier,phase}` (docs/observability.md §"Request
flight recorder"), and with a tuner attached the `serving_tuner_*` /
`serving_slo_verdict{tier}` families (serving/autotuner.py).
Every request runs inside a `serve/request` tracing span.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

import numpy as np

from ..optimize import tracing
from ..optimize.metrics import registry
from ..parallel.inference import (BatchExecutionError, DeadlineExceededError,
                                  NonFiniteOutputError, QueueFullError,
                                  ServerClosedError)
from ..utils.http_server import JsonHttpServer
from . import flight_recorder
from .breaker import BreakerOpenError
from .model_pool import ModelPool, SwapError
from .scheduler import DEFAULT_TIER_SLO_MS, TierShedError

__all__ = ["ServingGateway"]

# Latency histogram buckets in ms — sub-ms to 10 s covers an AOT CPU
# forward through a tunneled-TPU worst case.
LATENCY_BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                      250.0, 500.0, 1000.0, 2500.0, 10000.0)


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return float(sorted_vals[idx])


def register_metrics() -> None:
    """Pre-register the gateway's request/latency families (bench
    --once): a scrape taken before any traffic must already show them."""
    reg = registry()
    reg.counter("serving_requests_total",
                "Gateway requests by terminal status (ok/shed/error)")
    reg.counter("serving_admitted_total",
                "Requests admitted past SLO/backpressure checks")
    reg.counter("serving_shed_total",
                "Requests shed before a forward served them, by reason")
    reg.histogram("serving_latency_ms",
                  "End-to-end request latency through the gateway",
                  buckets=LATENCY_BUCKETS_MS)
    reg.gauge("serving_latency_p50_ms",
              "p50 gateway latency over the recent window")
    reg.gauge("serving_latency_p99_ms",
              "p99 gateway latency over the recent window")
    reg.gauge("serving_tier_p99_ms",
              "p99 gateway latency per priority tier over the recent "
              "window (compare against serving_tier_slo_ms)")


class ServingGateway(JsonHttpServer):
    """HTTP + in-process serving facade over a ModelPool.

    `default_deadline_ms` applies to requests that carry no deadline
    (None = no SLO, never shed on time). `shed_headroom` scales the
    admission wait estimate (>1.0 sheds earlier, trading recall of the
    SLO for fewer wasted queue slots)."""

    def __init__(self, pool: Optional[ModelPool] = None, *, port: int = 0,
                 pool_size: int = 8,
                 default_deadline_ms: Optional[float] = None,
                 shed_headroom: float = 1.0,
                 latency_window_s: float = 60.0):
        super().__init__(
            get_routes={"/health": self._health_route,
                        "/models": self._models_route,
                        "/stats": self._stats_route,
                        "/debug/requests": self._debug_requests_route,
                        "/debug/tuner": self._debug_tuner_route},
            post_routes={"/predict": self._predict_route,
                         "/generate": self._generate_route,
                         "/swap": self._swap_route,
                         "/config": self._config_route},
            raw_get_routes={"/trace": self._trace_route},
            port=port, pool_size=pool_size, expose_metrics=True)
        self.pool = pool if pool is not None else ModelPool()
        # Operator escape hatch: DL4JTPU_FLIGHT_RECORDER=1 arms the
        # per-request recorder without a code change.
        flight_recorder.maybe_enable_from_env()
        self.default_deadline_ms = default_deadline_ms
        self.shed_headroom = float(shed_headroom)
        # ONE latency-percentile definition (docs/observability.md §"The
        # serving control loop"): /stats, the scrape gauges, and the
        # SLO monitor all read the serving_latency_ms histogram's
        # windowed ring over this many recent seconds.
        self.latency_window_s = float(latency_window_s)
        # Window floor: the registry (and its histogram rings) is
        # process-global but THIS gateway is not — observations stamped
        # before it existed (a previous gateway in the same process)
        # must never leak into its percentiles.
        self._born = time.monotonic()
        # AutoTuner attachment point (serving/autotuner.py). None by
        # default: no monitor, no thread, no ledger — today's serving
        # path bitwise.
        self.tuner = None
        reg = registry()
        self._req_c = reg.counter(
            "serving_requests_total",
            "Gateway requests by terminal status (ok/shed/error)")
        self._admit_c = reg.counter(
            "serving_admitted_total",
            "Requests admitted past SLO/backpressure checks")
        self._shed_c = reg.counter(
            "serving_shed_total",
            "Requests shed before a forward served them, by reason")
        self._lat_h = reg.histogram(
            "serving_latency_ms",
            "End-to-end request latency through the gateway",
            buckets=LATENCY_BUCKETS_MS)
        self._slo_breach_c = reg.counter(
            "serving_slo_breach_total",
            "Requests whose wall latency exceeded their tier's "
            "serving_tier_slo_ms, counted at response time")
        reg.register_collector(self._collect_percentiles)

    # ------------------------------------------------------------ model mgmt
    def add_model(self, name: str, model, **kw):
        """pool.add passthrough (see ModelPool.add for knobs)."""
        return self.pool.add(name, model, **kw)

    def add_decode_model(self, name: str, model, **kw):
        """pool.add_decode passthrough: register a generative entry
        behind a DecodeEngine, served via generate()/POST /generate
        (see ModelPool.add_decode for knobs)."""
        return self.pool.add_decode(name, model, **kw)

    def add_fused_group(self, group_name: str, members, **kw):
        """pool.add_fused_group passthrough: N same-geometry models
        behind one fused forward (falls back to independent entries
        when the member set cannot merge)."""
        return self.pool.add_fused_group(group_name, members, **kw)

    def warmup(self, name: Optional[str] = None, **kw) -> "ServingGateway":
        self.pool.warmup(name, **kw)
        return self

    def swap(self, name: str, **kw) -> Dict[str, Any]:
        """Checkpoint-gated hot-swap (ModelPool.swap protocol)."""
        return self.pool.swap(name, **kw)

    def load(self) -> Dict[str, float]:
        """Aggregate admission load across every entry: total queued
        requests (the serving_queue_depth gauge's sum) and the worst
        per-entry EWMA wait estimate. This is the signal a federation
        replica rides on its beats so the front-end's weighted
        least-loaded dispatch sees each replica's pressure
        (serving/federation.py) — engines that expose no estimator
        (decode) contribute depth only."""
        depth = 0
        wait = 0.0
        for e in self.pool.entries():
            try:
                depth += int(e.engine.queue_depth())
            except Exception:
                continue
            est = getattr(e.engine, "estimate_wait_s", None)
            if est is not None:
                try:
                    wait = max(wait, float(est()))
                except Exception:
                    pass
        return {"queue_depth": depth, "est_wait_s": wait}

    # -------------------------------------------------------------- predict
    def predict(self, name: str, x, *,
                deadline_ms: Optional[float] = None,
                _trace_sink: Optional[list] = None) -> np.ndarray:
        """In-process entry point (the HTTP route is a thin wrapper).
        Raises DeadlineExceededError / QueueFullError on shed,
        BreakerOpenError when the model's circuit breaker fast-fails
        the request, BatchExecutionError (NonFiniteOutputError for
        NaN/Inf outputs) when the forward itself failed, KeyError on
        unknown model.

        `_trace_sink` (private: the /predict route) receives the
        completed flight-recorder summary when the recorder is enabled,
        so the HTTP response can embed the phase timeline."""
        # Unknown model: plain KeyError, no metrics — client-supplied
        # junk names must not mint unbounded label cardinality.
        entry = self.pool.get(name)
        t0 = time.perf_counter()
        status = "error"
        # Flight recorder (docs/observability.md): disabled (default)
        # this is None and every touch below is one branch.
        tr = flight_recorder.new_trace(name, entry.tier)
        try:
            if deadline_ms is None:
                deadline_ms = self.default_deadline_ms
            deadline = None if deadline_ms is None else \
                time.monotonic() + float(deadline_ms) / 1000.0
            with tracing.span("serve/request", cat="serve", model=name):
                # Circuit breaker (docs/serving.md): an open breaker
                # fast-fails BEFORE admission — no queue slot, no
                # forward rows, a distinct terminal status. Half-open
                # admits one probe; its forward outcome re-closes or
                # re-opens the breaker via the engine hooks.
                br = entry.breaker
                if br is not None and not br.allow():
                    status = "breaker_open"
                    raise BreakerOpenError(
                        f"model {name!r} circuit breaker is "
                        f"{br.state} — fast-failing without queuing")
                # Tier shed (docs/serving.md §multi-model): under
                # saturation a lower-tier request must not take a queue
                # slot behind traffic that always outranks it — typed
                # 503, immediately, never a hang.
                sch = self.pool.scheduler
                if sch is not None:
                    sname = entry.engine.sched_name or name
                    shed_reason = sch.should_shed(sname)
                    if shed_reason is not None:
                        self._shed_c.labels(model=name,
                                            reason=shed_reason).inc()
                        status = "shed"
                        raise TierShedError(
                            f"model {name!r} (tier {entry.tier!r}) shed: "
                            "a higher tier's backlog saturates the "
                            "shared device budget")
                if deadline is not None:
                    # SLO-aware admission: estimated completion past the
                    # deadline means this request can only waste a queue
                    # slot — shed it NOW with a distinct status.
                    est = entry.engine.estimate_wait_s() * self.shed_headroom
                    if time.monotonic() + est > deadline:
                        self._shed_c.labels(model=name,
                                            reason="admission").inc()
                        status = "shed"
                        raise DeadlineExceededError(
                            f"estimated wait {est * 1000:.1f}ms cannot "
                            f"meet deadline {deadline_ms}ms — shed at "
                            "admission")
                self._admit_c.labels(model=name).inc()
                if tr is not None:
                    # admission = gateway entry → engine handoff
                    # (breaker / tier-shed / SLO-estimate checks)
                    tr.mark("admission")
                    # precision the forward will run at — makes the
                    # quant A/B attributable per-phase in exemplars
                    tr.ctx["precision"] = entry.precision
                    gname = entry.engine.sched_name
                    if gname and gname != name:
                        tr.ctx["fused_group"] = gname
                try:
                    out = entry.engine.output(
                        x, deadline=deadline, transform=entry.transform,
                        tag=name, trace=tr)
                except QueueFullError:
                    self._shed_c.labels(model=name,
                                        reason="queue_full").inc()
                    status = "shed"
                    raise
                except DeadlineExceededError:
                    # late shed: counted by the engine's on_shed hook
                    # (reason="expired") — only the status lands here.
                    status = "shed"
                    raise
            status = "ok"
            return out
        finally:
            dur_ms = (time.perf_counter() - t0) * 1000.0
            self._req_c.labels(model=name, status=status).inc()
            self._lat_h.labels(model=name).observe(dur_ms)
            # Tier-labeled children only exist when a scheduler ranks
            # the pool (keeps the default single-model scrape bitwise).
            tiered = self.pool.scheduler is not None
            if tiered:
                self._lat_h.labels(tier=entry.tier).observe(dur_ms)
            # SLO burn counter (always on, recorder or not): a breach
            # between scrapes must leave a durable count behind.
            slo_ms = self._tier_slo(entry.tier)
            if slo_ms is not None and dur_ms > slo_ms:
                self._slo_breach_c.labels(model=name,
                                          tier=entry.tier).inc()
            if tr is not None:
                if not tr.marks:
                    # request died in the admission checks (breaker
                    # fast-fail / tier shed / hopeless deadline): the
                    # whole timeline IS admission
                    tr.mark("admission")
                if "precision" not in tr.ctx:
                    # fast-fail paths skip the admitted-path stamp; the
                    # exemplar ring must label precision consistently
                    tr.ctx["precision"] = entry.precision
                if entry.breaker is not None:
                    tr.ctx["breaker"] = entry.breaker.state
                summary = flight_recorder.complete(
                    tr, status, dur_ms, slo_ms,
                    want_summary=_trace_sink is not None)
                if _trace_sink is not None and summary is not None:
                    _trace_sink.append(summary)

    # ------------------------------------------------------------- generate
    def generate(self, name: str, prompt, *,
                 max_new_tokens: int = 32,
                 deadline_ms: Optional[float] = None,
                 _trace_sink: Optional[list] = None):
        """In-process decode entry point (POST /generate is the thin
        wrapper): run `prompt` through `name`'s DecodeEngine — admitted
        between decode steps, riding the token-granularity continuous
        batch — and return the generated sequence (token-id list for
        the transformer arm, [steps, features] array for the stream
        arm).

        The admission sequence is predict()'s, verbatim: breaker
        fast-fail, tier shed, EWMA deadline estimate, then the engine.
        Raises the same typed taxonomy plus DecodeStepError (a
        mid-generation step failure — KV freed, batchmates unharmed)
        and KVCacheExhaustedError (KV backpressure, a QueueFullError
        subtype). The flight-recorder timeline routes device time
        through the `prefill`/`decode_step` phases, with
        `tokens_generated`/`kv_blocks` in the exemplar ctx."""
        entry = self.pool.get(name)
        t0 = time.perf_counter()
        status = "error"
        tr = flight_recorder.new_trace(name, entry.tier)
        try:
            if deadline_ms is None:
                deadline_ms = self.default_deadline_ms
            deadline = None if deadline_ms is None else \
                time.monotonic() + float(deadline_ms) / 1000.0
            with tracing.span("serve/generate", cat="serve", model=name):
                br = entry.breaker
                if br is not None and not br.allow():
                    status = "breaker_open"
                    raise BreakerOpenError(
                        f"model {name!r} circuit breaker is "
                        f"{br.state} — fast-failing without queuing")
                sch = self.pool.scheduler
                if sch is not None:
                    sname = entry.engine.sched_name or name
                    shed_reason = sch.should_shed(sname)
                    if shed_reason is not None:
                        self._shed_c.labels(model=name,
                                            reason=shed_reason).inc()
                        status = "shed"
                        raise TierShedError(
                            f"model {name!r} (tier {entry.tier!r}) shed: "
                            "a higher tier's backlog saturates the "
                            "shared device budget")
                if deadline is not None:
                    est = entry.engine.estimate_wait_s() * self.shed_headroom
                    if time.monotonic() + est > deadline:
                        self._shed_c.labels(model=name,
                                            reason="admission").inc()
                        status = "shed"
                        raise DeadlineExceededError(
                            f"estimated wait {est * 1000:.1f}ms cannot "
                            f"meet deadline {deadline_ms}ms — shed at "
                            "admission")
                self._admit_c.labels(model=name).inc()
                if tr is not None:
                    tr.mark("admission")
                    tr.ctx["precision"] = entry.precision
                try:
                    out = entry.engine.generate(
                        prompt, max_new_tokens=max_new_tokens,
                        deadline=deadline, trace=tr)
                except QueueFullError:
                    # KVCacheExhaustedError lands here too (subclass) —
                    # both are backpressure, both 429 at the route.
                    self._shed_c.labels(model=name,
                                        reason="queue_full").inc()
                    status = "shed"
                    raise
                except DeadlineExceededError:
                    status = "shed"
                    raise
            status = "ok"
            return out
        finally:
            dur_ms = (time.perf_counter() - t0) * 1000.0
            self._req_c.labels(model=name, status=status).inc()
            self._lat_h.labels(model=name).observe(dur_ms)
            tiered = self.pool.scheduler is not None
            if tiered:
                self._lat_h.labels(tier=entry.tier).observe(dur_ms)
            slo_ms = self._tier_slo(entry.tier)
            if slo_ms is not None and dur_ms > slo_ms:
                self._slo_breach_c.labels(model=name,
                                          tier=entry.tier).inc()
            if tr is not None:
                if not tr.marks:
                    tr.mark("admission")
                if "precision" not in tr.ctx:
                    tr.ctx["precision"] = entry.precision
                if entry.breaker is not None:
                    tr.ctx["breaker"] = entry.breaker.state
                summary = flight_recorder.complete(
                    tr, status, dur_ms, slo_ms,
                    want_summary=_trace_sink is not None)
                if _trace_sink is not None and summary is not None:
                    _trace_sink.append(summary)

    def _tier_slo(self, tier: Optional[str]) -> Optional[float]:
        """The latency SLO a request of `tier` is judged against: the
        scheduler's live per-tier config when the pool runs one, else
        the documented defaults (an untiered pool still burns against
        the standard-tier budget)."""
        sch = self.pool.scheduler
        if sch is not None:
            return sch.tier_slo_ms.get(tier)
        return DEFAULT_TIER_SLO_MS.get(tier)

    # ---------------------------------------------------------------- stats
    def _windowed_latencies(self):
        """([(model, sorted_vals)], [(tier, sorted_vals)]) from the
        serving_latency_ms histogram rings over the last
        `latency_window_s` seconds — the single percentile source
        /stats, the scrape gauges, and the SLO monitor share (the
        recent-latency deques this replaced had their own, subtly
        different, definition)."""
        now = time.monotonic()
        w = min(self.latency_window_s, max(0.0, now - self._born))
        items, titems = [], []
        for labels, child in self._lat_h.items():
            vals = child.window_values(w, now=now)
            if not vals:
                continue
            if "model" in labels:
                items.append((labels["model"], sorted(vals)))
            elif "tier" in labels:
                titems.append((labels["tier"], sorted(vals)))
        return sorted(items), sorted(titems)

    def stats(self) -> Dict[str, Any]:
        """Per-model {p50_ms, p99_ms, count} over the windowed latency
        ring plus the pool description (bench.py's serving row reads
        this)."""
        out: Dict[str, Any] = {"models": self.pool.describe()}
        items, titems = self._windowed_latencies()
        out["latency"] = {
            name: {"p50_ms": round(_percentile(vals, 0.50), 3),
                   "p99_ms": round(_percentile(vals, 0.99), 3),
                   "count": len(vals)}
            for name, vals in items}
        if titems:
            out["tiers"] = {
                t: {"p50_ms": round(_percentile(v, 0.50), 3),
                    "p99_ms": round(_percentile(v, 0.99), 3),
                    "count": len(v)}
                for t, v in titems}
        return out

    def _collect_percentiles(self, reg) -> None:
        g50 = reg.gauge("serving_latency_p50_ms",
                        "p50 gateway latency over the recent window")
        g99 = reg.gauge("serving_latency_p99_ms",
                        "p99 gateway latency over the recent window")
        items, titems = self._windowed_latencies()
        for name, vals in items:
            g50.labels(model=name).set(_percentile(vals, 0.50))
            g99.labels(model=name).set(_percentile(vals, 0.99))
        if titems:
            tg = reg.gauge(
                "serving_tier_p99_ms",
                "p99 gateway latency per priority tier over the recent "
                "window (compare against serving_tier_slo_ms)")
            for t, vals in titems:
                tg.labels(tier=t).set(_percentile(vals, 0.99))

    # ------------------------------------------------------------ lifecycle
    def attach_tuner(self, tuner=None, *, start: bool = True, **kw):
        """Arm the serving control loop (serving/autotuner.py): attach
        an AutoTuner over this gateway's pool — built from `kw`
        (interval_s, ledger_path, knobs, monitor, ...) when none is
        passed — and start its tick thread by default. Until this is
        called the gateway runs the exact untuned path."""
        from .autotuner import AutoTuner
        if tuner is None:
            tuner = AutoTuner(self.pool, **kw)
        self.tuner = tuner
        if start:
            tuner.start()
        return tuner

    def stop(self):
        """Graceful: finish in-flight HTTP handlers (JsonHttpServer),
        stop the tuner thread if one is attached, then drain the
        engines (stragglers served, stranded callers failed with
        ServerClosedError — never hung)."""
        super().stop()
        if self.tuner is not None:
            self.tuner.stop()
        self.pool.shutdown()

    # --------------------------------------------------------------- routes
    def _health_route(self, _):
        # Degraded = any model's breaker is not closed: the gateway is
        # up, but some traffic is being fast-failed (docs/serving.md).
        breakers = {e.name: e.breaker.state
                    for e in self.pool.entries() if e.breaker is not None}
        degraded = sorted(n for n, s in breakers.items() if s != "closed")
        return 200, {"status": "degraded" if degraded else "ok",
                     "models": sorted(self.pool.names()),
                     "breakers": breakers, "degraded": degraded}

    def _models_route(self, _):
        return 200, {"models": self.pool.describe()}

    def _stats_route(self, _):
        return 200, self.stats()

    def _debug_requests_route(self, params):
        """GET /debug/requests?model=&tier= — the slow-request exemplar
        store: full phase timelines + context of the last N over-SLO /
        errored / shed requests (flight_recorder ring)."""
        if not flight_recorder.is_enabled():
            return 404, {"status": "error", "enabled": False,
                         "error": "flight recorder disabled — enable "
                                  "serving.flight_recorder or set "
                                  "DL4JTPU_FLIGHT_RECORDER=1"}
        params = params or {}
        reqs = flight_recorder.exemplars(model=params.get("model"),
                                         tier=params.get("tier"))
        return 200, {"status": "ok", "enabled": True,
                     "count": len(reqs), "requests": reqs}

    def _trace_route(self):
        """GET /trace — Chrome trace-event export of the span ring
        (serving spans carry cat="serve"), same surface UIServer has
        served since PR 2; gated behind the recorder enable flag."""
        if not flight_recorder.is_enabled():
            body = json.dumps(
                {"status": "error", "enabled": False,
                 "error": "flight recorder disabled — enable "
                          "serving.flight_recorder or set "
                          "DL4JTPU_FLIGHT_RECORDER=1"}).encode()
            return 404, "application/json", body
        body = json.dumps(tracing.export_trace_events()).encode()
        return 200, "application/json", body

    def _predict_route(self, req: dict):
        name = req.get("model", "default")
        x = np.asarray(req["features"], np.float32)
        deadline_ms = req.get("deadline_ms")
        sink = [] if flight_recorder.is_enabled() else None
        try:
            out = self.predict(name, x, deadline_ms=deadline_ms,
                               _trace_sink=sink)
            # inside the try: a concurrent remove() between the forward
            # and this lookup must surface as the typed 404, not a 500
            version = self.pool.get(name).version.get("file", "initial")
        except KeyError as e:
            return 404, {"status": "error", "error": str(e)}
        except BreakerOpenError as e:
            return 503, {"status": "unavailable", "reason": "breaker_open",
                         "error": str(e)}
        except TierShedError as e:
            return 503, {"status": "shed", "reason": "tier_shed",
                         "error": str(e)}
        except QueueFullError as e:
            return 429, {"status": "shed", "reason": "queue_full",
                         "error": str(e)}
        except DeadlineExceededError as e:
            return 503, {"status": "shed", "reason": "deadline",
                         "error": str(e)}
        except NonFiniteOutputError as e:
            return 500, {"status": "error", "reason": "nonfinite",
                         "error": str(e)}
        except BatchExecutionError as e:
            return 500, {"status": "error", "reason": "batch_failed",
                         "error": str(e)}
        except ServerClosedError as e:
            return 503, {"status": "error", "error": str(e)}
        resp = {"status": "ok", "model": name, "version": version,
                "predictions": np.asarray(out).tolist()}
        if sink:
            resp["trace"] = sink[0]
        return 200, resp

    def _generate_route(self, req: dict):
        """POST /generate {"model", "prompt", "max_new_tokens",
        "deadline_ms"} — the decode twin of /predict with the same
        typed status chain. A ValueError from prompt validation (wrong
        shape, out-of-vocab tokens, exceeds max_context) is the
        client's fault: typed 400."""
        name = req.get("model", "default")
        if "prompt" not in req:
            return 400, {"status": "error", "reason": "bad_prompt",
                         "error": "request body needs a 'prompt' field"}
        deadline_ms = req.get("deadline_ms")
        sink = [] if flight_recorder.is_enabled() else None
        try:
            out = self.generate(
                name, req["prompt"],
                max_new_tokens=int(req.get("max_new_tokens", 32)),
                deadline_ms=deadline_ms, _trace_sink=sink)
            # inside the try: a concurrent remove() between the decode
            # and this lookup must surface as the typed 404, not a 500
            version = self.pool.get(name).version.get("file", "initial")
        except KeyError as e:
            return 404, {"status": "error", "error": str(e)}
        except ValueError as e:
            return 400, {"status": "error", "reason": "bad_prompt",
                         "error": str(e)}
        except BreakerOpenError as e:
            return 503, {"status": "unavailable", "reason": "breaker_open",
                         "error": str(e)}
        except TierShedError as e:
            return 503, {"status": "shed", "reason": "tier_shed",
                         "error": str(e)}
        except QueueFullError as e:
            # KVCacheExhaustedError inherits this arm: KV backpressure
            # is a retryable 429, never a 500.
            return 429, {"status": "shed", "reason": "queue_full",
                         "error": str(e)}
        except DeadlineExceededError as e:
            return 503, {"status": "shed", "reason": "deadline",
                         "error": str(e)}
        except NonFiniteOutputError as e:
            return 500, {"status": "error", "reason": "nonfinite",
                         "error": str(e)}
        except BatchExecutionError as e:
            # DecodeStepError inherits this arm: a failed step is a
            # server-side 500 with the victim's KV already freed.
            return 500, {"status": "error", "reason": "batch_failed",
                         "error": str(e)}
        except ServerClosedError as e:
            return 503, {"status": "error", "error": str(e)}
        resp = {"status": "ok", "model": name, "version": version,
                "tokens": np.asarray(out).tolist()}
        if sink:
            resp["trace"] = sink[0]
        return 200, resp

    def _swap_route(self, req: dict):
        name = req.get("model", "default")
        kw = {}
        if req.get("quantize"):
            # {"quantize": "int8" | "bf16" | "fp32"} promotes the
            # checkpoint at that precision behind the canary gate
            kw["quantize"] = str(req["quantize"])
        try:
            return 200, self.swap(name, **kw)
        except KeyError as e:
            return 404, {"status": "error", "error": str(e)}
        except SwapError as e:
            return 409, {"status": "swap_failed", "error": str(e)}

    # Live-reconfigurable knobs POST /config accepts: per-entry
    # (routed at req["model"]) and scheduler-level (no model needed).
    _ENTRY_KNOBS = ("packed_admission", "pack_bucket", "tier", "weight",
                    "batch_timeout_ms", "breaker_threshold",
                    "breaker_reset_s")
    _SCHED_KNOBS = ("quantum", "shed_depth", "starvation_budget",
                    "tier_slo_ms")

    def _config_route(self, req: dict):
        """Live reconfiguration. Per-entry knobs (packed_admission /
        pack_bucket / tier / weight / batch_timeout_ms /
        breaker_threshold / breaker_reset_s) route at
        req["model"]; scheduler-level knobs (quantum / shed_depth /
        starvation_budget / tier_slo_ms) need no model and create the
        shared scheduler on first use. Typed 400 on unknown knobs or
        invalid values (reason: unknown_knob / invalid_value), 404 on
        unknown model, 409 on invalid per-entry combinations (unknown
        tier, fused-group member)."""
        unknown = sorted(set(req) - set(self._ENTRY_KNOBS)
                         - set(self._SCHED_KNOBS) - {"model"})
        if unknown:
            return 400, {"status": "error", "reason": "unknown_knob",
                         "error": "unknown config knob(s): "
                                  + ", ".join(unknown)}
        try:
            entry_kw: Dict[str, Any] = {}
            if "packed_admission" in req:
                entry_kw["packed_admission"] = bool(req["packed_admission"])
            if "pack_bucket" in req:
                entry_kw["pack_bucket"] = int(req["pack_bucket"])
            if "tier" in req:
                entry_kw["tier"] = req["tier"]
            if "weight" in req:
                entry_kw["weight"] = float(req["weight"])
            if "batch_timeout_ms" in req:
                entry_kw["batch_timeout_ms"] = float(req["batch_timeout_ms"])
            if "breaker_threshold" in req:
                entry_kw["breaker_threshold"] = int(req["breaker_threshold"])
            if "breaker_reset_s" in req:
                entry_kw["breaker_reset_s"] = float(req["breaker_reset_s"])
            sched_kw: Dict[str, Any] = {}
            if "quantum" in req:
                sched_kw["quantum"] = float(req["quantum"])
            if "shed_depth" in req:
                sched_kw["shed_depth"] = int(req["shed_depth"])
            if "starvation_budget" in req:
                sched_kw["starvation_budget"] = int(
                    req["starvation_budget"])
            if "tier_slo_ms" in req:
                slo = req["tier_slo_ms"]
                if not isinstance(slo, dict):
                    raise TypeError("tier_slo_ms must be a "
                                    "{tier: slo_ms} object")
                sched_kw["tier_slo_ms"] = {
                    str(t): float(v) for t, v in slo.items()}
        except (TypeError, ValueError) as e:
            return 400, {"status": "error", "reason": "invalid_value",
                         "error": str(e)}
        if not entry_kw and not sched_kw:
            return 400, {"status": "error",
                         "error": "no reconfigurable knob in request "
                                  "(packed_admission/pack_bucket/tier/"
                                  "weight/batch_timeout_ms/"
                                  "breaker_threshold/breaker_reset_s/"
                                  "quantum/shed_depth/starvation_budget/"
                                  "tier_slo_ms)"}
        out: Dict[str, Any] = {"status": "ok"}
        if sched_kw:
            try:
                out["scheduler"] = self.pool.reconfigure_scheduler(
                    **sched_kw)
            except ValueError as e:
                return 400, {"status": "error", "reason": "invalid_value",
                             "error": str(e)}
        if entry_kw:
            name = req.get("model", "default")
            try:
                out.update(self.pool.reconfigure(name, **entry_kw))
            except KeyError as e:
                return 404, {"status": "error", "error": str(e)}
            except ValueError as e:
                return 409, {"status": "error", "error": str(e)}
        return 200, out

    def _debug_tuner_route(self, _):
        """GET /debug/tuner — the AutoTuner decision trail: state,
        knob table with guardrails, known-good snapshot, and the last
        ledger rows. 404 until attach_tuner() arms the control loop
        (flight-recorder route pattern)."""
        if self.tuner is None:
            return 404, {"status": "error", "enabled": False,
                         "error": "no AutoTuner attached — "
                                  "gateway.attach_tuner() arms the "
                                  "serving control loop"}
        body = self.tuner.describe()
        body.update({"status": "ok", "enabled": True})
        return 200, body
