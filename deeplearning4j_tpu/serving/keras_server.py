"""Keras-backend training server.

Reference parity: deeplearning4j-keras (452 LoC): a py4j GatewayServer
(keras/Server.java:15-18) exposing DeepLearning4jEntryPoint.fit() — a
Keras user ships an HDF5 model (+ batched HDF5 data) and the JVM trains
it. Here the transport is stdlib HTTP+JSON (utils/http_server) and the
import path is the framework's own Keras HDF5 importer:

  POST /fit     {"model_path": "...h5", "features": [...], "labels":
                 [...], "epochs": n, "batch_size": n}
                → trains the imported model, returns final score and a
                  handle id
  POST /predict {"handle": id, "features": [...]} → predictions
  GET  /health
"""
from __future__ import annotations

import threading
from typing import Dict

import numpy as np

from ..utils.http_server import JsonHttpServer


class KerasBackendServer(JsonHttpServer):
    def __init__(self, port: int = 0, pool_size: int = 8):
        super().__init__(
            get_routes={"/health": self._health},
            post_routes={"/fit": self._fit, "/predict": self._predict},
            port=port, pool_size=pool_size, expose_metrics=True)
        self._models: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._next_id = 0

    def _health(self, _):
        return 200, {"status": "ok", "models": len(self._models)}

    def _fit(self, req: dict):
        from ..keras_import import KerasModelImport
        net = KerasModelImport.import_keras_sequential_model_and_weights(
            req["model_path"])
        x = np.asarray(req["features"], np.float32)
        y = np.asarray(req["labels"], np.float32)
        net.fit(x, y, epochs=int(req.get("epochs", 1)),
                batch_size=int(req.get("batch_size", 32)))
        with self._lock:
            handle = f"model-{self._next_id}"
            self._next_id += 1
            self._models[handle] = net
        return 200, {"handle": handle, "score": float(net.score_value),
                     "iterations": net.iteration}

    def _predict(self, req: dict):
        with self._lock:
            net = self._models.get(req.get("handle"))
        if net is None:
            raise KeyError(f"unknown handle {req.get('handle')!r}")
        out = net.output(np.asarray(req["features"], np.float32))
        return 200, {"predictions": np.asarray(out).tolist()}
