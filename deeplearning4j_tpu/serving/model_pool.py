"""Named model pool with checkpoint-gated zero-downtime hot-swap.

Each entry pairs a live network with its continuous-batching execution
engine (parallel/inference.ParallelInference) and, optionally, the
CheckpointManager a training run publishes to. The pool is the
gateway's routing table (docs/serving.md) and the owner of the swap
protocol:

1. **Gate** — `CheckpointManager.latest_valid()` picks the newest
   checkpoint whose sha256 manifest entry verifies; torn/corrupt
   publishes are skipped, an empty manifest refuses the swap.
2. **Decode off the hot path** — params/state npz trees are read and
   device-staged against the LIVE model's trees as templates (same
   treedef, same shapes — an architecture mismatch fails here, before
   traffic is touched), while the engine keeps serving.
3. **Pause–assign–warm** — the engine's execution lock is held just
   long enough to assign the new trees and push one zero batch per
   warmed bucket through the EXISTING AOT executables (shapes are
   unchanged, so this re-verifies the fast path with the new params and
   compiles nothing). In-flight requests finish first; queued requests
   WAIT — none are dropped or failed.
4. **Rollback on failure** — if the warm forward raises, the old trees
   are restored before the lock is released and the swap reports
   failed; traffic never sees half-swapped params.

4½. **Canary gate** (docs/serving.md) — after pause-assign-warm, a
   retained golden batch runs through the NEW params; non-finite
   outputs (or drift past the optional `canary_max_drift` knob vs the
   OLD params' outputs on the same batch) auto-roll back to the old
   tree and raise `SwapError`, counted as
   `serving_swaps_total{outcome="canary_rejected"}` — a checkpoint
   that passes its sha256 gate but computes garbage never reaches
   traffic.

Swap outcomes land in `serving_swaps_total{model,outcome}`; per-model
queue depth is sampled into `serving_queue_depth{model}` and breaker
state into `serving_breaker_state{model}` at scrape time.
"""
from __future__ import annotations

import os
import threading
import weakref
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.padding import next_pow2_bucket, repeat_tail_rows
from ..optimize import tracing
from ..optimize.metrics import registry
from ..parallel.inference import (InferenceMode, NonFiniteOutputError,
                                  ParallelInference)
from ..utils import faults
from ..utils.model_serializer import (PARAMS_ENTRY, STATE_ENTRY,
                                      CheckpointCorruptError,
                                      _npz_bytes_to_tree, _read_entry,
                                      validate_checkpoint)
from .breaker import STATE_VALUES, CircuitBreaker

__all__ = ["ModelEntry", "ModelPool", "SwapError"]


class SwapError(RuntimeError):
    """Hot-swap refused: no CheckpointManager attached, no valid
    checkpoint published, architecture mismatch, the warm forward
    failed, or the canary gate rejected the new params (in the latter
    two cases the old params were rolled back and are still serving)."""


class _CanaryRejected(RuntimeError):
    """Internal: the post-warm golden-batch check failed — distinguishes
    the canary_rejected swap outcome from a plain warm failure."""


def _swap_counter(name: str, outcome: str):
    registry().counter(
        "serving_swaps_total",
        "Checkpoint hot-swap attempts by outcome "
        "(ok/noop/failed/canary_rejected)"
        ).labels(model=name, outcome=outcome).inc()


def _golden_forward(model, golden: np.ndarray) -> np.ndarray:
    """Run the golden batch through the model padded to its pow2 bucket
    (the same rule the engine coalesces to, so a warmed server compiles
    nothing here) and slice the real rows back."""
    n = golden.shape[0]
    xs = repeat_tail_rows(golden, next_pow2_bucket(n) - n)
    return np.asarray(model.output(xs))[:n]


class ModelEntry:
    """One named served model: the live network, its batching engine,
    and the checkpoint source it hot-swaps from."""

    def __init__(self, name: str, model, engine: ParallelInference,
                 checkpoints=None, breaker: Optional[CircuitBreaker] = None,
                 golden_batch: Optional[np.ndarray] = None,
                 canary_max_drift: Optional[float] = None):
        self.name = name
        self.model = model
        self.engine = engine
        self.checkpoints = checkpoints
        self.breaker = breaker
        # Canary substrate: a small retained input batch (provided, or
        # captured from the first served request) replayed through new
        # params before a swap promotes them; `canary_max_drift` bounds
        # max|new - old| output drift on it (None = finiteness only).
        self.golden_batch = None if golden_batch is None else \
            np.asarray(golden_batch)
        self.canary_max_drift = canary_max_drift
        # Manifest record of the checkpoint currently serving; empty
        # until the first swap (initial params came from the caller,
        # not a published checkpoint).
        self.version: Dict[str, Any] = {}
        self.swaps = 0

    def describe(self) -> Dict[str, Any]:
        out = {
            "model": self.name,
            "version": self.version.get("file", "initial"),
            "iteration": int(getattr(self.model, "iteration", 0)),
            "swaps": self.swaps,
            "queue_depth": self.engine.queue_depth(),
            "warmed_buckets": list(self.engine.warmed_buckets),
            "total_forwards": self.engine.total_forwards,
            "total_shed": self.engine.total_shed,
            "total_batch_failures": self.engine.total_batch_failures,
        }
        if self.breaker is not None:
            out["breaker"] = self.breaker.describe()
        return out


class ModelPool:
    """Thread-safe name → ModelEntry routing table + swap protocol."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, ModelEntry] = {}
        # Weakly-referenced scrape collector: queue depth is sampled at
        # scrape time only (never in the request path), and a dead pool
        # silently drops out of the scrape.
        wr = weakref.ref(self)

        def _collect(reg, _wr=wr):
            pool = _wr()
            if pool is None:
                return
            g = reg.gauge("serving_queue_depth",
                          "Requests queued per served model")
            bg = reg.gauge("serving_breaker_state",
                           "Circuit breaker state per model (0=closed, "
                           "1=open, 2=half_open)")
            for e in pool.entries():
                g.labels(model=e.name).set(e.engine.queue_depth())
                if e.breaker is not None:
                    bg.labels(model=e.name).set(
                        STATE_VALUES[e.breaker.state])

        registry().register_collector(_collect)

    # ------------------------------------------------------------- routing
    def add(self, name: str, model, *, checkpoints=None,
            batch_limit: int = 32, queue_limit: int = 256,
            batch_timeout_ms: float = 2.0,
            inference_mode: InferenceMode = InferenceMode.BATCHED,
            check_finite: bool = True,
            breaker: Optional[CircuitBreaker] = None,
            breaker_threshold: int = 5,
            breaker_reset_s: float = 30.0,
            golden_batch=None,
            canary_max_drift: Optional[float] = None,
            packed_admission: bool = False,
            pack_bucket: int = 0) -> ModelEntry:
        """Register an init()ed model under `name` behind a fresh
        continuous-batching engine. `checkpoints` (a CheckpointManager
        or a directory path) enables hot-swap for this entry.

        Resilience knobs (docs/serving.md): `check_finite` fails a
        forward whose outputs carry NaN/Inf (on by default for served
        entries — the breaker's instant trip); `breaker` (or
        `breaker_threshold`/`breaker_reset_s` for the default one)
        guards this entry's /predict path; `golden_batch` seeds the
        swap canary input (otherwise the first served request's rows
        are retained); `canary_max_drift` bounds output drift a swap
        may introduce on the golden batch (None = finiteness only);
        `packed_admission`/`pack_bucket` coalesce short sequence
        requests into one segment-masked [1, pack_bucket] row (the
        model's attention layers must run packed_segments=True —
        docs/serving.md §packed)."""
        if isinstance(checkpoints, (str, os.PathLike)):
            from ..optimize.resilience import CheckpointManager
            checkpoints = CheckpointManager(checkpoints)
        engine = ParallelInference(
            model, inference_mode=inference_mode, batch_limit=batch_limit,
            queue_limit=queue_limit, batch_timeout_ms=batch_timeout_ms,
            check_finite=check_finite, packed_admission=packed_admission,
            pack_bucket=pack_bucket)
        if breaker is None:
            breaker = CircuitBreaker(name,
                                     failure_threshold=breaker_threshold,
                                     reset_timeout_s=breaker_reset_s)
        entry = ModelEntry(name, model, engine, checkpoints,
                           breaker=breaker, golden_batch=golden_batch,
                           canary_max_drift=canary_max_drift)
        # Engine-level telemetry hooks: late (in-queue) deadline sheds,
        # per-forward batch stats, and batch failures, labeled by model.
        reg = registry()
        shed_c = reg.counter(
            "serving_shed_total",
            "Requests shed before a forward served them, by reason")
        fwd_c = reg.counter("serving_forwards_total",
                            "Coalesced forward passes executed")
        rows_c = reg.counter("serving_rows_total",
                             "Real (un-padded) request rows served")
        fill_h = reg.histogram(
            "serving_batch_rows",
            "Real rows per coalesced forward (bucket fill)")
        fail_c = reg.counter(
            "serving_batch_failures_total",
            "Coalesced forwards that raised or returned non-finite "
            "outputs")

        def _on_shed(req, reason, _name=name):
            shed_c.labels(model=_name, reason=reason).inc()

        def _on_batch(reqs, rows, bucket, dur_s, _name=name,
                      _entry=entry, _breaker=breaker):
            fwd_c.labels(model=_name).inc()
            rows_c.labels(model=_name).inc(rows)
            fill_h.labels(model=_name).observe(rows)
            _breaker.record_success()
            if _entry.golden_batch is None and reqs:
                # Retain a slice of real traffic as the swap canary
                # input (first served request, at most 4 rows).
                _entry.golden_batch = np.asarray(reqs[0].x[:4]).copy()

        def _on_batch_error(exc, n_requests, _name=name, _breaker=breaker):
            fail_c.labels(model=_name).inc()
            _breaker.record_failure(
                trip=isinstance(exc, NonFiniteOutputError))

        engine.on_shed = _on_shed
        engine.on_batch = _on_batch
        engine.on_batch_error = _on_batch_error
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            self._entries[name] = entry
        return entry

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"no model named {name!r} in the pool "
                           f"(have: {sorted(self.names())})")
        return entry

    def remove(self, name: str) -> None:
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is not None:
            entry.engine.shutdown()

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def entries(self) -> List[ModelEntry]:
        with self._lock:
            return list(self._entries.values())

    def describe(self) -> List[Dict[str, Any]]:
        return [e.describe() for e in self.entries()]

    # -------------------------------------------------------------- warmup
    def warmup(self, name: Optional[str] = None, *,
               max_bucket: Optional[int] = None,
               time_steps: Optional[int] = None) -> "ModelPool":
        """AOT-precompile every pow2 bucket for one model (or all):
        after this, steady-state serving never compiles."""
        targets = [self.get(name)] if name else self.entries()
        for e in targets:
            e.engine.warmup(max_bucket=max_bucket, time_steps=time_steps)
        return self

    # ---------------------------------------------------------------- swap
    def swap(self, name: str, *, manager=None,
             time_steps: Optional[int] = None) -> Dict[str, Any]:
        """Checkpoint-gated zero-downtime hot-swap (module docstring
        protocol). Returns {"swapped": bool, "model", "file",
        "iteration"}; raises :class:`SwapError` when the gate or the
        warm fails (old params keep serving either way)."""
        entry = self.get(name)
        mgr = manager or entry.checkpoints
        if mgr is None:
            _swap_counter(name, "failed")
            raise SwapError(f"model {name!r} has no CheckpointManager "
                            "attached — nothing to swap from")
        rec = mgr.latest_valid()
        if rec is None:
            _swap_counter(name, "failed")
            raise SwapError(
                f"no valid checkpoint in {mgr.directory!r} — manifest "
                "empty or every entry torn/corrupt")
        if rec.get("file") and rec.get("file") == entry.version.get("file"):
            _swap_counter(name, "noop")
            return {"swapped": False, "model": name, "file": rec["file"],
                    "iteration": rec.get("iteration", 0),
                    "reason": "already serving this checkpoint"}
        path = os.path.join(mgr.directory, rec["file"])
        model = entry.model
        with tracing.span("serve/swap", model=name, file=rec.get("file")):
            # Decode + device-stage OUTSIDE the execution lock: traffic
            # keeps flowing while the npz trees are read. The live trees
            # are the templates, so a config/architecture drift fails
            # here — before anything was mutated. (Chaos seam:
            # "serve.decode" exercises exactly this pre-mutation path.)
            try:
                faults.fire("serve.decode")
                meta = validate_checkpoint(path)
                with zipfile.ZipFile(path, "r") as zf:
                    new_params = _npz_bytes_to_tree(
                        _read_entry(zf, path, PARAMS_ENTRY),
                        model.params_tree)
                    new_state = _npz_bytes_to_tree(
                        _read_entry(zf, path, STATE_ENTRY),
                        model.state_tree)
            except (CheckpointCorruptError, ValueError,
                    faults.FaultInjected) as e:
                _swap_counter(name, "failed")
                raise SwapError(
                    f"checkpoint {rec.get('file')!r} cannot serve model "
                    f"{name!r}: {e}") from e
            old = (model.params_tree, model.state_tree,
                   int(model.iteration), int(model.epoch))
            buckets = list(entry.engine.warmed_buckets) or [1]
            golden = entry.golden_batch
            with entry.engine.paused():
                old_out = None
                if golden is not None:
                    # The canary reference: OLD params' outputs on the
                    # retained golden batch, computed inside the pause
                    # window so no concurrent forward interleaves.
                    try:
                        old_out = _golden_forward(model, golden)
                    except Exception:
                        old_out = None  # old model already broken:
                        # canary degrades to the finiteness check
                model.params_tree = new_params
                model.state_tree = new_state
                model.iteration = int(meta.get("iteration", old[2]))
                model.epoch = int(meta.get("epoch", old[3]))
                if hasattr(model, "_rnn_carry"):
                    model._rnn_carry = None
                try:
                    # Warm the new params through the EXISTING AOT
                    # executables (warmup() re-precompile is a no-op per
                    # stored signature: zero compile events).
                    for b in buckets:
                        faults.fire("swap.warm")
                        model.warmup(b, time_steps=time_steps)
                    # Canary gate: the new params must produce all-finite
                    # outputs on the golden batch (and, with
                    # canary_max_drift set, stay within the drift budget
                    # of the old outputs) BEFORE traffic resumes.
                    if golden is not None:
                        new_out = _golden_forward(model, golden)
                        if not np.isfinite(new_out).all():
                            raise _CanaryRejected(
                                "non-finite outputs on the golden batch")
                        drift_cap = entry.canary_max_drift
                        if (drift_cap is not None and old_out is not None
                                and np.isfinite(old_out).all()):
                            drift = float(np.max(np.abs(
                                new_out - old_out))) if new_out.size else 0.0
                            if drift > drift_cap:
                                raise _CanaryRejected(
                                    f"golden-batch output drift {drift:.6g} "
                                    f"exceeds canary_max_drift {drift_cap}")
                except Exception as e:
                    # Auto-rollback: restore the OLD tree references
                    # (bitwise the pre-swap params) before the pause
                    # lock releases — traffic never sees the rejected
                    # checkpoint.
                    (model.params_tree, model.state_tree,
                     model.iteration, model.epoch) = old
                    if hasattr(model, "_rnn_carry"):
                        model._rnn_carry = None
                    canary = isinstance(e, _CanaryRejected)
                    _swap_counter(
                        name, "canary_rejected" if canary else "failed")
                    what = ("canary gate rejected"
                            if canary else "warm forward failed on")
                    raise SwapError(
                        f"{what} {rec.get('file')!r}; rolled back to "
                        f"previous params: {e}") from e
        with self._lock:
            entry.version = dict(rec)
            entry.swaps += 1
        _swap_counter(name, "ok")
        return {"swapped": True, "model": name, "file": rec.get("file"),
                "iteration": rec.get("iteration", 0)}

    # ------------------------------------------------------------ lifecycle
    def shutdown(self) -> None:
        for e in self.entries():
            e.engine.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
