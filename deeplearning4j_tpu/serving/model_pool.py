"""Named model pool with checkpoint-gated zero-downtime hot-swap.

Each entry pairs a live network with its continuous-batching execution
engine (parallel/inference.ParallelInference) and, optionally, the
CheckpointManager a training run publishes to. The pool is the
gateway's routing table (docs/serving.md) and the owner of the swap
protocol:

1. **Gate** — `CheckpointManager.latest_valid()` picks the newest
   checkpoint whose sha256 manifest entry verifies; torn/corrupt
   publishes are skipped, an empty manifest refuses the swap.
2. **Decode off the hot path** — params/state npz trees are read and
   device-staged against the LIVE model's trees as templates (same
   treedef, same shapes — an architecture mismatch fails here, before
   traffic is touched), while the engine keeps serving.
3. **Pause–assign–warm** — the engine's execution lock is held just
   long enough to assign the new trees and push one zero batch per
   warmed bucket through the EXISTING AOT executables (shapes are
   unchanged, so this re-verifies the fast path with the new params and
   compiles nothing). In-flight requests finish first; queued requests
   WAIT — none are dropped or failed.
4. **Rollback on failure** — if the warm forward raises, the old trees
   are restored before the lock is released and the swap reports
   failed; traffic never sees half-swapped params.

4½. **Canary gate** (docs/serving.md) — after pause-assign-warm, a
   retained golden batch runs through the NEW params; non-finite
   outputs (or drift past the optional `canary_max_drift` knob vs the
   OLD params' outputs on the same batch) auto-roll back to the old
   tree and raise `SwapError`, counted as
   `serving_swaps_total{outcome="canary_rejected"}` — a checkpoint
   that passes its sha256 gate but computes garbage never reaches
   traffic.

Swap outcomes land in `serving_swaps_total{model,outcome}`; per-model
queue depth is sampled into `serving_queue_depth{model}` and breaker
state into `serving_breaker_state{model}` at scrape time.
"""
from __future__ import annotations

import os
import threading
import weakref
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from ..data.padding import next_pow2_bucket, repeat_tail_rows
from ..optimize import tracing
from ..optimize.metrics import registry
from ..parallel.inference import (InferenceMode, NonFiniteOutputError,
                                  ParallelInference)
from ..quantize import quantize as quantize_mod
from ..utils import faults
from ..utils.model_serializer import (PARAMS_ENTRY, STATE_ENTRY,
                                      CheckpointCorruptError,
                                      _npz_bytes_to_tree, _read_entry,
                                      validate_checkpoint)
from .breaker import STATE_VALUES, CircuitBreaker
from .scheduler import DeviceScheduler, TIER_VALUES

__all__ = ["FusedModelGroup", "ModelEntry", "ModelPool", "SwapError"]


class SwapError(RuntimeError):
    """Hot-swap refused: no CheckpointManager attached, no valid
    checkpoint published, architecture mismatch, the warm forward
    failed, or the canary gate rejected the new params (in the latter
    two cases the old params were rolled back and are still serving)."""


class _CanaryRejected(RuntimeError):
    """Internal: the post-warm golden-batch check failed — distinguishes
    the canary_rejected swap outcome from a plain warm failure."""


#: serving precisions the quantized swap plane can promote
PRECISIONS = ("fp32", "bf16", "int8")


def _swap_counter(name: str, outcome: str, precision: str = "fp32"):
    registry().counter(
        "serving_swaps_total",
        "Checkpoint hot-swap attempts by outcome "
        "(ok/noop/failed/canary_rejected) and target precision"
        ).labels(model=name, outcome=outcome, precision=precision).inc()


def _set_precision_gauge(name: str, precision: str):
    """One-hot `serving_precision{model,precision}` gauge: the scrape
    surface's answer to 'what precision is this model serving at right
    now' without diffing swap counters."""
    g = registry().gauge(
        "serving_precision",
        "Active serving precision per model (1 = the labeled "
        "precision is live)")
    for p in PRECISIONS:
        g.labels(model=name, precision=p).set(
            1.0 if p == precision else 0.0)


def _fused_fallback_counter(reason: str, n: int = 1):
    registry().counter(
        "serving_fused_fallback_total",
        "Members served per-model instead of fused, by reason "
        "(ineligible/ejected/dissolved)"
        ).labels(reason=reason).inc(n)


def register_metrics() -> None:
    """Pre-register every pool-owned family (bench --once): a scrape
    taken before the first request must already show them at zero."""
    reg = registry()
    fam = reg.counter(
        "serving_fused_fallback_total",
        "Members served per-model instead of fused, by reason "
        "(ineligible/ejected/dissolved)")
    for reason in ("ineligible", "ejected", "dissolved"):
        fam.labels(reason=reason)
    reg.counter("serving_shed_total",
                "Requests shed before a forward served them, by reason")
    reg.counter("serving_forwards_total",
                "Coalesced forward passes executed")
    reg.counter("serving_rows_total",
                "Real (un-padded) request rows served")
    reg.histogram("serving_batch_rows",
                  "Real rows per coalesced forward (bucket fill)")
    reg.counter("serving_swaps_total",
                "Checkpoint hot-swap attempts by outcome "
                "(ok/noop/failed/canary_rejected) and target precision")
    reg.gauge("serving_precision",
              "Active serving precision per model (1 = the labeled "
              "precision is live)")
    reg.gauge("serving_queue_depth", "Requests queued per served model")


def _golden_forward(model, golden: np.ndarray) -> np.ndarray:
    """Run the golden batch through the model padded to its pow2 bucket
    (the same rule the engine coalesces to, so a warmed server compiles
    nothing here) and slice the real rows back."""
    n = golden.shape[0]
    xs = repeat_tail_rows(golden, next_pow2_bucket(n) - n)
    return np.asarray(model.output(xs))[:n]


class ModelEntry:
    """One named served model: the live network, its batching engine,
    and the checkpoint source it hot-swaps from."""

    def __init__(self, name: str, model, engine: ParallelInference,
                 checkpoints=None, breaker: Optional[CircuitBreaker] = None,
                 golden_batch: Optional[np.ndarray] = None,
                 canary_max_drift: Optional[float] = None,
                 tier: str = "standard", weight: float = 1.0):
        self.name = name
        self.model = model
        self.engine = engine
        self.checkpoints = checkpoints
        self.breaker = breaker
        # Priority tier + WFQ weight (serving/scheduler.py). Defaults
        # never construct a scheduler — single-model pools keep the
        # exact pre-scheduler dispatch path.
        self.tier = tier
        self.weight = float(weight)
        # Fused-group plumbing: members of a FusedModelGroup share one
        # engine; `transform` slices this member's output columns out of
        # the fused forward, `group` owns the per-member swap protocol.
        self.transform = None
        self.group: Optional["FusedModelGroup"] = None
        # Canary substrate: a small retained input batch (provided, or
        # captured from the first served request) replayed through new
        # params before a swap promotes them; `canary_max_drift` bounds
        # max|new - old| output drift on it (None = finiteness only).
        self.golden_batch = None if golden_batch is None else \
            np.asarray(golden_batch)
        self.canary_max_drift = canary_max_drift
        # Active serving precision ("fp32" until a quantized swap
        # promotes an int8/bf16 tree) — stamped on metrics, traces,
        # and describe() so the A/B is attributable everywhere.
        self.precision = "fp32"
        # Manifest record of the checkpoint currently serving; empty
        # until the first swap (initial params came from the caller,
        # not a published checkpoint).
        self.version: Dict[str, Any] = {}
        self.swaps = 0

    def describe(self) -> Dict[str, Any]:
        out = {
            "model": self.name,
            "version": self.version.get("file", "initial"),
            "iteration": int(getattr(self.model, "iteration", 0)),
            "swaps": self.swaps,
            "queue_depth": self.engine.queue_depth(),
            "warmed_buckets": list(self.engine.warmed_buckets),
            "total_forwards": self.engine.total_forwards,
            "total_shed": self.engine.total_shed,
            "total_batch_failures": self.engine.total_batch_failures,
            "tier": self.tier,
            "weight": self.weight,
            "batch_timeout_ms": float(self.engine.batch_timeout_ms),
            "precision": self.precision,
        }
        if self.group is not None:
            out["fused_group"] = self.group.name
        if self.breaker is not None:
            out["breaker"] = self.breaker.describe()
        return out


class ModelPool:
    """Thread-safe name → ModelEntry routing table + swap protocol."""

    def __init__(self, scheduler: Optional[DeviceScheduler] = None):
        self._lock = threading.Lock()
        self._entries: Dict[str, ModelEntry] = {}
        # Cross-entry device arbitration (serving/scheduler.py). None
        # until a caller passes one or an add() names a non-default
        # tier/weight — a pool that never does keeps the exact
        # pre-scheduler behavior (satellite: bitwise default).
        self.scheduler = scheduler
        # Weakly-referenced scrape collector: queue depth is sampled at
        # scrape time only (never in the request path), and a dead pool
        # silently drops out of the scrape.
        wr = weakref.ref(self)

        def _collect(reg, _wr=wr):
            pool = _wr()
            if pool is None:
                return
            g = reg.gauge("serving_queue_depth",
                          "Requests queued per served model")
            bg = reg.gauge("serving_breaker_state",
                           "Circuit breaker state per model (0=closed, "
                           "1=open, 2=half_open)")
            for e in pool.entries():
                g.labels(model=e.name).set(e.engine.queue_depth())
                if e.breaker is not None:
                    bg.labels(model=e.name).set(
                        STATE_VALUES[e.breaker.state])

        registry().register_collector(_collect)

    # ----------------------------------------------------------- scheduling
    def _ensure_scheduler(self) -> DeviceScheduler:
        """Create the shared DeviceScheduler on first demand and
        retro-register every existing entry at its recorded tier/weight
        (entries added before any priority was expressed default to
        standard/1.0 — the same arbitration-neutral values)."""
        if self.scheduler is None:
            self.scheduler = DeviceScheduler()
            for e in self.entries():
                self._sched_register(e)
        return self.scheduler

    def _sched_register(self, entry: ModelEntry) -> None:
        """Register one entry (or its fused group) with the scheduler
        and point its engine at the shared dispatch slot. A fused
        group's members schedule as ONE unit under the group name."""
        sch = self.scheduler
        if sch is None:
            return
        sched_name = entry.group.name if entry.group is not None \
            else entry.name
        sch.register(sched_name, tier=entry.tier, weight=entry.weight,
                     depth_fn=entry.engine.queue_depth)
        entry.engine.scheduler = sch
        entry.engine.sched_name = sched_name

    # ------------------------------------------------------------- routing
    def _serving_families(self):
        """The per-engine telemetry families (registry dedups by name)."""
        reg = registry()
        return (
            reg.counter(
                "serving_shed_total",
                "Requests shed before a forward served them, by reason"),
            reg.counter("serving_forwards_total",
                        "Coalesced forward passes executed"),
            reg.counter("serving_rows_total",
                        "Real (un-padded) request rows served"),
            reg.histogram(
                "serving_batch_rows",
                "Real rows per coalesced forward (bucket fill)"),
            reg.counter(
                "serving_batch_failures_total",
                "Coalesced forwards that raised or returned non-finite "
                "outputs"),
        )

    def _wire_hooks(self, entry: ModelEntry) -> None:
        """Engine-level telemetry hooks for a single-model entry: late
        (in-queue) deadline sheds, per-forward batch stats, and batch
        failures, labeled by model; breaker success/failure per
        forward."""
        shed_c, fwd_c, rows_c, fill_h, fail_c = self._serving_families()
        name, breaker = entry.name, entry.breaker

        def _on_shed(req, reason, _name=name):
            shed_c.labels(model=_name, reason=reason).inc()

        def _on_batch(reqs, rows, bucket, dur_s, _name=name,
                      _entry=entry, _breaker=breaker):
            fwd_c.labels(model=_name).inc()
            rows_c.labels(model=_name).inc(rows)
            fill_h.labels(model=_name).observe(rows)
            _breaker.record_success()
            if (_entry.golden_batch is None and reqs
                    and getattr(reqs[0], "x", None) is not None):
                # Retain a slice of real traffic as the swap canary
                # input (first served request, at most 4 rows). Decode
                # requests carry prompts, not feature rows — no capture.
                _entry.golden_batch = np.asarray(reqs[0].x[:4]).copy()

        def _on_batch_error(exc, n_requests, _name=name, _breaker=breaker):
            fail_c.labels(model=_name).inc()
            _breaker.record_failure(
                trip=isinstance(exc, NonFiniteOutputError))

        entry.engine.on_shed = _on_shed
        entry.engine.on_batch = _on_batch
        entry.engine.on_batch_error = _on_batch_error

    def add(self, name: str, model, *, checkpoints=None,
            batch_limit: int = 32, queue_limit: int = 256,
            batch_timeout_ms: float = 2.0,
            inference_mode: InferenceMode = InferenceMode.BATCHED,
            check_finite: bool = True,
            breaker: Optional[CircuitBreaker] = None,
            breaker_threshold: int = 5,
            breaker_reset_s: float = 30.0,
            golden_batch=None,
            canary_max_drift: Optional[float] = None,
            packed_admission: bool = False,
            pack_bucket: int = 0,
            tier: str = "standard",
            weight: float = 1.0) -> ModelEntry:
        """Register an init()ed model under `name` behind a fresh
        continuous-batching engine. `checkpoints` (a CheckpointManager
        or a directory path) enables hot-swap for this entry.

        Resilience knobs (docs/serving.md): `check_finite` fails a
        forward whose outputs carry NaN/Inf (on by default for served
        entries — the breaker's instant trip); `breaker` (or
        `breaker_threshold`/`breaker_reset_s` for the default one)
        guards this entry's /predict path; `golden_batch` seeds the
        swap canary input (otherwise the first served request's rows
        are retained); `canary_max_drift` bounds output drift a swap
        may introduce on the golden batch (None = finiteness only);
        `packed_admission`/`pack_bucket` coalesce short sequence
        requests into one segment-masked [1, pack_bucket] row (the
        model's attention layers must run packed_segments=True —
        docs/serving.md §packed).

        Priority knobs (docs/serving.md §multi-model): `tier`
        (critical/standard/batch) and `weight` (WFQ share within the
        tier) rank this entry against its pool-mates under saturation.
        Naming a non-default tier or weight creates the pool's shared
        DeviceScheduler on the spot (and retro-registers every existing
        entry); all-default pools never construct one and keep the
        exact single-model dispatch path."""
        if tier not in TIER_VALUES:
            raise ValueError(f"unknown tier {tier!r}; one of "
                             f"{tuple(TIER_VALUES)}")
        if isinstance(checkpoints, (str, os.PathLike)):
            from ..optimize.resilience import CheckpointManager
            checkpoints = CheckpointManager(checkpoints)
        engine = ParallelInference(
            model, inference_mode=inference_mode, batch_limit=batch_limit,
            queue_limit=queue_limit, batch_timeout_ms=batch_timeout_ms,
            check_finite=check_finite, packed_admission=packed_admission,
            pack_bucket=pack_bucket)
        if breaker is None:
            breaker = CircuitBreaker(name,
                                     failure_threshold=breaker_threshold,
                                     reset_timeout_s=breaker_reset_s)
        entry = ModelEntry(name, model, engine, checkpoints,
                           breaker=breaker, golden_batch=golden_batch,
                           canary_max_drift=canary_max_drift,
                           tier=tier, weight=weight)
        self._wire_hooks(entry)
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            self._entries[name] = entry
        _set_precision_gauge(name, entry.precision)
        if (self.scheduler is not None or tier != "standard"
                or weight != 1.0):
            self._ensure_scheduler()
            self._sched_register(entry)
        return entry

    def add_decode(self, name: str, model, *, checkpoints=None,
                   max_decode_batch: int = 8, queue_limit: int = 64,
                   max_context: Optional[int] = None,
                   pack_bucket: int = 64,
                   kv_block_tokens: int = 16,
                   kv_max_blocks: int = 256,
                   feature_dim: Optional[int] = None,
                   check_finite: bool = True,
                   breaker: Optional[CircuitBreaker] = None,
                   breaker_threshold: int = 5,
                   breaker_reset_s: float = 30.0,
                   tier: str = "standard",
                   weight: float = 1.0) -> ModelEntry:
        """Register a GENERATIVE entry under `name` behind a
        DecodeEngine (serving/decode.py): token-granularity continuous
        batching over a paged KV cache, served through POST /generate.

        The model family picks the adapter: a
        :class:`~.decode.TransformerDecoder` decodes through the
        packed-prefill + paged-KV token arm (`pack_bucket`,
        `kv_block_tokens`, `kv_max_blocks` size that plane); a streaming
        network exposing ``rnn_time_step`` decodes through the
        recurrent arm (`feature_dim` is its per-step input width —
        required, and the net's ``n_out`` must equal it, since the
        output feeds back as the next step's input).

        Breaker / tier / weight / checkpoint knobs mean exactly what
        they mean on :meth:`add` — the entry rides the same routing
        table, swap protocol (the engine's ``swap_warm`` re-warms the
        decode signature grid inside the pause window), and describe()
        surface."""
        from .decode import (DecodeEngine, PagedKVCache, RecurrentAdapter,
                             TransformerAdapter, TransformerDecoder)
        if tier not in TIER_VALUES:
            raise ValueError(f"unknown tier {tier!r}; one of "
                             f"{tuple(TIER_VALUES)}")
        if isinstance(checkpoints, (str, os.PathLike)):
            from ..optimize.resilience import CheckpointManager
            checkpoints = CheckpointManager(checkpoints)
        if isinstance(model, TransformerDecoder):
            cache = PagedKVCache(
                layers=model.n_layers, heads=model.heads,
                head_dim=model.head_dim,
                block_tokens=kv_block_tokens, max_blocks=kv_max_blocks)
            adapter = TransformerAdapter(model, cache,
                                         pack_bucket=pack_bucket,
                                         check_finite=check_finite)
        elif hasattr(model, "rnn_time_step"):
            if feature_dim is None:
                raise ValueError(
                    "recurrent decode entries need feature_dim= (the "
                    "net's per-step input width)")
            adapter = RecurrentAdapter(model, feature_dim=feature_dim,
                                       check_finite=check_finite)
        else:
            raise ValueError(
                f"model {type(model).__name__} fits neither decode arm: "
                "need a TransformerDecoder or a streaming net with "
                "rnn_time_step")
        engine = DecodeEngine(adapter, name=name,
                              max_decode_batch=max_decode_batch,
                              queue_limit=queue_limit,
                              max_context=max_context)
        if breaker is None:
            breaker = CircuitBreaker(name,
                                     failure_threshold=breaker_threshold,
                                     reset_timeout_s=breaker_reset_s)
        entry = ModelEntry(name, model, engine, checkpoints,
                           breaker=breaker, tier=tier, weight=weight)
        self._wire_hooks(entry)
        with self._lock:
            if name in self._entries:
                engine.shutdown()
                raise ValueError(f"model {name!r} already registered")
            self._entries[name] = entry
        _set_precision_gauge(name, entry.precision)
        if (self.scheduler is not None or tier != "standard"
                or weight != 1.0):
            self._ensure_scheduler()
            self._sched_register(entry)
        return entry

    def add_fused_group(self, group_name: str, members, *,
                        checkpoints: Optional[Dict[str, Any]] = None,
                        batch_limit: int = 32, queue_limit: int = 256,
                        batch_timeout_ms: float = 2.0,
                        breaker_threshold: int = 5,
                        breaker_reset_s: float = 30.0,
                        canary_max_drift: Optional[float] = None,
                        tier: str = "standard", weight: float = 1.0):
        """Register N same-input-geometry models as ONE fused pool
        entry group (docs/serving.md §multi-model): their graphs merge
        into a single channel-concatenated forward
        (nn/graph/fusion.build_fused_serving_net) behind ONE shared
        continuous-batching engine, each member's traffic rides the
        shared batch, and each member's output columns are sliced back
        under its own name — hot-swap, canary, checkpoints, and circuit
        breakers stay PER MEMBER.

        `members` is an ordered name → model mapping (or a list of
        (name, model) pairs); `checkpoints` maps member names to their
        CheckpointManagers / directories. The group schedules as one
        WFQ unit under `group_name` at `tier`/`weight`.

        Fallback rule: when the member set cannot merge (not graphs,
        differing input geometry, uninitialized members), every member
        is registered as an ordinary independent entry instead —
        counted in `serving_fused_fallback_total{reason="ineligible"}`
        — and the list of independent entries is returned. On success
        the :class:`FusedModelGroup` is returned."""
        from ..nn.graph.fusion import FusionIneligibleError
        named = list(members.items()) if isinstance(members, dict) \
            else list(members)
        ckpts = checkpoints or {}
        with self._lock:
            for nm, _ in named:
                if nm in self._entries:
                    raise ValueError(f"model {nm!r} already registered")
        try:
            group = FusedModelGroup(
                self, group_name, named, checkpoints=ckpts,
                batch_limit=batch_limit, queue_limit=queue_limit,
                batch_timeout_ms=batch_timeout_ms,
                breaker_threshold=breaker_threshold,
                breaker_reset_s=breaker_reset_s,
                canary_max_drift=canary_max_drift,
                tier=tier, weight=weight)
        except FusionIneligibleError as e:
            _fused_fallback_counter("ineligible", len(named))
            entries = [self.add(nm, m, checkpoints=ckpts.get(nm),
                                batch_limit=batch_limit,
                                queue_limit=queue_limit,
                                batch_timeout_ms=batch_timeout_ms,
                                breaker_threshold=breaker_threshold,
                                breaker_reset_s=breaker_reset_s,
                                canary_max_drift=canary_max_drift,
                                tier=tier, weight=weight)
                       for nm, m in named]
            for entry in entries:
                entry.fused_fallback = str(e)
            return entries
        with self._lock:
            for nm, _ in named:
                if nm in self._entries:  # raced a concurrent add
                    group.engine.shutdown()
                    raise ValueError(f"model {nm!r} already registered")
            for entry in group.member_entries():
                self._entries[entry.name] = entry
        if (self.scheduler is not None or tier != "standard"
                or weight != 1.0):
            self._ensure_scheduler()
            self._sched_register(group.member_entries()[0])
        return group

    def eject_member(self, name: str) -> ModelEntry:
        """Fall one member back to per-model dispatch (swap-state or
        behavior divergence): the member leaves its fused group and gets
        its own independent engine; the group rebuilds around the
        remaining members, or dissolves entirely when fewer than two
        remain. Counted in `serving_fused_fallback_total`."""
        entry = self.get(name)
        if entry.group is None:
            raise ValueError(f"model {name!r} is not in a fused group")
        return entry.group.eject(name)

    def reconfigure(self, name: str, *,
                    packed_admission: Optional[bool] = None,
                    pack_bucket: Optional[int] = None,
                    tier: Optional[str] = None,
                    weight: Optional[float] = None,
                    batch_timeout_ms: Optional[float] = None,
                    breaker_threshold: Optional[int] = None,
                    breaker_reset_s: Optional[float] = None
                    ) -> Dict[str, Any]:
        """Live per-entry reconfiguration (the gateway's POST /config
        surface and the AutoTuner's per-entry actuator). Tier/weight
        changes re-rank the entry in the shared scheduler (creating it
        on first use); `batch_timeout_ms` (the collector linger) is a
        plain live set — the collector thread reads it every iteration,
        so the next coalescing window already honors it, no engine
        rebuild, no recompile; `breaker_threshold`/`breaker_reset_s`
        retune the entry's circuit breaker in place
        (CircuitBreaker.reconfigure — validated, effective on the next
        admission decision); packed-admission changes rebuild the
        entry's engine with the new admission mode — the old engine
        drains its queue, the new one is warmed to the old bucket set
        first, and no queued request is dropped. Fused-group members
        cannot be reconfigured in place (eject_member first)."""
        entry = self.get(name)
        if entry.group is not None:
            raise ValueError(
                f"model {name!r} is a member of fused group "
                f"{entry.group.name!r}; eject_member() it before "
                "reconfiguring")
        changed: List[str] = []
        if breaker_threshold is not None or breaker_reset_s is not None:
            if entry.breaker is None:
                raise ValueError(
                    f"model {name!r} has no circuit breaker to "
                    "reconfigure")
            entry.breaker.reconfigure(failure_threshold=breaker_threshold,
                                      reset_timeout_s=breaker_reset_s)
            if breaker_threshold is not None:
                changed.append("breaker_threshold")
            if breaker_reset_s is not None:
                changed.append("breaker_reset_s")
        if batch_timeout_ms is not None:
            bt = float(batch_timeout_ms)
            if bt < 0:
                raise ValueError("batch_timeout_ms must be >= 0")
            entry.engine.batch_timeout_ms = bt
            changed.append("batch_timeout_ms")
        if tier is not None or weight is not None:
            if tier is not None:
                if tier not in TIER_VALUES:
                    raise ValueError(f"unknown tier {tier!r}; one of "
                                     f"{tuple(TIER_VALUES)}")
                entry.tier = tier
                changed.append("tier")
            if weight is not None:
                if float(weight) <= 0:
                    raise ValueError("weight must be > 0")
                entry.weight = float(weight)
                changed.append("weight")
            self._ensure_scheduler()
            self._sched_register(entry)
        if packed_admission is not None or pack_bucket is not None:
            old = entry.engine
            packed = old.packed_admission if packed_admission is None \
                else bool(packed_admission)
            bucket = old.pack_bucket if pack_bucket is None \
                else int(pack_bucket)
            engine = ParallelInference(
                entry.model, inference_mode=old.inference_mode,
                batch_limit=old.batch_limit,
                batch_timeout_ms=old.batch_timeout_ms,
                queue_limit=old._queue.maxsize,
                check_finite=old.check_finite,
                packed_admission=packed, pack_bucket=bucket)
            if old.warmed_buckets:
                # Warm the replacement BEFORE it takes traffic so the
                # flip costs no steady-state compiles (shared model =
                # shared compile cache; only a new packed signature
                # compiles, once, here).
                engine.warmup(max_bucket=max(old.warmed_buckets))
            entry.engine = engine
            self._wire_hooks(entry)
            self._sched_register(entry)
            old.shutdown()
            changed.append("packed_admission")
        out = entry.describe()
        out["reconfigured"] = changed
        return out

    def reconfigure_scheduler(self, **knobs) -> Dict[str, Any]:
        """Scheduler-level live reconfiguration (quantum / shed_depth /
        starvation_budget / tier_slo_ms — DeviceScheduler.reconfigure),
        creating the shared scheduler on first use so an operator can
        set SLOs before any tiered entry exists. Raises ValueError on
        invalid values, mutating nothing."""
        return self._ensure_scheduler().reconfigure(**knobs)

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"no model named {name!r} in the pool "
                           f"(have: {sorted(self.names())})")
        return entry

    def remove(self, name: str) -> None:
        with self._lock:
            entry = self._entries.get(name)
            if entry is not None and entry.group is not None:
                raise ValueError(
                    f"model {name!r} is a member of fused group "
                    f"{entry.group.name!r}; eject_member() it first")
            self._entries.pop(name, None)
        if entry is not None:
            entry.engine.shutdown()

    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def entries(self) -> List[ModelEntry]:
        with self._lock:
            return list(self._entries.values())

    def describe(self) -> List[Dict[str, Any]]:
        return [e.describe() for e in self.entries()]

    # -------------------------------------------------------------- warmup
    def warmup(self, name: Optional[str] = None, *,
               max_bucket: Optional[int] = None,
               time_steps: Optional[int] = None) -> "ModelPool":
        """AOT-precompile every pow2 bucket for one model (or all):
        after this, steady-state serving never compiles."""
        targets = [self.get(name)] if name else self.entries()
        for e in targets:
            e.engine.warmup(max_bucket=max_bucket, time_steps=time_steps)
        return self

    # ---------------------------------------------------------------- swap
    def swap(self, name: str, *, manager=None,
             time_steps: Optional[int] = None,
             quantize: Optional[str] = None) -> Dict[str, Any]:
        """Checkpoint-gated zero-downtime hot-swap (module docstring
        protocol). Returns {"swapped": bool, "model", "file",
        "iteration", "precision"}; raises :class:`SwapError` when the
        gate or the warm fails (old params keep serving either way).

        `quantize` ("int8" | "bf16" | "fp32"/None) makes quantization a
        DEPLOYMENT decision: the decoded fp32 checkpoint is quantized
        via quantize.quantize_tree before promotion, and the golden-
        batch canary compares the quantized outputs against the
        currently-serving ones under `canary_max_drift` — a quantized
        tree that drifts past the accuracy budget is rolled back with
        the `canary_rejected` outcome exactly like a bad checkpoint."""
        target = quantize or "fp32"
        if target not in PRECISIONS:
            _swap_counter(name, "failed", target)
            raise SwapError(f"unknown quantize mode {quantize!r}; one of "
                            f"{PRECISIONS}")
        entry = self.get(name)
        if entry.group is not None:
            # Fused-group member: the group owns the swap protocol (the
            # fused trees must be rebuilt under the SHARED engine's
            # pause). /swap stays per-member for callers either way.
            return entry.group.swap_member(name, manager=manager,
                                           time_steps=time_steps,
                                           quantize=quantize)
        mgr = manager or entry.checkpoints
        if mgr is None:
            _swap_counter(name, "failed", target)
            raise SwapError(f"model {name!r} has no CheckpointManager "
                            "attached — nothing to swap from")
        rec = mgr.latest_valid()
        if rec is None:
            _swap_counter(name, "failed", target)
            raise SwapError(
                f"no valid checkpoint in {mgr.directory!r} — manifest "
                "empty or every entry torn/corrupt")
        if (rec.get("file") and rec.get("file") == entry.version.get("file")
                and target == entry.precision):
            # Same file AND same precision: re-quantizing the serving
            # checkpoint to a different precision is a real swap.
            _swap_counter(name, "noop", target)
            return {"swapped": False, "model": name, "file": rec["file"],
                    "iteration": rec.get("iteration", 0),
                    "precision": entry.precision,
                    "reason": "already serving this checkpoint"}
        path = os.path.join(mgr.directory, rec["file"])
        model = entry.model
        with tracing.span("serve/swap", cat="serve", model=name,
                          file=rec.get("file")):
            # Decode + device-stage OUTSIDE the execution lock: traffic
            # keeps flowing while the npz trees are read. The live trees
            # are the templates, so a config/architecture drift fails
            # here — before anything was mutated. (Chaos seam:
            # "serve.decode" exercises exactly this pre-mutation path.)
            try:
                faults.fire("serve.decode")
                meta = validate_checkpoint(path)
                # Checkpoints are always fp32: when the LIVE tree is
                # quantized, the decode template is its dequantized
                # shape (same treedef as the published file).
                params_template = model.params_tree
                if entry.precision != "fp32":
                    params_template = quantize_mod.dequantize_tree(
                        params_template)
                with zipfile.ZipFile(path, "r") as zf:
                    new_params = _npz_bytes_to_tree(
                        _read_entry(zf, path, PARAMS_ENTRY),
                        params_template)
                    new_state = _npz_bytes_to_tree(
                        _read_entry(zf, path, STATE_ENTRY),
                        model.state_tree)
                if target != "fp32":
                    # Quantize OFF the hot path, before the pause: the
                    # engine keeps serving old params while per-channel
                    # scales are computed.
                    new_params = quantize_mod.quantize_tree(
                        new_params, target)
            except (CheckpointCorruptError, ValueError,
                    quantize_mod.AlreadyQuantizedError,
                    faults.FaultInjected) as e:
                _swap_counter(name, "failed", target)
                raise SwapError(
                    f"checkpoint {rec.get('file')!r} cannot serve model "
                    f"{name!r}: {e}") from e
            old = (model.params_tree, model.state_tree,
                   int(model.iteration), int(model.epoch))
            buckets = list(entry.engine.warmed_buckets) or [1]
            golden = entry.golden_batch
            # The pause window is the stall every queued request feels
            # (their sched_wait phase) — record it as its own span so a
            # serving-trace tail reads "swap in progress", not mystery.
            with tracing.span("serve/swap_pause", cat="serve",
                              model=name), entry.engine.paused():
                old_out = None
                if golden is not None:
                    # The canary reference: OLD params' outputs on the
                    # retained golden batch, computed inside the pause
                    # window so no concurrent forward interleaves.
                    try:
                        old_out = _golden_forward(model, golden)
                    except Exception:
                        old_out = None  # old model already broken:
                        # canary degrades to the finiteness check
                model.params_tree = new_params
                model.state_tree = new_state
                model.iteration = int(meta.get("iteration", old[2]))
                model.epoch = int(meta.get("epoch", old[3]))
                if hasattr(model, "_rnn_carry"):
                    model._rnn_carry = None
                try:
                    # Warm the new params through the EXISTING AOT
                    # executables (warmup() re-precompile is a no-op per
                    # stored signature: zero compile events). Decode
                    # engines warm their own (row × KV view) grid.
                    swap_warm = getattr(entry.engine, "swap_warm", None)
                    for b in buckets:
                        faults.fire("swap.warm")
                        if swap_warm is not None:
                            swap_warm(b)
                        else:
                            model.warmup(b, time_steps=time_steps)
                    # Canary gate: the new params must produce all-finite
                    # outputs on the golden batch (and, with
                    # canary_max_drift set, stay within the drift budget
                    # of the old outputs) BEFORE traffic resumes.
                    if golden is not None:
                        new_out = _golden_forward(model, golden)
                        if not np.isfinite(new_out).all():
                            raise _CanaryRejected(
                                "non-finite outputs on the golden batch")
                        drift_cap = entry.canary_max_drift
                        if (drift_cap is not None and old_out is not None
                                and np.isfinite(old_out).all()):
                            drift = float(np.max(np.abs(
                                new_out - old_out))) if new_out.size else 0.0
                            if drift > drift_cap:
                                raise _CanaryRejected(
                                    f"golden-batch output drift {drift:.6g} "
                                    f"exceeds canary_max_drift {drift_cap}")
                except Exception as e:
                    # Auto-rollback: restore the OLD tree references
                    # (bitwise the pre-swap params) before the pause
                    # lock releases — traffic never sees the rejected
                    # checkpoint.
                    (model.params_tree, model.state_tree,
                     model.iteration, model.epoch) = old
                    if hasattr(model, "_rnn_carry"):
                        model._rnn_carry = None
                    canary = isinstance(e, _CanaryRejected)
                    _swap_counter(
                        name, "canary_rejected" if canary else "failed",
                        target)
                    what = ("canary gate rejected"
                            if canary else "warm forward failed on")
                    raise SwapError(
                        f"{what} {rec.get('file')!r} (precision "
                        f"{target}); rolled back to previous params: "
                        f"{e}") from e
        with self._lock:
            entry.version = dict(rec)
            entry.swaps += 1
            entry.precision = target
        _set_precision_gauge(name, target)
        _swap_counter(name, "ok", target)
        return {"swapped": True, "model": name, "file": rec.get("file"),
                "iteration": rec.get("iteration", 0),
                "precision": target}

    # ------------------------------------------------------------ lifecycle
    def shutdown(self) -> None:
        for e in self.entries():
            e.engine.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


class FusedModelGroup:
    """N co-resident same-input-geometry models behind ONE forward.

    The members' graphs are merged (nn/graph/fusion.merge_serving_conf)
    and sibling-fused into a single channel-concatenated network: one
    shared continuous-batching engine coalesces EVERY member's traffic
    into the same batch, runs one dispatch, and each request's transform
    slices its member's columns back out. One dispatch + one coalescing
    window serving N models is the multi-model throughput win
    (docs/serving.md §multi-model measures it).

    Per-member semantics are preserved:

    - **Breakers** — each member keeps its own CircuitBreaker. Success
      is recorded by the member's column transform on its normal path;
      failures are attributed through ``err.request_tags`` (only the
      members whose requests rode the failed forward are charged), and
      a member whose columns turn non-finite trips ONLY its own breaker
      (the fused engine runs check_finite=False; finiteness is judged
      per member column slice).
    - **Hot-swap / canary / checkpoints** — :meth:`swap_member` runs the
      full pool swap protocol for one member: decode against the SOLO
      member trees (the source of truth), rebuild the fused trees under
      the shared engine's pause, warm through the existing fused
      executables (zero compiles), and gate on a member-column golden
      canary with rollback of both solo and fused trees.
    - **Fallback** — an ineligible member set never reaches this class
      (ModelPool.add_fused_group registers independents instead), and
      :meth:`eject` returns one divergent member to per-model dispatch
      at runtime, rebuilding or dissolving the group.
    """

    def __init__(self, pool: ModelPool, name: str, named_members,
                 *, checkpoints: Dict[str, Any], batch_limit: int,
                 queue_limit: int, batch_timeout_ms: float,
                 breaker_threshold: int, breaker_reset_s: float,
                 canary_max_drift: Optional[float],
                 tier: str, weight: float):
        from ..nn.graph import fusion
        if tier not in TIER_VALUES:
            raise ValueError(f"unknown tier {tier!r}; one of "
                             f"{tuple(TIER_VALUES)}")
        self.pool = pool
        self.name = name
        self.tier = tier
        self.weight = float(weight)
        self._engine_kw = dict(batch_limit=batch_limit,
                               queue_limit=queue_limit,
                               batch_timeout_ms=batch_timeout_ms)
        self._breaker_kw = dict(failure_threshold=breaker_threshold,
                                reset_timeout_s=breaker_reset_s)
        self.members = [nm for nm, _ in named_members]
        self._models = {nm: m for nm, m in named_members}
        # Raises FusionIneligibleError on divergent members — the
        # caller's fallback-to-independent seam.
        self.fused_net, self.fusion_groups, self.col_slices = \
            fusion.build_fused_serving_net(named_members)
        # One engine for the whole group. check_finite stays OFF at the
        # engine level: a NaN in one member's columns must trip that
        # member's breaker only, so finiteness is judged per slice in
        # the member transforms below.
        self.engine = ParallelInference(self.fused_net,
                                        check_finite=False,
                                        **self._engine_kw)
        self._entries: Dict[str, ModelEntry] = {}
        for nm, model in named_members:
            ck = checkpoints.get(nm)
            if isinstance(ck, (str, os.PathLike)):
                from ..optimize.resilience import CheckpointManager
                ck = CheckpointManager(ck)
            entry = ModelEntry(
                nm, model, self.engine, ck,
                breaker=CircuitBreaker(nm, **self._breaker_kw),
                canary_max_drift=canary_max_drift,
                tier=tier, weight=weight)
            entry.group = self
            entry.transform = self._member_transform(nm, entry.breaker)
            self._entries[nm] = entry
        self._wire_group_hooks()

    # ------------------------------------------------------------ plumbing
    def member_entries(self) -> List[ModelEntry]:
        return [self._entries[nm] for nm in self.members]

    def named_members(self):
        return [(nm, self._models[nm]) for nm in self.members]

    def _member_transform(self, name: str, breaker: CircuitBreaker):
        """Column view for one member: slice its columns out of the
        fused output, fail THIS request (and trip THIS breaker, via the
        tagged error path) when they are non-finite, record breaker
        success otherwise."""
        def _t(rows, _name=name, _breaker=breaker):
            off, width = self.col_slices[_name]
            cols = np.asarray(rows)[..., off:off + width]
            if not np.isfinite(cols).all():
                raise NonFiniteOutputError(
                    f"fused member {_name!r} produced non-finite output "
                    "columns")
            _breaker.record_success()
            return cols
        return _t

    def _wire_group_hooks(self) -> None:
        """Shared-engine telemetry: batch stats label the GROUP (one
        forward serves many members); sheds label the member that owned
        the request; failures are attributed to member breakers through
        the error's request_tags."""
        shed_c, fwd_c, rows_c, fill_h, fail_c = \
            self.pool._serving_families()

        def _on_shed(req, reason, _g=self.name):
            shed_c.labels(model=req.tag or _g, reason=reason).inc()

        def _on_batch(reqs, rows, bucket, dur_s, _g=self.name):
            fwd_c.labels(model=_g).inc()
            rows_c.labels(model=_g).inc(rows)
            fill_h.labels(model=_g).observe(rows)
            for r in reqs:
                e = self._entries.get(r.tag)
                if e is not None and e.golden_batch is None:
                    # Retain per-member canary input from real traffic.
                    e.golden_batch = np.asarray(r.x[:4]).copy()

        def _on_batch_error(exc, n_requests, _g=self.name):
            fail_c.labels(model=_g).inc()
            trip = isinstance(exc, NonFiniteOutputError)
            tags = getattr(exc, "request_tags", None) or []
            charged = set()
            for tag in tags:
                e = self._entries.get(tag)
                if e is not None and tag not in charged:
                    charged.add(tag)
                    e.breaker.record_failure(trip=trip)

        self.engine.on_shed = _on_shed
        self.engine.on_batch = _on_batch
        self.engine.on_batch_error = _on_batch_error

    # ---------------------------------------------------------------- swap
    def swap_member(self, name: str, *, manager=None,
                    time_steps: Optional[int] = None,
                    quantize: Optional[str] = None) -> Dict[str, Any]:
        """Per-member checkpoint hot-swap inside the fused group: the
        ModelPool.swap protocol with the fused forward as the execution
        substrate. The member's SOLO model stays the decode template and
        source of truth; under the shared engine's pause the solo trees
        mutate, the fused trees rebuild from ALL members' current trees
        (concat — no compile), the warmed buckets re-verify through the
        existing fused executables, and a member-column canary gates
        promotion. Rollback restores both solo and fused trees, so
        neither this member nor its groupmates ever see half-swapped
        params."""
        entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"no member {name!r} in fused group "
                           f"{self.name!r}")
        if quantize and quantize != "fp32":
            # The fused forward runs ONE channel-concatenated weight
            # per layer; a single member at a different precision would
            # force per-member splits back into the fused matmul.
            # Quantize the whole group or serve the member solo.
            _swap_counter(name, "failed", quantize)
            raise SwapError(
                f"quantized swap is per-model; member {name!r} of fused "
                f"group {self.name!r} cannot change precision alone "
                "(eject it or serve it unfused)")
        mgr = manager or entry.checkpoints
        if mgr is None:
            _swap_counter(name, "failed")
            raise SwapError(f"model {name!r} has no CheckpointManager "
                            "attached — nothing to swap from")
        rec = mgr.latest_valid()
        if rec is None:
            _swap_counter(name, "failed")
            raise SwapError(
                f"no valid checkpoint in {mgr.directory!r} — manifest "
                "empty or every entry torn/corrupt")
        if rec.get("file") and rec.get("file") == entry.version.get("file"):
            _swap_counter(name, "noop")
            return {"swapped": False, "model": name, "file": rec["file"],
                    "iteration": rec.get("iteration", 0),
                    "reason": "already serving this checkpoint"}
        from ..nn.graph.fusion import fused_trees_from_members
        path = os.path.join(mgr.directory, rec["file"])
        model = entry.model  # the member's SOLO network
        fused = self.fused_net
        with tracing.span("serve/swap", cat="serve", model=name,
                          group=self.name, file=rec.get("file")):
            try:
                faults.fire("serve.decode")
                meta = validate_checkpoint(path)
                with zipfile.ZipFile(path, "r") as zf:
                    new_params = _npz_bytes_to_tree(
                        _read_entry(zf, path, PARAMS_ENTRY),
                        model.params_tree)
                    new_state = _npz_bytes_to_tree(
                        _read_entry(zf, path, STATE_ENTRY),
                        model.state_tree)
            except (CheckpointCorruptError, ValueError,
                    faults.FaultInjected) as e:
                _swap_counter(name, "failed")
                raise SwapError(
                    f"checkpoint {rec.get('file')!r} cannot serve model "
                    f"{name!r}: {e}") from e
            old_solo = (model.params_tree, model.state_tree,
                        int(model.iteration), int(model.epoch))
            old_fused = (fused.params_tree, fused.state_tree)
            buckets = list(self.engine.warmed_buckets) or [1]
            golden = entry.golden_batch
            off, width = self.col_slices[name]
            with tracing.span("serve/swap_pause", cat="serve",
                              model=name), self.engine.paused():
                old_cols = None
                if golden is not None:
                    try:
                        old_cols = _golden_forward(
                            fused, golden)[..., off:off + width]
                    except Exception:
                        old_cols = None  # degrade to finiteness check
                model.params_tree = new_params
                model.state_tree = new_state
                model.iteration = int(meta.get("iteration", old_solo[2]))
                model.epoch = int(meta.get("epoch", old_solo[3]))
                try:
                    # Rebuild the fused trees from every member's
                    # CURRENT solo trees — pure concat, the fused
                    # executables keep their shapes.
                    fused.params_tree, fused.state_tree = \
                        fused_trees_from_members(self.fusion_groups,
                                                 self.named_members())
                    for b in buckets:
                        faults.fire("swap.warm")
                        fused.warmup(b, time_steps=time_steps)
                    if golden is not None:
                        new_cols = _golden_forward(
                            fused, golden)[..., off:off + width]
                        if not np.isfinite(new_cols).all():
                            raise _CanaryRejected(
                                "non-finite member columns on the "
                                "golden batch")
                        drift_cap = entry.canary_max_drift
                        if (drift_cap is not None and old_cols is not None
                                and np.isfinite(old_cols).all()):
                            drift = float(np.max(np.abs(
                                new_cols - old_cols))) \
                                if new_cols.size else 0.0
                            if drift > drift_cap:
                                raise _CanaryRejected(
                                    f"member-column drift {drift:.6g} "
                                    "exceeds canary_max_drift "
                                    f"{drift_cap}")
                except Exception as e:
                    (model.params_tree, model.state_tree,
                     model.iteration, model.epoch) = old_solo
                    fused.params_tree, fused.state_tree = old_fused
                    canary = isinstance(e, _CanaryRejected)
                    _swap_counter(
                        name, "canary_rejected" if canary else "failed")
                    what = ("canary gate rejected"
                            if canary else "warm forward failed on")
                    raise SwapError(
                        f"{what} {rec.get('file')!r}; rolled back to "
                        f"previous params: {e}") from e
        entry.version = dict(rec)
        entry.swaps += 1
        _swap_counter(name, "ok")
        return {"swapped": True, "model": name, "file": rec.get("file"),
                "iteration": rec.get("iteration", 0)}

    # --------------------------------------------------------------- eject
    def eject(self, name: str) -> ModelEntry:
        """Return one member to independent per-model dispatch and
        rebuild the group around the remaining members (dissolving it
        entirely below two). The ejected member keeps its breaker,
        checkpoints, canary state, and pool name; it gets a fresh
        engine warmed to the group's bucket set. Queued requests on the
        old shared engine are served by its shutdown drain."""
        if name not in self._entries:
            raise KeyError(f"no member {name!r} in fused group "
                           f"{self.name!r}")
        pool = self.pool
        old_engine = self.engine
        warm_top = max(old_engine.warmed_buckets) \
            if old_engine.warmed_buckets else None

        def _independent(entry: ModelEntry) -> None:
            entry.group = None
            entry.transform = None
            entry.engine = ParallelInference(
                entry.model, check_finite=True, **self._engine_kw)
            if warm_top:
                entry.engine.warmup(max_bucket=warm_top)
            pool._wire_hooks(entry)
            pool._sched_register(entry)

        ejected = self._entries.pop(name)
        self.members.remove(name)
        self._models.pop(name)
        _independent(ejected)
        _fused_fallback_counter("ejected")
        if len(self.members) >= 2:
            # Rebuild the fused substrate around the survivors: new
            # merged net, new engine (the old executables baked the
            # departed member's columns in).
            from ..nn.graph import fusion
            self.fused_net, self.fusion_groups, self.col_slices = \
                fusion.build_fused_serving_net(self.named_members())
            self.engine = ParallelInference(self.fused_net,
                                            check_finite=False,
                                            **self._engine_kw)
            if warm_top:
                self.engine.warmup(max_bucket=warm_top)
            for nm in self.members:
                e = self._entries[nm]
                e.engine = self.engine
                e.transform = self._member_transform(nm, e.breaker)
                pool._sched_register(e)
            self._wire_group_hooks()
        else:
            # One member left: a fused group of one is just overhead.
            for nm in list(self.members):
                e = self._entries.pop(nm)
                self.members.remove(nm)
                self._models.pop(nm)
                _independent(e)
                _fused_fallback_counter("dissolved")
            if pool.scheduler is not None:
                pool.scheduler.unregister(self.name)
        old_engine.shutdown()
        return ejected

    def describe(self) -> Dict[str, Any]:
        return {
            "group": self.name,
            "members": list(self.members),
            "col_slices": {nm: list(self.col_slices[nm])
                           for nm in self.members},
            "tier": self.tier,
            "weight": self.weight,
            "total_forwards": self.engine.total_forwards,
            "queue_depth": self.engine.queue_depth(),
            "fused_nodes": [g.fused_name for g in self.fusion_groups],
        }
