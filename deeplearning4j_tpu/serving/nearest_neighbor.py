"""Nearest-neighbor serving: facade + REST server.

Reference parity: deeplearning4j-nearestneighbor-server's
NearestNeighborsServer.java (Play REST over a VPTree'd corpus; POST /knn
with {ndarray, k} → base64-NDArray JSON DTOs, nearestneighbor/model/) and
NearestNeighbor.java (the per-request search).

TPU-native redesign: queries batch into ONE jitted brute-force top-k on
the device (clustering/vptree.knn_brute_force — the MXU-shaped algorithm;
VPTree remains available for host-side serving). Play is replaced by
stdlib http.server with plain-JSON DTOs (float lists, not base64 java
NDArrays)."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..clustering.vptree import VPTree, knn_brute_force
from ..utils.http_server import JsonHttpServer


class NearestNeighbor:
    """One-shot k-NN over a corpus (reference NearestNeighbor.java)."""

    def __init__(self, points, metric: str = "euclidean",
                 use_device: bool = True):
        self.points = np.asarray(points, np.float32)
        self.metric = metric
        self.use_device = use_device
        self._tree: Optional[VPTree] = None
        if not use_device:
            self._tree = VPTree(self.points, metric=metric)

    def search(self, query, k: int):
        """→ (indices [Q, k] or [k], distances) — device top-k by default,
        VPTree on host otherwise."""
        q = np.asarray(query, np.float32)
        single = q.ndim == 1
        if self.use_device:
            idx, dist = knn_brute_force(self.points, q, k, self.metric)
            return (idx[0], dist[0]) if single else (idx, dist)
        if single:
            return self._tree.search(q, k)
        pairs = [self._tree.search(row, k) for row in q]
        return (np.stack([p[0] for p in pairs]),
                np.stack([p[1] for p in pairs]))


class NearestNeighborsServer(JsonHttpServer):
    """REST k-NN server (reference NearestNeighborsServer.java).

    Endpoints:
      POST /knn    {"point": [...] | [[...]], "k": n} →
                   {"results": [{"index": i, "distance": d}, ...]} (or a
                   list of such result lists for batched queries)
      GET  /health → {"status": "ok", "corpus": N, "dim": D}
    """

    def __init__(self, points, port: int = 0, metric: str = "euclidean",
                 use_device: bool = True, pool_size: int = 8):
        super().__init__(get_routes={"/health": self._health},
                         post_routes={"/knn": self._knn}, port=port,
                         pool_size=pool_size, expose_metrics=True)
        self.nn = NearestNeighbor(points, metric=metric,
                                  use_device=use_device)

    def _health(self, _):
        return 200, {"status": "ok",
                     "corpus": int(self.nn.points.shape[0]),
                     "dim": int(self.nn.points.shape[1])}

    def _knn(self, req: dict):
        point = np.asarray(req["point"], np.float32)
        k = int(req.get("k", 5))
        idx, dist = self.nn.search(point, k)
        if point.ndim == 1:
            results = [{"index": int(i), "distance": float(d)}
                       for i, d in zip(idx, dist)]
        else:
            results = [[{"index": int(i), "distance": float(d)}
                        for i, d in zip(row_i, row_d)]
                       for row_i, row_d in zip(idx, dist)]
        return 200, {"results": results}
