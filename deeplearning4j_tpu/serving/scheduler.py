"""Priority-tier weighted fair queuing across served models sharing one
device (docs/serving.md §multi-model).

The gateway's engines each own a collector thread, and every coalesced
forward previously raced for the device unarbitrated: one chatty batch
model could head-of-line-block a latency-critical one. The
:class:`DeviceScheduler` is the arbiter those collectors now pass
through: before a forward dispatches it must hold THE dispatch slot
(one per scheduler — the shared device budget), and when several
collectors are waiting the slot goes to

1. the highest **priority tier** present (``critical`` > ``standard`` >
   ``batch``), then
2. within a tier, the largest **deficit** (weighted deficit round-robin:
   every time an entry is passed over while waiting, its deficit grows
   by ``weight x quantum``; a dispatch pays ``cost x quantum / weight``
   back — service is charged inversely to weight, so two contending
   entries split the device exactly ``weight_a : weight_b``), then
3. FIFO arrival order.

So under saturation high tiers keep bounded latency, equal-tier entries
share the device in proportion to their WFQ weights, and low tiers
degrade gracefully — they are *passed over*, never starved silently:
an entry passed over more than ``starvation_budget`` consecutive times
while it had queued work increments
``serving_starvation_total{model}`` (the pager signal). Entries that
are not waiting accrue nothing — the counter can never grow without
queued work.

Admission-side degradation: :meth:`should_shed` tells the gateway to
shed a LOW-tier request with a typed 503 (``tier_shed``) when some
strictly-higher tier already has ``shed_depth`` requests queued — the
low-tier client gets an immediate typed answer instead of a queue slot
behind traffic that will always outrank it.

Chaos seam: every slot acquisition fires the ``serve.schedule`` fault
point (utils/faults.py), so an armed plan fails scheduling decisions
deterministically — the forward that owned the slot surfaces a typed
``BatchExecutionError`` to its callers, never a hang.

Metrics (PR-2 registry): ``serving_starvation_total{model}``,
``serving_sched_dispatch_total{model,tier}``,
``serving_tier_slo_ms{tier}`` (the configured per-tier latency SLOs the
gateway's ``serving_tier_p99_ms{tier}`` gauges are judged against).

A pool without tiers never constructs a scheduler: ``ModelPool.add``
defaults leave ``engine.scheduler`` unset and every dispatch runs
exactly the pre-scheduler path.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, List, Optional

from ..optimize.metrics import registry
from ..utils import faults

__all__ = ["DeviceScheduler", "TierShedError", "TIERS", "TIER_VALUES",
           "DEFAULT_TIER_SLO_MS", "register_metrics"]

# Priority tiers, highest first. TIER_VALUES orders them (lower = more
# important) and doubles as the stable metric encoding.
TIERS = ("critical", "standard", "batch")
TIER_VALUES = {"critical": 0, "standard": 1, "batch": 2}

# Default per-tier p99 SLOs in ms (docs/serving.md table) — exported as
# serving_tier_slo_ms{tier} so dashboards compare the observed
# serving_tier_p99_ms{tier} against the budget without config access.
DEFAULT_TIER_SLO_MS = {"critical": 50.0, "standard": 250.0,
                       "batch": 2000.0}

# Deficits are bounded so an entry idle-waiting behind a pathological
# storm cannot bank unbounded credit and then monopolize the device.
_DEFICIT_CAP = 1e6


class TierShedError(RuntimeError):
    """Typed tier shed: a lower-tier request was rejected at admission
    because a higher tier's backlog already saturates the shared device
    budget. Maps to HTTP 503 ``tier_shed`` — the graceful-degradation
    contract (shed fast, never head-of-line-block)."""


def register_metrics() -> None:
    """Pre-register the scheduler families (bench --once pattern) and
    the per-tier SLO gauges at their defaults."""
    reg = registry()
    reg.counter("serving_starvation_total",
                "Times an entry with queued work was passed over beyond "
                "its starvation budget")
    reg.counter("serving_sched_dispatch_total",
                "Forwards dispatched through the device scheduler")
    g = reg.gauge("serving_tier_slo_ms",
                  "Configured p99 latency SLO per priority tier")
    for tier, slo in DEFAULT_TIER_SLO_MS.items():
        g.labels(tier=tier).set(slo)


class _SchedEntry:
    __slots__ = ("name", "tier", "tier_value", "weight", "deficit",
                 "passed_over", "depth_fn", "dispatches", "starvations",
                 "last_passovers")

    def __init__(self, name: str, tier: str, weight: float,
                 depth_fn: Optional[Callable[[], int]]):
        self.name = name
        self.tier = tier
        self.tier_value = TIER_VALUES[tier]
        self.weight = float(weight)
        self.deficit = 0.0
        self.passed_over = 0     # consecutive pass-overs while waiting
        self.depth_fn = depth_fn  # queued-request gauge for should_shed
        self.dispatches = 0
        self.starvations = 0
        # pass-over run length of the most recent GRANT (snapshotted
        # before the grant resets passed_over): the flight recorder's
        # "how many times was this batch's slot passed over" context
        self.last_passovers = 0


class _Waiter:
    __slots__ = ("name", "seq", "granted")

    def __init__(self, name: str, seq: int):
        self.name = name
        self.seq = seq
        self.granted = False


class DeviceScheduler:
    """Weighted deficit-round-robin arbiter for one shared device.

    ``quantum`` is the deficit an entry of weight 1.0 accrues per
    pass-over; a dispatch is charged ``cost x quantum / weight``, so
    two entries contending within a tier split dispatches in exactly
    their weight ratio.
    ``starvation_budget`` is how many consecutive pass-overs a waiting
    entry absorbs before ``serving_starvation_total{model}`` fires.
    ``shed_depth`` is the higher-tier queue depth past which lower-tier
    admissions shed (:meth:`should_shed`). ``tier_slo_ms`` overrides
    the exported per-tier SLO gauges."""

    def __init__(self, *, quantum: float = 1.0, starvation_budget: int = 3,
                 shed_depth: int = 8,
                 tier_slo_ms: Optional[Dict[str, float]] = None):
        self.quantum = float(quantum)
        self.starvation_budget = int(starvation_budget)
        self.shed_depth = int(shed_depth)
        self.tier_slo_ms = dict(DEFAULT_TIER_SLO_MS)
        if tier_slo_ms:
            self.tier_slo_ms.update(
                {t: float(v) for t, v in tier_slo_ms.items()})
        self._cv = threading.Condition()
        self._entries: Dict[str, _SchedEntry] = {}
        self._waiters: List[_Waiter] = []
        self._busy = False
        self._seq = 0
        reg = registry()
        self._starv_c = reg.counter(
            "serving_starvation_total",
            "Times an entry with queued work was passed over beyond "
            "its starvation budget")
        self._disp_c = reg.counter(
            "serving_sched_dispatch_total",
            "Forwards dispatched through the device scheduler")
        slo_g = reg.gauge("serving_tier_slo_ms",
                          "Configured p99 latency SLO per priority tier")
        for tier, slo in self.tier_slo_ms.items():
            slo_g.labels(tier=tier).set(slo)

    # ---------------------------------------------------------- registry
    def register(self, name: str, *, tier: str = "standard",
                 weight: float = 1.0,
                 depth_fn: Optional[Callable[[], int]] = None) -> None:
        """Register (or re-register: the reconfigure path) one served
        entry. ``depth_fn`` samples that entry's queued-request count
        for the tier-shed rule — never called on the dispatch path."""
        if tier not in TIER_VALUES:
            raise ValueError(f"unknown tier {tier!r}; one of {TIERS}")
        if weight <= 0:
            raise ValueError("weight must be > 0")
        with self._cv:
            old = self._entries.get(name)
            e = _SchedEntry(name, tier, weight, depth_fn)
            if old is not None:  # keep accounting across reconfigure
                e.deficit = old.deficit
                e.dispatches = old.dispatches
                e.starvations = old.starvations
            self._entries[name] = e

    def unregister(self, name: str) -> None:
        with self._cv:
            self._entries.pop(name, None)

    # ------------------------------------------------------- reconfigure
    def reconfigure(self, *, quantum: Optional[float] = None,
                    shed_depth: Optional[int] = None,
                    starvation_budget: Optional[int] = None,
                    tier_slo_ms: Optional[Dict[str, float]] = None
                    ) -> Dict[str, object]:
        """Live scheduler-level reconfiguration (the gateway's
        POST /config scheduler knobs and the AutoTuner's actuator).
        Validates BEFORE mutating — an invalid call changes nothing —
        and re-exports the serving_tier_slo_ms gauges on SLO changes.
        Raises ValueError on invalid values (unknown tier, non-positive
        quantum/budget/depth)."""
        if quantum is not None and float(quantum) <= 0:
            raise ValueError("quantum must be > 0")
        if shed_depth is not None and int(shed_depth) < 1:
            raise ValueError("shed_depth must be >= 1")
        if starvation_budget is not None and int(starvation_budget) < 1:
            raise ValueError("starvation_budget must be >= 1")
        slo_update: Dict[str, float] = {}
        if tier_slo_ms:
            for t, v in dict(tier_slo_ms).items():
                if t not in TIER_VALUES:
                    raise ValueError(
                        f"unknown tier {t!r} in tier_slo_ms; one of {TIERS}")
                if float(v) <= 0:
                    raise ValueError(f"tier_slo_ms[{t!r}] must be > 0")
                slo_update[t] = float(v)
        with self._cv:
            if quantum is not None:
                self.quantum = float(quantum)
            if shed_depth is not None:
                self.shed_depth = int(shed_depth)
            if starvation_budget is not None:
                self.starvation_budget = int(starvation_budget)
            if slo_update:
                self.tier_slo_ms.update(slo_update)
        if slo_update:
            slo_g = registry().gauge(
                "serving_tier_slo_ms",
                "Configured p99 latency SLO per priority tier")
            for t, v in slo_update.items():
                slo_g.labels(tier=t).set(v)
        return self.config()

    def config(self) -> Dict[str, object]:
        """The scheduler-level knob values (the reconfigure surface's
        current state; per-entry state lives in describe())."""
        with self._cv:
            return {"quantum": self.quantum,
                    "shed_depth": self.shed_depth,
                    "starvation_budget": self.starvation_budget,
                    "tier_slo_ms": dict(self.tier_slo_ms)}

    def names(self) -> List[str]:
        with self._cv:
            return list(self._entries)

    # ---------------------------------------------------------- dispatch
    @contextlib.contextmanager
    def slot(self, name: str, cost: float = 1.0):
        """Hold the device dispatch slot for one coalesced forward.
        Blocks until this entry wins arbitration; releasing re-arbitrates
        among the remaining waiters. Unregistered names are admitted
        FIFO at standard tier (they still serialize on the device)."""
        faults.fire("serve.schedule")
        with self._cv:
            self._seq += 1
            w = _Waiter(name, self._seq)
            self._waiters.append(w)
            self._maybe_grant_locked()
            while not w.granted:
                self._cv.wait(timeout=0.1)
        try:
            yield self
        finally:
            with self._cv:
                self._busy = False
                e = self._entries.get(name)
                if e is not None:
                    e.deficit = max(
                        -_DEFICIT_CAP,
                        e.deficit - float(cost) * self.quantum / e.weight)
                self._maybe_grant_locked()
                self._cv.notify_all()

    def _maybe_grant_locked(self) -> None:
        """Grant the slot to the best waiter (callers hold self._cv)."""
        if self._busy or not self._waiters:
            return
        best = min(self._waiters, key=self._waiter_key)
        self._waiters.remove(best)
        self._account_pick_locked(best.name)
        best.granted = True
        self._busy = True
        self._cv.notify_all()

    def _waiter_key(self, w: _Waiter):
        e = self._entries.get(w.name)
        if e is None:  # unregistered: standard tier, zero deficit
            return (TIER_VALUES["standard"], 0.0, w.seq)
        return (e.tier_value, -e.deficit, w.seq)

    def _account_pick_locked(self, picked: str) -> None:
        """DRR bookkeeping for one grant: the pick resets its pass-over
        run; every OTHER still-waiting entry earns weight x quantum of
        deficit and one pass-over (starvation fires past the budget)."""
        e = self._entries.get(picked)
        if e is not None:
            e.last_passovers = e.passed_over
            e.passed_over = 0
            e.dispatches += 1
            self._disp_c.labels(model=picked, tier=e.tier).inc()
        else:
            self._disp_c.labels(model=picked, tier="standard").inc()
        seen = set()
        for w in self._waiters:
            if w.name in seen:
                continue
            seen.add(w.name)
            o = self._entries.get(w.name)
            if o is None:
                continue
            o.deficit = min(_DEFICIT_CAP,
                            o.deficit + o.weight * self.quantum)
            o.passed_over += 1
            if o.passed_over > self.starvation_budget:
                o.passed_over = 0
                o.starvations += 1
                self._starv_c.labels(model=o.name).inc()

    def _select(self, waiting: List[str]) -> str:
        """Deterministic one-shot arbitration over `waiting` entry names
        (unit-test surface for the pick rule — same tier/deficit/
        starvation accounting as the live slot path, no threads)."""
        with self._cv:
            ws = []
            for n in waiting:
                self._seq += 1
                ws.append(_Waiter(n, self._seq))
            best = min(ws, key=self._waiter_key)
            self._waiters = [w for w in ws if w is not best]
            self._account_pick_locked(best.name)
            e = self._entries.get(best.name)
            if e is not None:
                e.deficit = max(-_DEFICIT_CAP,
                                e.deficit - self.quantum / e.weight)
            self._waiters = []
            return best.name

    def last_passovers(self, name: Optional[str]) -> int:
        """Pass-over run length of `name`'s most recent slot grant (0
        for unknown/unregistered names) — read by the engine right after
        it wins the slot, as exemplar context."""
        with self._cv:
            e = self._entries.get(name)
            return e.last_passovers if e is not None else 0

    # --------------------------------------------------------- admission
    def should_shed(self, name: str) -> Optional[str]:
        """Admission check for one request routed at `name`: returns
        a shed reason (``"tier_shed"``) when a strictly-higher tier
        already has >= ``shed_depth`` requests queued, else None.
        Sampling queue depths happens here (admission), never on the
        dispatch path."""
        with self._cv:
            e = self._entries.get(name)
            if e is None:
                return None
            others = [o for o in self._entries.values()
                      if o.tier_value < e.tier_value
                      and o.depth_fn is not None]
        for o in others:
            try:
                # deliberate unlocked read of a config int: depth
                # sampling happens outside _cv by design (see above)
                if int(o.depth_fn()) >= self.shed_depth:  # jaxlint: atomic
                    return "tier_shed"
            except Exception:
                continue  # a broken gauge must never shed traffic
        return None

    # ------------------------------------------------------------- intro
    def describe(self) -> Dict[str, dict]:
        with self._cv:
            return {e.name: {"tier": e.tier, "weight": e.weight,
                             "deficit": round(e.deficit, 3),
                             "dispatches": e.dispatches,
                             "starvations": e.starvations}
                    for e in self._entries.values()}
