"""Streaming: NDArray pub/sub + model-serving routes (reference
dl4j-streaming: Kafka NDArrayPublisher/NDArrayConsumer + Camel
DL4jServeRouteBuilder, SURVEY.md §2.4)."""
from .ndarray_stream import (Broker, HttpBrokerClient, InProcessBroker,
                             NDArrayConsumer, NDArrayPublisher,
                             NDArrayStreamServer, NDArrayTopic, ServeRoute,
                             get_default_broker, set_default_broker)
