"""Streaming: NDArray pub/sub + model-serving routes (reference
dl4j-streaming: Kafka NDArrayPublisher/NDArrayConsumer + Camel
DL4jServeRouteBuilder, SURVEY.md §2.4)."""
from .ndarray_stream import (NDArrayConsumer, NDArrayPublisher,
                             NDArrayStreamServer, NDArrayTopic, ServeRoute)
