"""NDArray pub/sub streaming + serve routes.

Reference parity: dl4j-streaming's Kafka pipeline —
streaming/kafka/{NDArrayPublisher,NDArrayConsumer,NDArrayKafkaClient}
(byte-serialized NDArrays through topics) and
streaming/routes/DL4jServeRouteBuilder.java (consume a topic, run the
model, publish predictions).

TPU-native redesign: Kafka/Camel are infrastructure choices, not
behavior; the behavioral surface (named topics, non-blocking publish,
blocking consume, a serve route wiring a model between topics) is kept
over an in-process broker with an optional stdlib-HTTP transport for
cross-process use. Arrays ride as JSON (shape + flat values) — the
base64-NDArray DTO role."""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

import numpy as np

from ..utils.http_server import JsonHttpServer


def _encode(arr: np.ndarray) -> dict:
    arr = np.asarray(arr, np.float32)
    return {"shape": list(arr.shape), "data": arr.reshape(-1).tolist()}


def _decode(obj: dict) -> np.ndarray:
    return np.asarray(obj["data"], np.float32).reshape(obj["shape"])


class NDArrayTopic:
    """One named topic: fan-out to every subscriber queue (the Kafka
    topic/consumer-group role, single-partition semantics)."""

    def __init__(self, name: str, queue_size: int = 256):
        self.name = name
        self._queue_size = queue_size
        self._subscribers: List["queue.Queue"] = []
        self._lock = threading.Lock()

    def subscribe(self) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue(maxsize=self._queue_size)
        with self._lock:
            self._subscribers.append(q)
        return q

    def unsubscribe(self, q: "queue.Queue") -> None:
        with self._lock:
            if q in self._subscribers:
                self._subscribers.remove(q)

    def publish(self, arr: np.ndarray) -> None:
        arr = np.asarray(arr, np.float32)
        with self._lock:
            subs = list(self._subscribers)
        for q in subs:
            try:
                q.put_nowait(arr)
            except queue.Full:
                pass  # slow consumer drops, publisher never blocks


class Broker:
    """The pluggable transport seam (round 5; the reference swaps
    brokers at the Camel/Kafka component level —
    kafka/NDArrayKafkaClient.java:10). An implementation maps topic
    names to objects with the NDArrayTopic surface: `publish(arr)`,
    `subscribe() -> queue.Queue`, `unsubscribe(q)`. Publishers,
    consumers, and serve routes are broker-agnostic; an external-system
    adapter (Kafka, Pub/Sub, ...) implements `topic` with a consumer
    thread feeding the returned queue. Ships: InProcessBroker (default)
    and HttpBrokerClient (a remote NDArrayStreamServer)."""

    def topic(self, name: str):
        raise NotImplementedError


class InProcessBroker(Broker):
    """Topics live in this process (the single-JVM embedded-broker
    role); NDArrayStreamServer exposes the SAME broker over HTTP for
    cross-process use."""

    def __init__(self):
        self._topics: Dict[str, NDArrayTopic] = {}
        self._lock = threading.Lock()

    def topic(self, name: str) -> NDArrayTopic:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                t = self._topics[name] = NDArrayTopic(name)
            return t


_Broker = InProcessBroker  # back-compat alias
_default_broker: Broker = InProcessBroker()


def get_default_broker() -> Broker:
    return _default_broker


def set_default_broker(broker: Broker) -> Broker:
    """Swap the process-wide default transport (e.g. to an external
    adapter); returns the previous broker so callers can restore it."""
    global _default_broker
    prev = _default_broker
    _default_broker = broker
    return prev


class _HttpTopic:
    """Client-side topic over a remote NDArrayStreamServer: publish
    POSTs; subscribe long-polls /consume on a daemon thread into a
    local queue (the consumer-thread pattern an external-broker adapter
    uses too)."""

    def __init__(self, base_url: str, name: str, client_id: str,
                 poll_timeout: float):
        self._url = base_url.rstrip("/")
        self.name = name
        self._client_id = client_id
        self._poll_timeout = poll_timeout
        self._pollers: List[tuple] = []  # (queue, stop_event, thread)
        self._n = 0
        self._lock = threading.Lock()

    def _post(self, route: str, payload: dict) -> dict:
        import json
        import urllib.request
        req = urllib.request.Request(
            self._url + route, json.dumps(payload).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(
                req, timeout=self._poll_timeout + 10) as resp:
            return json.loads(resp.read())

    def publish(self, arr) -> None:
        self._post("/publish", {"topic": self.name,
                                **_encode(np.asarray(arr, np.float32))})

    def subscribe(self) -> "queue.Queue":
        q: "queue.Queue" = queue.Queue(maxsize=256)
        stop = threading.Event()
        with self._lock:  # unique client id under concurrent subscribes
            self._n += 1
            client = f"{self._client_id}-{self._n}"
        # Register the server-side subscription SYNCHRONOUSLY (a
        # zero-wait consume) so subscribe-then-publish cannot lose the
        # first message to the poller's startup window — the
        # InProcessBroker ordering guarantee holds over HTTP too. The
        # registration consume can itself return a message (a publish
        # raced between a previous subscriber's registration and now, or
        # the server pre-seeded the queue) — dropping that payload would
        # silently lose the first message, so deliver it here.
        out = self._post("/consume", {"topic": self.name, "client": client,
                                      "timeout": 0.0})
        if not out.get("empty", True):
            q.put_nowait(_decode(out))

        warned = [False]

        def run():
            try:
                while not stop.is_set():
                    try:
                        out = self._post("/consume", {
                            "topic": self.name, "client": client,
                            "timeout": self._poll_timeout})
                    except Exception as e:
                        if not warned[0]:  # visible, once (dead server)
                            import logging
                            logging.getLogger(__name__).warning(
                                "HTTP broker poll of %s/%s failing (%s); "
                                "retrying", self._url, self.name, e)
                            warned[0] = True
                        if stop.wait(0.2):
                            return
                        continue
                    if not out.get("empty", True):
                        try:
                            q.put_nowait(_decode(out))
                        except queue.Full:
                            pass  # slow consumer drops, like NDArrayTopic
            finally:
                # the POLLER posts the goodbye, strictly AFTER its last
                # /consume — an unsubscribe posted from another thread
                # could be overtaken by an in-flight consume that
                # re-registers the queue server-side
                try:
                    self._post("/unsubscribe", {"topic": self.name,
                                                "client": client})
                except Exception:
                    pass  # server gone: its consumer map died with it

        t = threading.Thread(target=run, daemon=True)
        t.start()
        with self._lock:
            self._pollers.append((q, stop, t))
        return q

    def unsubscribe(self, q: "queue.Queue") -> None:
        """Stops the poller; the poller itself then releases the
        server-side queue (see run()'s finally) so publishes stop
        fanning into a dead subscription."""
        with self._lock:
            ents = [e for e in self._pollers if e[0] is q]
            for ent in ents:
                self._pollers.remove(ent)
        for ent in ents:
            ent[1].set()


class HttpBrokerClient(Broker):
    """Broker over a remote NDArrayStreamServer — the cross-process
    transport as a first-class Broker implementation (so a serve route
    can consume from one machine's topics and publish to another's)."""

    def __init__(self, base_url: str, client_id: Optional[str] = None,
                 poll_timeout: float = 2.0):
        import uuid
        self._base_url = base_url
        self._client_id = client_id or uuid.uuid4().hex[:8]
        self._poll_timeout = float(poll_timeout)
        self._topics: Dict[str, _HttpTopic] = {}
        self._lock = threading.Lock()

    def topic(self, name: str) -> _HttpTopic:
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                t = self._topics[name] = _HttpTopic(
                    self._base_url, name, self._client_id,
                    self._poll_timeout)
            return t


class NDArrayPublisher:
    """Reference kafka/NDArrayPublisher: publish(arr) onto a topic."""

    def __init__(self, topic: str, broker: Optional[Broker] = None):
        self._topic = (broker or _default_broker).topic(topic)

    def publish(self, arr) -> None:
        self._topic.publish(np.asarray(arr, np.float32))


class NDArrayConsumer:
    """Reference kafka/NDArrayConsumer: blocking getArrays()."""

    def __init__(self, topic: str, broker: Optional[Broker] = None):
        self._queue = (broker or _default_broker).topic(topic).subscribe()

    def get(self, timeout: Optional[float] = None) -> np.ndarray:
        return self._queue.get(timeout=timeout)

    def poll(self) -> Optional[np.ndarray]:
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None


class ServeRoute:
    """Reference streaming/routes/DL4jServeRouteBuilder: consume arrays
    from `input_topic`, run the model, publish predictions to
    `output_topic` — on a background thread until stop()."""

    def __init__(self, model, input_topic: str, output_topic: str,
                 broker: Optional[Broker] = None):
        self.model = model
        self._consumer = NDArrayConsumer(input_topic, broker)
        self._publisher = NDArrayPublisher(output_topic, broker)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.served = 0
        self.errors = 0

    def start(self) -> "ServeRoute":
        import logging
        log = logging.getLogger(__name__)

        def run():
            while not self._stop.is_set():
                try:
                    arr = self._consumer.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    self._publisher.publish(self.model.output(arr))
                    self.served += 1
                except Exception:  # one bad input must not kill the route
                    self.errors += 1
                    log.exception("ServeRoute: dropping bad input of shape "
                                  "%s", np.shape(arr))
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class NDArrayStreamServer(JsonHttpServer):
    """Cross-process transport: POST /publish {topic, shape, data};
    POST /consume {topic, timeout} (long-poll; registers the caller's
    subscription on first consume)."""

    def __init__(self, port: int = 0, broker: Optional[Broker] = None,
                 subscriber_idle_ttl: float = 300.0):
        super().__init__(get_routes={"/health": self._health},
                         post_routes={"/publish": self._publish,
                                      "/consume": self._consume,
                                      "/unsubscribe": self._unsubscribe},
                         port=port)
        # Default to the SHARED broker so in-process publishers/consumers
        # and remote HTTP clients see the same topics.
        self._broker = broker or _default_broker
        # (topic, client) → (queue, last_seen); idle entries evict so
        # departed clients don't leak permanently-subscribed queues.
        self._consumers: Dict[tuple, tuple] = {}
        self._ttl = float(subscriber_idle_ttl)
        self._lock = threading.Lock()

    def _health(self, _):
        return 200, {"status": "ok"}

    def _publish(self, req: dict):
        self._broker.topic(req["topic"]).publish(_decode(req))
        return 200, {"ok": True}

    def _unsubscribe(self, req: dict):
        """Prompt release of a remote client's subscription (the idle
        TTL sweep is only the departed-without-goodbye fallback)."""
        key = (req["topic"], str(req.get("client", "default")))
        with self._lock:
            ent = self._consumers.pop(key, None)
        if ent is not None:
            self._broker.topic(key[0]).unsubscribe(ent[0])
        return 200, {"ok": ent is not None}

    def _consume(self, req: dict):
        import time
        # Subscriptions key on (topic, client) so DISTINCT remote clients
        # each get full fan-out, matching in-process NDArrayConsumer
        # semantics; pass a stable "client" id per consumer process.
        key = (req["topic"], str(req.get("client", "default")))
        now = time.time()
        with self._lock:
            # evict subscriptions idle past the TTL (departed clients)
            for k in [k for k, (_, seen) in self._consumers.items()
                      if now - seen > self._ttl]:
                q_dead, _ = self._consumers.pop(k)
                self._broker.topic(k[0]).unsubscribe(q_dead)
            ent = self._consumers.get(key)
            if ent is None:
                q = self._broker.topic(key[0]).subscribe()
            else:
                q = ent[0]
            self._consumers[key] = (q, now)
        # Clamp the wait below the TTL so an ACTIVE long-poll can never be
        # evicted mid-wait by another client's sweep; refresh last_seen
        # when the wait ends.
        wait = min(float(req.get("timeout", 5.0)), self._ttl * 0.5)
        try:
            arr = q.get(timeout=wait)
        except queue.Empty:
            arr = None
        with self._lock:
            if key in self._consumers:
                self._consumers[key] = (self._consumers[key][0],
                                        time.time())
        if arr is None:
            return 200, {"empty": True}
        return 200, {"empty": False, **_encode(arr)}
