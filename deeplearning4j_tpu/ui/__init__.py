"""Observability: StatsListener → StatsStorage → static report + live UIServer
(reference deeplearning4j-ui-parent, SURVEY.md §2.6/§5.5)."""
from .components import (ChartHistogram, ChartHorizontalBar, ChartLine,
                         ChartScatter, ComponentDiv, ComponentTable,
                         ComponentText, component_from_json,
                         component_to_json, render_component)
from .convolutional import ConvolutionalIterationListener
from .remote import RemoteStatsStorageRouter, StatsReceiverServer
from .report import export_json, render_html, render_html_report
from .server import UIServer
from .stats import (FileStatsStorage, InMemoryStatsStorage, StatsListener,
                    StatsStorage, StatsUpdateConfiguration)
