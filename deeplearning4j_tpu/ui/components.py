"""ui-components: declarative, JSON-serializable report components.

Reference parity: deeplearning4j-ui-components — an object model of
texts/tables/charts serialized to JSON and rendered by a small JS
runtime (deeplearning4j-ui-parent/deeplearning4j-ui-components/src/main/
java/org/deeplearning4j/ui/api/Component.java and components/chart/
ChartLine, ChartScatter, ChartHistogram, ChartHorizontalBar,
components/table/ComponentTable, components/text/ComponentText,
components/component/ComponentDiv). Users compose components, ship them
as JSON, and any surface renders them.

TPU-native transposition: components are serde-registered dataclasses
(the same registry that round-trips layer configs, `utils/serde.py`), so
`to_json`/`from_json` IS the wire format; rendering is server-side SVG/
HTML (`render_component`, standalone — no JS runtime), matching how the
rest of this framework's UI modules render."""
from __future__ import annotations

import html as _html
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from ..utils import serde

__all__ = [
    "Component", "ComponentText", "ComponentTable", "ComponentDiv",
    "ChartLine", "ChartScatter", "ChartHistogram", "ChartHorizontalBar",
    "render_component", "component_to_json", "component_from_json",
]


@dataclass
class Component:
    """Base marker (reference ui/api/Component.java)."""


@serde.register
@dataclass
class ComponentText(Component):
    """reference components/text/ComponentText.java"""
    text: str = ""
    font_size: int = 12
    color: str = "#000000"

    def html(self) -> str:
        return (f'<p style="font-size:{int(self.font_size)}px;'
                f'color:{_html.escape(self.color)}">'
                f'{_html.escape(self.text)}</p>')


@serde.register
@dataclass
class ComponentTable(Component):
    """reference components/table/ComponentTable.java"""
    header: Sequence[str] = ()
    content: Sequence[Sequence[str]] = ()
    border: int = 1

    def html(self) -> str:
        head = "".join(f"<th>{_html.escape(str(h))}</th>"
                       for h in self.header)
        rows = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>"
                             for c in row) + "</tr>"
            for row in self.content)
        return (f'<table border="{int(self.border)}" '
                f'style="border-collapse:collapse">'
                f"<tr>{head}</tr>{rows}</table>")


@serde.register
@dataclass
class ComponentDiv(Component):
    """Container (reference components/component/ComponentDiv.java)."""
    components: List[Component] = field(default_factory=list)
    style: str = ""

    def html(self) -> str:
        inner = "".join(c.html() for c in self.components)
        return f'<div style="{_html.escape(self.style)}">{inner}</div>'


def _axes_box(w, h, pad):
    return (f'<line x1="{pad}" y1="{h - pad}" x2="{w - pad}" '
            f'y2="{h - pad}" stroke="#333"/>'
            f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{h - pad}" '
            f'stroke="#333"/>')


_SERIES_COLORS = ("#3366cc", "#dc3912", "#ff9900", "#109618", "#990099",
                  "#0099c6")


@dataclass
class _Chart(Component):
    title: str = ""
    width: int = 480
    height: int = 300

    def _frame(self, body: str) -> str:
        t = (f'<text x="{self.width // 2}" y="14" text-anchor="middle" '
             f'font-size="13">{_html.escape(self.title)}</text>')
        return (f'<svg xmlns="http://www.w3.org/2000/svg" '
                f'viewBox="0 0 {self.width} {self.height}" '
                f'width="{self.width}" height="{self.height}">'
                f'<rect width="{self.width}" height="{self.height}" '
                f'fill="#ffffff"/>{t}{body}</svg>')


def _scale(vals, lo, hi, a, b):
    span = (hi - lo) if hi > lo else 1.0
    return [a + (v - lo) / span * (b - a) for v in vals]


@serde.register
@dataclass
class ChartLine(_Chart):
    """reference components/chart/ChartLine.java: named (x, y) series."""
    series_names: Sequence[str] = ()
    x: Sequence[Sequence[float]] = ()
    y: Sequence[Sequence[float]] = ()

    def html(self) -> str:
        pad = 28
        allx = [v for s in self.x for v in s] or [0.0]
        ally = [v for s in self.y for v in s] or [0.0]
        body = [_axes_box(self.width, self.height, pad)]
        for i, (xs, ys) in enumerate(zip(self.x, self.y)):
            px = _scale(xs, min(allx), max(allx), pad, self.width - pad)
            py = _scale(ys, min(ally), max(ally), self.height - pad, pad)
            pts = " ".join(f"{a:.1f},{b:.1f}" for a, b in zip(px, py))
            color = _SERIES_COLORS[i % len(_SERIES_COLORS)]
            body.append(f'<polyline points="{pts}" fill="none" '
                        f'stroke="{color}" stroke-width="1.5"/>')
            if i < len(self.series_names):
                body.append(
                    f'<text x="{self.width - pad}" y="{pad + 14 * i}" '
                    f'text-anchor="end" font-size="11" fill="{color}">'
                    f'{_html.escape(str(self.series_names[i]))}</text>')
        return self._frame("".join(body))


@serde.register
@dataclass
class ChartScatter(_Chart):
    """reference components/chart/ChartScatter.java"""
    series_names: Sequence[str] = ()
    x: Sequence[Sequence[float]] = ()
    y: Sequence[Sequence[float]] = ()

    def html(self) -> str:
        pad = 28
        allx = [v for s in self.x for v in s] or [0.0]
        ally = [v for s in self.y for v in s] or [0.0]
        body = [_axes_box(self.width, self.height, pad)]
        for i, (xs, ys) in enumerate(zip(self.x, self.y)):
            px = _scale(xs, min(allx), max(allx), pad, self.width - pad)
            py = _scale(ys, min(ally), max(ally), self.height - pad, pad)
            color = _SERIES_COLORS[i % len(_SERIES_COLORS)]
            body.extend(f'<circle cx="{a:.1f}" cy="{b:.1f}" r="2.5" '
                        f'fill="{color}"/>' for a, b in zip(px, py))
        return self._frame("".join(body))


@serde.register
@dataclass
class ChartHistogram(_Chart):
    """reference components/chart/ChartHistogram.java: explicit bin
    edges (lower/upper) + counts."""
    lower: Sequence[float] = ()
    upper: Sequence[float] = ()
    y: Sequence[float] = ()

    @staticmethod
    def from_values(values, bins: int = 20, **kw) -> "ChartHistogram":
        counts, edges = np.histogram(np.asarray(values, np.float64),
                                     bins=bins)
        return ChartHistogram(lower=edges[:-1].tolist(),
                              upper=edges[1:].tolist(),
                              y=counts.astype(float).tolist(), **kw)

    def html(self) -> str:
        pad = 28
        if not self.y:
            return self._frame(_axes_box(self.width, self.height, pad))
        lo, hi = min(self.lower), max(self.upper)
        ymax = max(self.y) or 1.0
        body = [_axes_box(self.width, self.height, pad)]
        for l, u, c in zip(self.lower, self.upper, self.y):
            x0 = _scale([l], lo, hi, pad, self.width - pad)[0]
            x1 = _scale([u], lo, hi, pad, self.width - pad)[0]
            hh = (self.height - 2 * pad) * (c / ymax)
            body.append(
                f'<rect x="{x0:.1f}" y="{self.height - pad - hh:.1f}" '
                f'width="{max(x1 - x0 - 1, 1):.1f}" height="{hh:.1f}" '
                f'fill="#3366cc"/>')
        return self._frame("".join(body))


@serde.register
@dataclass
class ChartHorizontalBar(_Chart):
    """reference components/chart/ChartHorizontalBar.java"""
    labels: Sequence[str] = ()
    values: Sequence[float] = ()

    def html(self) -> str:
        pad = 28
        n = max(len(self.values), 1)
        vmax = max([abs(v) for v in self.values] or [1.0]) or 1.0
        bh = (self.height - 2 * pad) / n
        body = [_axes_box(self.width, self.height, pad)]
        for i, v in enumerate(self.values):
            w = (self.width - 2 * pad - 80) * abs(v) / vmax
            y = pad + i * bh
            body.append(
                f'<rect x="{pad + 80}" y="{y + 2:.1f}" width="{w:.1f}" '
                f'height="{max(bh - 4, 2):.1f}" fill="#109618"/>')
            if i < len(self.labels):
                body.append(
                    f'<text x="{pad + 74}" y="{y + bh / 2 + 4:.1f}" '
                    f'text-anchor="end" font-size="11">'
                    f'{_html.escape(str(self.labels[i]))}</text>')
        return self._frame("".join(body))


def component_to_json(component: Component) -> str:
    """Serialize any component tree (the reference's Component JSON
    contract — `@class`-tagged, round-trippable)."""
    return serde.to_json(component)


def component_from_json(js: str) -> Component:
    return serde.from_json(js)


def render_component(component: Component) -> str:
    """Standalone HTML document for a component tree."""
    return (f"<!doctype html><html><head><meta charset='utf-8'>"
            f"<title>report</title></head><body>{component.html()}"
            f"</body></html>")
