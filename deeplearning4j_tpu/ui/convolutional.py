"""Convolutional-activations UI module: what is the CNN looking at?

Reference parity: deeplearning4j-ui's ConvolutionalIterationListener
(deeplearning4j-ui-parent/deeplearning4j-ui/src/main/java/org/
deeplearning4j/ui/weights/ConvolutionalIterationListener.java:38) renders
every conv layer's activation maps as a tiled grayscale grid each N
iterations and streams it to the play UI's `convolutional` module
(ui/play/PlayUIServer.java:15-22).

TPU-native redesign: the reference scrapes activations out of the
workspace-managed forward pass; here activations live inside a fused
jitted step, so the listener runs its OWN tiny probe forward
(`feed_forward` on a fixed probe example) at the reporting frequency —
deterministic, device-efficient (one extra forward per N iterations),
and independent of batch contents. Grids are encoded as real PNGs with
a stdlib-only encoder (zlib + struct — no image libraries in the
environment) and pushed to the live UIServer, which serves them inline
on /activations."""
from __future__ import annotations

import struct
import zlib
from typing import List, Optional, Tuple

import numpy as np

from ..optimize.listeners import IterationListener


def png_gray(img: np.ndarray) -> bytes:
    """Encode a [h, w] uint8 array as an 8-bit grayscale PNG."""
    img = np.asarray(img, np.uint8)
    h, w = img.shape
    raw = b"".join(b"\x00" + img[r].tobytes() for r in range(h))

    def chunk(tag: bytes, data: bytes) -> bytes:
        return (struct.pack(">I", len(data)) + tag + data +
                struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0)
    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr) +
            chunk(b"IDAT", zlib.compress(raw)) + chunk(b"IEND", b""))


def activation_grid(act: np.ndarray, border: int = 1,
                    max_channels: int = 64) -> np.ndarray:
    """[H, W, C] feature maps -> one tiled uint8 [rows*H', cols*W'] grid
    (per-channel min-max normalized, the reference's grayscale scaling)."""
    act = np.asarray(act, np.float32)
    if act.ndim != 3:
        raise ValueError(f"need [H, W, C] activations, got {act.shape}")
    h, w, c = act.shape
    c = min(c, max_channels)
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    gh, gw = h + border, w + border
    grid = np.zeros((rows * gh + border, cols * gw + border), np.uint8)
    for i in range(c):
        m = act[:, :, i]
        lo, hi = float(m.min()), float(m.max())
        span = (hi - lo) if hi > lo else 1.0
        tile = ((m - lo) / span * 255.0).astype(np.uint8)
        r, col = divmod(i, cols)
        grid[border + r * gh:border + r * gh + h,
             border + col * gw:border + col * gw + w] = tile
    return grid


class ConvolutionalIterationListener(IterationListener):
    """Render per-conv-layer activation grids into the live UI every
    `frequency` iterations (reference
    ConvolutionalIterationListener.java:38 role).

    `probe`: one input example ([1, H, W, C] — or [H, W, C], auto-
    batched) forwarded through the net at each report. `ui`: a UIServer
    (defaults to the running singleton at first report)."""

    def __init__(self, probe, frequency: int = 10, ui=None,
                 max_channels: int = 64):
        probe = np.asarray(probe, np.float32)
        if probe.ndim == 3:
            probe = probe[None]
        if probe.ndim != 4:
            raise ValueError(f"probe must be [1, H, W, C], got {probe.shape}")
        self.probe = probe[:1]
        self.frequency = max(1, int(frequency))
        self.max_channels = int(max_channels)
        self._ui = ui

    def _grids(self, model) -> List[Tuple[str, bytes]]:
        out = []
        if hasattr(model, "feed_forward_named"):  # ComputationGraph
            if len(model.conf.network_inputs) != 1:
                raise ValueError(
                    "ConvolutionalIterationListener supports single-input "
                    "graphs (one probe); got inputs "
                    f"{model.conf.network_inputs}")
            acts = model.feed_forward_named(self.probe)
            skip = set(model.conf.network_inputs)
            named = [(n, acts[n]) for n in model.conf.topo_order
                     if n in acts and n not in skip]
        else:  # MultiLayerNetwork: [input] + per-layer activations
            ff = model.feed_forward(self.probe)
            layers = getattr(model, "layers", [])
            named = [(f"layer{i} "
                      f"({type(layers[i]).__name__ if i < len(layers) else '?'})",
                      act) for i, act in enumerate(ff[1:])]
        for name, act in named:
            a = np.asarray(act)
            if a.ndim != 4:
                continue  # not a spatial activation
            out.append((str(name), png_gray(
                activation_grid(a[0], max_channels=self.max_channels))))
        return out

    def iteration_done(self, model, iteration):
        if iteration % self.frequency != 0:
            return
        if self._ui is None:
            from .server import UIServer
            self._ui = UIServer.get_instance()
        self._ui.attach_activations(self._grids(model), iteration)
