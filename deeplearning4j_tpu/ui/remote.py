"""Remote stats: POST training stats to a central receiver.

Reference parity: deeplearning4j-ui-remote-iterationlisteners'
RemoteUIStatsStorageRouter (workers POST SBE-encoded stats) +
deeplearning4j-play's RemoteReceiverModule (accepts them into the
attached StatsStorage) — the mechanism Spark workers use to report to one
central UI (SURVEY.md §5.5). JSON over stdlib HTTP here; the storage API
on both ends is the same StatsStorage the local pipeline uses, so a
multi-host run can point every process's StatsListener at one chief-side
receiver."""
from __future__ import annotations

import json
import queue
import threading
import urllib.request
from typing import Optional

from ..utils.http_server import JsonHttpServer
from .stats import StatsStorage


class RemoteStatsStorageRouter(StatsStorage):
    """StatsStorage facade that forwards put_update over HTTP (reference
    RemoteUIStatsStorageRouter). Posts happen on a background thread so a
    slow receiver never stalls the train loop; retries are bounded."""

    def __init__(self, url: str, queue_size: int = 256, retries: int = 3,
                 timeout: float = 5.0):
        self.url = url.rstrip("/") + "/stats"
        self.retries = int(retries)
        self.timeout = float(timeout)
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        # dropped is bumped from both the caller thread (queue full) and
        # the pump thread (retries exhausted): += is a read-modify-write,
        # so both sites go through _drop() under this lock
        self._drop_lock = threading.Lock()
        self.dropped = 0
        self._shutdown = False
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _drop(self) -> None:
        with self._drop_lock:
            self.dropped += 1

    def put_update(self, session_id: str, record: dict) -> None:
        try:
            self._queue.put_nowait({"session": session_id, **record})
        except queue.Full:
            self._drop()  # never stall training on a slow receiver

    def _pump(self):
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                body = json.dumps(item).encode()
                for attempt in range(self.retries):
                    try:
                        req = urllib.request.Request(
                            self.url, data=body,
                            headers={"Content-Type": "application/json"})
                        urllib.request.urlopen(req, timeout=self.timeout)
                        break
                    except Exception:
                        if attempt == self.retries - 1:
                            self._drop()
            finally:
                self._queue.task_done()

    def flush(self, timeout: float = 10.0):
        """Block until queued records have been POSTED (not merely
        dequeued — unfinished_tasks counts the in-flight record too)."""
        import time
        deadline = time.time() + timeout
        while self._queue.unfinished_tasks and time.time() < deadline:
            time.sleep(0.02)

    def shutdown(self):
        if not self._shutdown:
            self._shutdown = True
            self._queue.put(None)
            self._thread.join(timeout=5)

    # remote router is write-only (reference: the router interface)
    def list_session_ids(self):
        raise NotImplementedError("RemoteStatsStorageRouter is write-only; "
                                  "query the receiver's storage")

    def get_updates(self, session_id):
        raise NotImplementedError("RemoteStatsStorageRouter is write-only; "
                                  "query the receiver's storage")


class StatsReceiverServer(JsonHttpServer):
    """HTTP receiver writing into a local StatsStorage (reference
    RemoteReceiverModule): POST /stats {session, ...record}; GET /sessions
    lists what arrived."""

    def __init__(self, storage: StatsStorage, port: int = 0):
        super().__init__(get_routes={"/sessions": self._sessions},
                         post_routes={"/stats": self._stats}, port=port)
        self.storage = storage

    def _sessions(self, _):
        return 200, {"sessions": self.storage.list_session_ids()}

    def _stats(self, rec: dict):
        sid = rec.pop("session", "remote")
        self.storage.put_update(sid, rec)
        return 200, {"ok": True}
