"""Static training report from a StatsStorage.

Reference parity: the role of deeplearning4j-play's train UI module
(PlayUIServer score chart, model tab, system tab) — rendered as a
self-contained static HTML file (inline SVG, zero JS dependencies) plus
a machine-readable JSON export. A live server adds nothing on a TPU pod
where runs are batch jobs; a file artifact is greppable and archivable.
"""
from __future__ import annotations

import html
import json
from typing import Any, Dict, List

from .stats import StatsStorage


def export_json(storage: StatsStorage, session_id: str = None) -> str:
    """All updates for one (or the only) session as a JSON document."""
    sessions = storage.list_session_ids()
    if not sessions:
        raise ValueError("Storage holds no sessions")
    sid = session_id or sessions[0]
    return json.dumps({"session": sid,
                       "updates": storage.get_updates(sid)}, indent=2)


def _svg_polyline(xs: List[float], ys: List[float], width=640, height=240,
                  pad=36) -> str:
    if not xs:
        return "<svg></svg>"
    x0, x1 = min(xs), max(xs) or 1
    y0, y1 = min(ys), max(ys)
    if y1 == y0:
        y1 = y0 + 1
    sx = lambda x: pad + (x - x0) / max(x1 - x0, 1e-12) * (width - 2 * pad)
    sy = lambda y: height - pad - (y - y0) / (y1 - y0) * (height - 2 * pad)
    pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
    return (
        f'<svg viewBox="0 0 {width} {height}" width="{width}" '
        f'height="{height}" xmlns="http://www.w3.org/2000/svg">'
        f'<rect width="{width}" height="{height}" fill="#fafafa"/>'
        f'<text x="{pad}" y="16" font-size="11">score (min '
        f'{y0:.4g}, max {y1:.4g})</text>'
        f'<polyline fill="none" stroke="#2266cc" stroke-width="1.5" '
        f'points="{pts}"/></svg>')


def _svg_histogram(hist: Dict[str, Any], width=300, height=90,
                   pad=4) -> str:
    """Bar chart for one param histogram record (the reference histogram
    UI module's per-layer view)."""
    counts = hist.get("counts") or []
    if not counts:
        return "<svg></svg>"
    peak = max(counts) or 1
    n = len(counts)
    bw = (width - 2 * pad) / n
    bars = "".join(
        f'<rect x="{pad + i * bw:.1f}" '
        f'y="{height - pad - c / peak * (height - 2 * pad):.1f}" '
        f'width="{max(bw - 1, 1):.1f}" '
        f'height="{c / peak * (height - 2 * pad):.1f}" fill="#44aa66"/>'
        for i, c in enumerate(counts))
    return (f'<svg viewBox="0 0 {width} {height}" width="{width}" '
            f'height="{height}" xmlns="http://www.w3.org/2000/svg">'
            f'<rect width="{width}" height="{height}" fill="#fafafa"/>'
            f'{bars}'
            f'<text x="{pad}" y="{height - 2}" font-size="9">'
            f'{hist.get("min", 0):.3g}</text>'
            f'<text x="{width - 40}" y="{height - 2}" font-size="9">'
            f'{hist.get("max", 0):.3g}</text></svg>')


def render_html(storage: StatsStorage, session_id: str = None,
                refresh_seconds: float = None) -> str:
    """Render the training report document (the train UI module's
    overview + histogram + update views). With `refresh_seconds` the
    page self-reloads — that is the live UIServer's watch mode."""
    sessions = storage.list_session_ids()
    if not sessions:
        raise ValueError("Storage holds no sessions")
    sid = session_id or sessions[0]
    updates = [u for u in storage.get_updates(sid) if "epoch_end" not in u]
    iters = [u["iteration"] for u in updates if u.get("score") is not None]
    scores = [u["score"] for u in updates if u.get("score") is not None]
    times = [u.get("iteration_ms") for u in updates
             if u.get("iteration_ms") is not None]
    last = updates[-1] if updates else {}

    rows = []
    if times:
        import statistics
        rows.append(("mean iteration (ms)",
                     f"{statistics.fmean(times):.2f}"))
    if scores:
        rows.append(("final score", f"{scores[-1]:.6g}"))
        rows.append(("best score", f"{min(scores):.6g}"))
    rows.append(("iterations", str(iters[-1] if iters else 0)))
    if "host_max_rss_mb" in last:
        rows.append(("host max RSS (MB)",
                     f"{last['host_max_rss_mb']:.1f}"))
    mm = last.get("param_mean_magnitudes") or {}
    table = "".join(f"<tr><td>{html.escape(k)}</td><td>{v}</td></tr>"
                    for k, v in rows)
    mm_table = "".join(
        f"<tr><td>{html.escape(k)}</td><td>{v:.6g}</td></tr>"
        for k, v in sorted(mm.items()))
    # per-layer histogram panels (last update that carried them)
    hists = {}
    for u in reversed(updates):
        if u.get("param_histograms"):
            hists = u["param_histograms"]
            break
    hist_panels = "".join(
        f'<div class="h"><div>{html.escape(name)}</div>'
        f'{_svg_histogram(h)}</div>'
        for name, h in sorted(hists.items()))
    hist_section = (f'<h2>Parameter histograms</h2>'
                    f'<div class="hwrap">{hist_panels}</div>'
                    if hist_panels else "")
    # update-magnitude trajectories (learning-rate health view)
    upd_series: Dict[str, list] = {}
    for u in updates:
        for k, v in (u.get("update_mean_magnitudes") or {}).items():
            upd_series.setdefault(k, []).append((u["iteration"], v))
    upd_section = ""
    if upd_series:
        charts = "".join(
            f'<div class="h"><div>{html.escape(k)}</div>'
            + _svg_polyline([float(i) for i, _ in pts],
                            [float(v) for _, v in pts], width=300,
                            height=90, pad=10)
            + "</div>"
            for k, pts in sorted(upd_series.items()))
        upd_section = (f'<h2>Update mean magnitudes</h2>'
                       f'<div class="hwrap">{charts}</div>')
    meta_refresh = (f'<meta http-equiv="refresh" '
                    f'content="{refresh_seconds:g}">'
                    if refresh_seconds else "")
    live_note = " (live)" if refresh_seconds else ""
    # hoisted out of the f-string: a backslash inside an f-string
    # expression is a SyntaxError before Python 3.12
    stats_json = export_json(storage, sid).replace("<", "\\u003c")
    return f"""<!doctype html>
<html><head><meta charset="utf-8">{meta_refresh}
<title>Training report — {html.escape(sid)}</title>
<style>body{{font:13px sans-serif;margin:2em}}td{{padding:2px 10px;
border-bottom:1px solid #eee}}h2{{margin-top:1.4em}}
.hwrap{{display:flex;flex-wrap:wrap;gap:12px}}
.h div{{font-size:11px;color:#555}}</style></head>
<body>
<h1>Training report{live_note}</h1>
<p>session <code>{html.escape(sid)}</code>, {len(updates)} updates</p>
<h2>Score</h2>
{_svg_polyline([float(i) for i in iters], [float(s) for s in scores])}
<h2>Summary</h2><table>{table}</table>
<h2>Parameter mean magnitudes (last iteration)</h2>
<table>{mm_table}</table>
{hist_section}
{upd_section}
<script type="application/json" id="stats-data">
{stats_json}
</script>
</body></html>"""


def render_html_report(storage: StatsStorage, path: str,
                       session_id: str = None) -> str:
    """Write a browsable report; returns the path (reference: the train
    module's overview page)."""
    with open(path, "w") as f:
        f.write(render_html(storage, session_id))
    return path
