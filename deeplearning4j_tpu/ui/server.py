"""Live training UI server — attach a StatsStorage and watch while fit()
runs.

Reference parity: deeplearning4j-play's PlayUIServer
(`ui/play/PlayUIServer.java:15-22`): `UIServer.getInstance()`,
`attach(statsStorage)`, pluggable modules (train overview, histograms,
update magnitudes), browse while training. Here the Play framework is a
stdlib ThreadingHTTPServer; every page request re-renders from the
attached storage, so the browser always sees the CURRENT run state, and
the page self-refreshes (watch mode). The remote-receiver module
counterpart lives in ui/remote.py (POST /stats); both can share one
storage so cluster workers report into the same live view.

Routes:
  GET /                  live HTML overview (self-refreshing)
  GET /train/sessions    JSON session ids
  GET /train/data        JSON all updates of the newest session
"""
from __future__ import annotations

import threading
from typing import Optional

from ..utils.http_server import JsonHttpServer
from .report import render_html
from .stats import StatsStorage


class UIServer:
    """PlayUIServer role; one instance per process via get_instance()."""

    _instance: Optional["UIServer"] = None
    _instance_lock = threading.Lock()

    def __init__(self, port: int = 0, refresh_seconds: float = 2.0):
        self._storages: list[StatsStorage] = []
        self._lock = threading.Lock()
        self.refresh_seconds = float(refresh_seconds)
        self._server = JsonHttpServer(
            get_routes={"/train/sessions": self._sessions,
                        "/train/data": self._data},
            post_routes={},
            raw_get_routes={"/": self._index},
            port=port)

    # ----------------------------------------------------------- lifecycle
    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        """Reference UIServer.getInstance(): lazily start the singleton."""
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls(port=port).start()
            return cls._instance

    def start(self) -> "UIServer":
        self._server.start()
        return self

    def stop(self):
        self._server.stop()
        with UIServer._instance_lock:
            if UIServer._instance is self:
                UIServer._instance = None

    @property
    def url(self) -> str:
        return self._server.url

    @property
    def port(self) -> int:
        return self._server.port

    # -------------------------------------------------------------- attach
    def attach(self, storage: StatsStorage) -> "UIServer":
        """Reference UIServer.attach(statsStorage): pages render from the
        newest session across all attached storages from now on."""
        with self._lock:
            if storage not in self._storages:
                self._storages.append(storage)
        return self

    def detach(self, storage: StatsStorage) -> "UIServer":
        with self._lock:
            if storage in self._storages:
                self._storages.remove(storage)
        return self

    def _pick(self):
        """(storage, session_id) of the most recently updated session."""
        with self._lock:
            storages = list(self._storages)
        best = None
        for st in storages:
            for sid in st.list_session_ids():
                updates = st.get_updates(sid)
                if not updates:
                    continue
                ts = updates[-1].get("timestamp", 0)
                if best is None or ts > best[2]:
                    best = (st, sid, ts)
        return (best[0], best[1]) if best else (None, None)

    # -------------------------------------------------------------- routes
    def _index(self):
        st, sid = self._pick()
        if st is None:
            body = (b"<!doctype html><meta http-equiv='refresh' "
                    b"content='2'><body>waiting for an attached "
                    b"StatsStorage with updates...</body>")
            return 200, "text/html; charset=utf-8", body
        doc = render_html(st, sid, refresh_seconds=self.refresh_seconds)
        return 200, "text/html; charset=utf-8", doc.encode()

    def _sessions(self, _):
        with self._lock:
            storages = list(self._storages)
        out = []
        for st in storages:
            out.extend(st.list_session_ids())
        return 200, {"sessions": out}

    def _data(self, _):
        st, sid = self._pick()
        if st is None:
            return 404, {"error": "no attached session"}
        return 200, {"session": sid, "updates": st.get_updates(sid)}
