"""Live training UI server — attach a StatsStorage and watch while fit()
runs.

Reference parity: deeplearning4j-play's PlayUIServer
(`ui/play/PlayUIServer.java:15-22`): `UIServer.getInstance()`,
`attach(statsStorage)`, pluggable modules (train overview, histograms,
update magnitudes), browse while training. Here the Play framework is a
stdlib ThreadingHTTPServer; every page request re-renders from the
attached storage, so the browser always sees the CURRENT run state, and
the page self-refreshes (watch mode). The remote-receiver module
counterpart lives in ui/remote.py (POST /stats); both can share one
storage so cluster workers report into the same live view.

Routes:
  GET /                  live HTML overview (self-refreshing)
  GET /train/sessions    JSON session ids
  GET /train/data        JSON all updates of the newest session
  GET /metrics           Prometheus text exposition of the process-global
                         MetricsRegistry (docs/observability.md)
  GET /trace             Chrome trace-event JSON of the tracing ring
                         (load in chrome://tracing / Perfetto)
  GET /tsne              embedding scatter plot (attach_embedding /
                         POST /tsne/upload — the tsne UI module role)
  POST /tsne/upload      {"points": [[x,y],...], "labels": [...]}
"""
from __future__ import annotations

import json
import threading
import zlib
from typing import Optional, Sequence

import numpy as np

from ..optimize import metrics as metrics_mod
from ..optimize import tracing
from ..utils.http_server import JsonHttpServer
from .report import render_html
from .stats import StatsStorage


def _scatter_svg(points: np.ndarray, labels: Sequence[str],
                 width=640, height=480, pad=24) -> str:
    """2-D embedding scatter (the tsne module's view). Points colored by
    label hash; labels legend capped at 12 entries."""
    import html as _html
    if len(points) == 0:
        return "<svg></svg>"
    p = np.asarray(points, np.float64)
    lo, hi = p.min(0), p.max(0)
    span = np.where(hi - lo > 0, hi - lo, 1.0)
    xy = (p - lo) / span
    uniq = []
    for l in labels:
        if l not in uniq:
            uniq.append(l)
    # crc32, not hash(): Python hash() is salted per process, which would
    # recolor every label on restart / across workers sharing one view
    color = {l: f"hsl({(zlib.crc32(str(l).encode()) % 360)},65%,45%)"
             for l in uniq}
    dots = "".join(
        f'<circle cx="{pad + x * (width - 2 * pad):.1f}" '
        f'cy="{height - pad - y * (height - 2 * pad):.1f}" r="3" '
        f'fill="{color[l]}"><title>{_html.escape(str(l))}</title>'
        f'</circle>'
        for (x, y), l in zip(xy, labels))
    legend = "".join(
        f'<text x="{pad + 90 * i}" y="14" font-size="11" '
        f'fill="{color[l]}">{_html.escape(str(l))[:10]}</text>'
        for i, l in enumerate(uniq[:12]))
    return (f'<svg viewBox="0 0 {width} {height}" width="{width}" '
            f'height="{height}" xmlns="http://www.w3.org/2000/svg">'
            f'<rect width="{width}" height="{height}" fill="#fafafa"/>'
            f'{legend}{dots}</svg>')


class UIServer:
    """PlayUIServer role; one instance per process via get_instance()."""

    _instance: Optional["UIServer"] = None
    _instance_lock = threading.Lock()

    def __init__(self, port: int = 0, refresh_seconds: float = 2.0):
        self._storages: list[StatsStorage] = []
        self._lock = threading.Lock()
        self.refresh_seconds = float(refresh_seconds)
        self._embedding = None  # (points [n,2], labels [n])
        self._model = None   # network shown on /model (flow module)
        self._activations = None  # ([(name, png_bytes)...], iteration)
        self._server = JsonHttpServer(
            get_routes={"/train/sessions": self._sessions,
                        "/train/data": self._data},
            post_routes={"/tsne/upload": self._tsne_upload},
            raw_get_routes={"/": self._index, "/tsne": self._tsne_page,
                            "/model": self._model_page,
                            "/activations": self._activations_page,
                            "/metrics": self._metrics,
                            "/trace": self._trace},
            port=port)

    # ----------------------------------------------------------- lifecycle
    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        """Reference UIServer.getInstance(): lazily start the singleton."""
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls(port=port).start()
            return cls._instance

    def start(self) -> "UIServer":
        self._server.start()
        return self

    def stop(self):
        self._server.stop()
        with UIServer._instance_lock:
            if UIServer._instance is self:
                UIServer._instance = None

    @property
    def url(self) -> str:
        return self._server.url

    @property
    def port(self) -> int:
        return self._server.port

    # -------------------------------------------------------------- attach
    def attach(self, storage: StatsStorage) -> "UIServer":
        """Reference UIServer.attach(statsStorage): pages render from the
        newest session across all attached storages from now on."""
        with self._lock:
            if storage not in self._storages:
                self._storages.append(storage)
        return self

    def detach(self, storage: StatsStorage) -> "UIServer":
        with self._lock:
            if storage in self._storages:
                self._storages.remove(storage)
        return self

    def _pick(self):
        """(storage, session_id) of the most recently updated session."""
        with self._lock:
            storages = list(self._storages)
        best = None
        for st in storages:
            for sid in st.list_session_ids():
                updates = st.get_updates(sid)
                if not updates:
                    continue
                ts = updates[-1].get("timestamp", 0)
                if best is None or ts > best[2]:
                    best = (st, sid, ts)
        return (best[0], best[1]) if best else (None, None)

    # -------------------------------------------------------------- routes
    def _index(self):
        st, sid = self._pick()
        if st is None:
            body = (b"<!doctype html><meta http-equiv='refresh' "
                    b"content='2'><body>waiting for an attached "
                    b"StatsStorage with updates...</body>")
            return 200, "text/html; charset=utf-8", body
        doc = render_html(st, sid, refresh_seconds=self.refresh_seconds)
        return 200, "text/html; charset=utf-8", doc.encode()

    def _sessions(self, _):
        with self._lock:
            storages = list(self._storages)
        out = []
        for st in storages:
            out.extend(st.list_session_ids())
        return 200, {"sessions": out}

    def _data(self, _):
        st, sid = self._pick()
        if st is None:
            return 404, {"error": "no attached session"}
        return 200, {"session": sid, "updates": st.get_updates(sid)}

    # ------------------------------------------------- observability scrape
    def _metrics(self):
        """Prometheus scrape target: the process-global registry, so one
        endpoint covers every network/wrapper in the process."""
        body = metrics_mod.registry().prometheus_text().encode()
        return 200, "text/plain; version=0.0.4; charset=utf-8", body

    def _trace(self):
        """Chrome trace-event JSON of the span ring (empty traceEvents
        list until tracing.enable() has been called)."""
        body = json.dumps(tracing.export_trace_events()).encode()
        return 200, "application/json", body

    # --------------------------------------------------------- flow module
    def attach_model(self, net) -> "UIServer":
        """Show the network's architecture on /model (the reference flow
        UI module: layer boxes in execution order with connections).
        Works for MultiLayerNetwork (chain) and ComputationGraph (DAG in
        topological order)."""
        with self._lock:
            self._model = net
        return self

    def _model_page(self):
        with self._lock:
            net = self._model
        if net is None:
            return (200, "text/html; charset=utf-8",
                    b"<!doctype html><body>no model attached - "
                    b"attach_model(net)</body>")
        import html as _html
        rows = []
        if hasattr(net, "layers"):  # MultiLayerNetwork chain
            for i, layer in enumerate(net.layers):
                rows.append((f"layer{i}", type(layer).__name__,
                             [f"layer{i-1}"] if i else []))
        else:  # ComputationGraph DAG
            for name in net.conf.topo_order:
                node = net.conf.nodes[name]
                kind = type(node.layer if node.is_layer()
                            else node.vertex).__name__
                rows.append((name, kind, list(node.inputs)))
        ypos = {name: 26 + i * 44 for i, (name, _, _) in enumerate(rows)}
        boxes, edges = [], []
        for name, kind, inputs in rows:
            y = ypos[name]
            boxes.append(
                f'<rect x="150" y="{y}" width="340" height="32" rx="6" '
                f'fill="#eef4ff" stroke="#88a"/>'
                f'<text x="160" y="{y + 20}" font-size="12">'
                f'{_html.escape(name)}: {_html.escape(kind)}</text>')
            for src in inputs:
                if src in ypos:
                    edges.append(
                        f'<line x1="320" y1="{ypos[src] + 32}" x2="320" '
                        f'y2="{y}" stroke="#668" marker-end="url(#a)"/>')
                else:  # network input
                    edges.append(
                        f'<text x="40" y="{y + 20}" font-size="11" '
                        f'fill="#486">{_html.escape(src)} &#8594;</text>')
        h = 26 + len(rows) * 44 + 20
        doc = (f"<!doctype html><html><head><meta charset='utf-8'>"
               f"<title>Model</title></head><body><h1>Model "
               f"({len(rows)} nodes)</h1>"
               f'<svg viewBox="0 0 640 {h}" width="640" height="{h}" '
               f'xmlns="http://www.w3.org/2000/svg">'
               f'<defs><marker id="a" markerWidth="8" markerHeight="8" '
               f'refX="6" refY="3" orient="auto">'
               f'<path d="M0,0 L6,3 L0,6 z" fill="#668"/></marker></defs>'
               f'{"".join(edges)}{"".join(boxes)}</svg></body></html>')
        return 200, "text/html; charset=utf-8", doc.encode()

    # ------------------------------------------------- convolutional module
    def attach_activations(self, grids, iteration: int) -> "UIServer":
        """Show per-conv-layer activation grids on /activations (the
        reference play `convolutional` module; fed by
        ui.convolutional.ConvolutionalIterationListener). `grids`:
        [(layer_name, png_bytes), ...]."""
        with self._lock:
            self._activations = (list(grids), int(iteration))
        return self

    def _activations_page(self):
        import base64
        import html as _html
        with self._lock:
            snap = self._activations
        if snap is None:
            return (200, "text/html; charset=utf-8",
                    b"<!doctype html><meta http-equiv='refresh' "
                    b"content='2'><body>no activations yet - add a "
                    b"ConvolutionalIterationListener</body>")
        grids, iteration = snap
        parts = [f"<!doctype html><html><head><meta charset='utf-8'>"
                 f"<meta http-equiv='refresh' "
                 f"content='{self.refresh_seconds}'>"
                 f"<title>Activations</title></head><body>"
                 f"<h1>Conv activations @ iteration {iteration}</h1>"]
        for name, png in grids:
            b64 = base64.b64encode(png).decode()
            parts.append(
                f"<h3>{_html.escape(str(name))}</h3>"
                f'<img style="image-rendering:pixelated" width="512" '
                f'src="data:image/png;base64,{b64}"/>')
        parts.append("</body></html>")
        return 200, "text/html; charset=utf-8", "".join(parts).encode()

    # --------------------------------------------------------- tsne module
    def attach_embedding(self, points, labels=None) -> "UIServer":
        """Show a 2-D embedding on /tsne (the reference tsne UI module:
        upload t-SNE coordinates, browse the scatter). Pairs naturally
        with clustering.tsne.TSNE output."""
        points = np.asarray(points, np.float64)
        if points.ndim != 2 or points.shape[1] != 2:
            raise ValueError(f"need [n, 2] points, got {points.shape}")
        labels = [""] * len(points) if labels is None else \
            [str(l) for l in labels]
        if len(labels) != len(points):
            raise ValueError("labels length != points length")
        with self._lock:
            self._embedding = (points, labels)
        return self

    def _tsne_upload(self, payload):
        self.attach_embedding(payload["points"], payload.get("labels"))
        return 200, {"count": len(payload["points"])}

    def _tsne_page(self):
        with self._lock:
            emb = self._embedding
        if emb is None:
            body = ("<!doctype html><body>no embedding attached — "
                    "attach_embedding(points, labels) or POST "
                    "/tsne/upload</body>").encode()
            return 200, "text/html; charset=utf-8", body
        doc = (f"<!doctype html><html><head><meta charset='utf-8'>"
               f"<title>t-SNE</title></head><body>"
               f"<h1>Embedding ({len(emb[0])} points)</h1>"
               f"{_scatter_svg(emb[0], emb[1])}</body></html>")
        return 200, "text/html; charset=utf-8", doc.encode()
