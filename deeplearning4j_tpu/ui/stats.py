"""Training observability: StatsListener → StatsStorage pipeline.

Reference parity: deeplearning4j-ui-model's BaseStatsListener
(ui/stats/BaseStatsListener.java:280+ — per-iteration score, timings,
memory, param/gradient/update histograms and mean magnitudes) routed
through the StatsStorageRouter contract (deeplearning4j-core
api/storage/StatsStorage.java) into InMemoryStatsStorage /
FileStatsStorage backends (ui/storage/). The SBE binary wire format and
the Play UI server are replaced by plain JSON records and a static HTML
report (ui/report.py) — the storage API surface is what downstream code
programs against, and that is preserved.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..optimize import metrics as metrics_mod
from ..optimize.listeners import IterationListener


# ---------------------------------------------------------------------------
# Storage (reference api/storage/StatsStorage.java)
# ---------------------------------------------------------------------------
class StatsStorage:
    """SPI: session-keyed append-only update records."""

    def put_update(self, session_id: str, record: Dict[str, Any]) -> None:
        raise NotImplementedError

    def list_session_ids(self) -> List[str]:
        raise NotImplementedError

    def get_updates(self, session_id: str) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def get_latest_update(self, session_id: str) -> Optional[Dict[str, Any]]:
        ups = self.get_updates(session_id)
        return ups[-1] if ups else None


class InMemoryStatsStorage(StatsStorage):
    """Reference ui/storage/InMemoryStatsStorage.java."""

    def __init__(self):
        self._updates: Dict[str, List[Dict[str, Any]]] = {}

    def put_update(self, session_id, record):
        self._updates.setdefault(session_id, []).append(record)

    def list_session_ids(self):
        return list(self._updates)

    def get_updates(self, session_id):
        return list(self._updates.get(session_id, []))


class FileStatsStorage(StatsStorage):
    """JSON-lines file persistence (reference ui/storage/FileStatsStorage
    over MapDB; a flat JSONL file is the TPU-era equivalent — trivially
    greppable and survives restarts)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def put_update(self, session_id, record):
        with open(self.path, "a") as f:
            f.write(json.dumps({"session": session_id, **record}) + "\n")

    def _read(self) -> List[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return []
        with open(self.path) as f:
            return [json.loads(line) for line in f if line.strip()]

    def list_session_ids(self):
        seen = []
        for rec in self._read():
            if rec["session"] not in seen:
                seen.append(rec["session"])
        return seen

    def get_updates(self, session_id):
        return [{k: v for k, v in rec.items() if k != "session"}
                for rec in self._read() if rec["session"] == session_id]


# ---------------------------------------------------------------------------
# Listener (reference ui/stats/BaseStatsListener.java)
# ---------------------------------------------------------------------------
class StatsUpdateConfiguration:
    """What to collect per update (reference
    DefaultStatsUpdateConfiguration builder)."""

    def __init__(self, *, collect_score: bool = True,
                 collect_timings: bool = True,
                 collect_memory: bool = True,
                 collect_histograms: bool = False,
                 histogram_bins: int = 20,
                 collect_mean_magnitudes: bool = True,
                 collect_updates: bool = False):
        self.collect_score = collect_score
        self.collect_timings = collect_timings
        self.collect_memory = collect_memory
        self.collect_histograms = collect_histograms
        self.histogram_bins = int(histogram_bins)
        self.collect_mean_magnitudes = collect_mean_magnitudes
        self.collect_updates = collect_updates


def _named_params(model):
    """Yield (name, np.ndarray) over the model's parameter tree."""
    tree = model.params_tree
    if isinstance(tree, dict):  # ComputationGraph: name-keyed
        for node, params in tree.items():
            for pname, arr in params.items():
                yield f"{node}/{pname}", np.asarray(arr)
    else:  # MultiLayerNetwork: indexed tuple
        for i, params in enumerate(tree):
            for pname, arr in params.items():
                yield f"layer{i}/{pname}", np.asarray(arr)


class StatsListener(IterationListener):
    """Collects per-iteration training statistics into a StatsStorage
    (reference StatsListener/BaseStatsListener). Attach with
    net.add_listener(StatsListener(storage))."""

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 session_id: Optional[str] = None,
                 config: Optional[StatsUpdateConfiguration] = None):
        self.storage = storage
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"session-{int(time.time() * 1000)}"
        self.config = config or StatsUpdateConfiguration()
        self._last_time: Optional[float] = None
        self._prev_params: Optional[Dict[str, np.ndarray]] = None

    def _histogram(self, arr: np.ndarray):
        counts, edges = np.histogram(arr, bins=self.config.histogram_bins)
        return {"counts": counts.tolist(),
                "min": float(edges[0]), "max": float(edges[-1])}

    def iteration_done(self, model, iteration: int) -> None:
        now = time.time()
        duration_ms = None if self._last_time is None \
            else (now - self._last_time) * 1000.0
        self._last_time = now
        if iteration % self.frequency != 0:
            return
        cfg = self.config
        rec: Dict[str, Any] = {"iteration": int(iteration),
                               "timestamp": now}
        if cfg.collect_score:
            rec["score"] = float(model.score_value) \
                if model.score_value is not None else None
        if cfg.collect_timings and duration_ms is not None:
            rec["iteration_ms"] = duration_ms
        if cfg.collect_memory:
            # host-side RSS, the JVM-heap analog; host_rss_bytes handles
            # the ru_maxrss unit split (KiB on Linux, BYTES on macOS)
            rec["host_max_rss_mb"] = \
                metrics_mod.host_rss_bytes() / (1024.0 * 1024.0)
            devs = metrics_mod.device_memory_stats()
            if devs and devs[0]["bytes_in_use"]:
                rec["device_bytes_in_use"] = devs[0]["bytes_in_use"]
        if cfg.collect_mean_magnitudes or cfg.collect_histograms or \
                cfg.collect_updates:
            mm: Dict[str, float] = {}
            hists: Dict[str, Any] = {}
            upd_mm: Dict[str, float] = {}
            new_prev: Dict[str, np.ndarray] = {}
            for name, arr in _named_params(model):
                if cfg.collect_mean_magnitudes:
                    mm[name] = float(np.mean(np.abs(arr)))
                if cfg.collect_histograms:
                    hists[name] = self._histogram(arr)
                if cfg.collect_updates:
                    if self._prev_params is not None and \
                            name in self._prev_params:
                        upd_mm[name] = float(np.mean(np.abs(
                            arr - self._prev_params[name])))
                    new_prev[name] = arr.copy()
            if cfg.collect_mean_magnitudes:
                rec["param_mean_magnitudes"] = mm
            if cfg.collect_histograms:
                rec["param_histograms"] = hists
            if cfg.collect_updates:
                self._prev_params = new_prev
                if upd_mm:
                    rec["update_mean_magnitudes"] = upd_mm
        self.storage.put_update(self.session_id, rec)

    def on_epoch_end(self, model, epoch: int) -> None:
        self.storage.put_update(self.session_id,
                                {"epoch_end": int(epoch),
                                 "iteration": int(model.iteration),
                                 "timestamp": time.time()})
