"""Deterministic fault-injection registry (reference: the chaos hooks DL4J's
parameter-server tests relied on, rebuilt as a first-class module).

Production code calls :func:`fire` / :func:`check` at *named injection
points*; when nothing is armed both are near-free no-ops.  Tests (or an
operator, via environment variables) arm a point with a plan string:

    ``"fail:2"``      raise :class:`FaultInjected` on the 2nd call
    ``"fail:1,3"``    ... on the 1st and 3rd calls
    ``"fail:2-4"``    ... on calls 2 through 4
    ``"fail:2/5"``    ... on calls 2, 7, 12, ... (every 5th from the 2nd:
                      a deterministic 20% failure rate for chaos storms)
    ``"fail:*"``      ... on every call
    ``"kill:3"``      SIGKILL *this process* on the 3rd call (crash tests)
    ``"delay:2@50"``  sleep 50 ms on the 2nd call, then continue (latency
                      injection — same call selectors as fail:/kill:,
                      e.g. ``"delay:*@10"``, ``"delay:1/4@25"``)

Call numbers are 1-based and counted per point, so a plan is fully
deterministic: the same program order always hits the same faults.

Points used by the training stack (arbitrary names are allowed):

    checkpoint.write   inside the atomic checkpoint writer, before rename
    ps.push / ps.pull  each parameter-server transport attempt (per retry)
    etl.next           each base-iterator poll in the async producer
    step.nonfinite     per-step divergence flag (checked, never raised)

Points used by the cluster health plane (docs/robustness.md):

    heartbeat.send     each watchdog beat publish — ``fail:`` suppresses
                       the beat (a peer goes quiet), ``delay:SEL@MS``
                       injects side-channel latency
    step.stall         checked in ClusterHealthMonitor.notify_step; when
                       armed the step report is swallowed, so the process
                       keeps beating but looks frozen (the deterministic
                       stand-in for a wedged main thread)

Points used by the bench scoreboard plane (docs/observability.md):

    bench.child        each heartbeat publish inside a bench --once
                       child (only when the parent armed the side
                       channel) — ``delay:SEL@MS`` with a huge MS wedges
                       the child mid-measurement, the deterministic
                       stand-in for the round-5 hung bench subprocess;
                       ``fail:`` silences the beat thread instead
    bench.probe        inside the tunnel-liveness probe subprocess,
                       before it touches jax — ``delay:`` wedges the
                       probe into a ``"tunnel": "dead"`` verdict

Points used by the serving stack (docs/serving.md):

    serve.forward      each coalesced forward in ParallelInference (and
                       each SEQUENTIAL-mode forward)
    serve.decode       the checkpoint decode/stage step of a hot-swap,
                       before any live state is mutated
    serve.pack         packed-admission assembly/unpack of a segment-
                       masked row (fires twice per packed forward:
                       before the pack and before the unpack)
    serve.schedule     entry to the device-scheduler slot, before the
                       waiter is enqueued — armed errors surface as
                       typed request failures without ever parking a
                       thread on the scheduler condition
    swap.warm          each per-bucket warm forward inside the
                       pause-assign-warm swap window (fires the rollback
                       path when armed)
    serve.decode_step  each iteration-level decode step in DecodeEngine,
                       before the step forward dispatches — an armed
                       failure fails the riding requests typed
                       (DecodeStepError), frees their KV blocks, and
                       leaves decode batchmates generating

Points used by the replica federation plane (docs/serving.md
§"Replica federation"):

    route.dispatch     each front-end dispatch leg (the first attempt
                       AND the failover retry each count one call) —
                       ``fail:`` drops the leg before the HTTP post,
                       exercising the typed failover path without
                       killing a replica; ``delay:SEL@MS`` injects
                       route latency
    replica.beat       each replica-side beat publish — ``fail:``
                       suppresses the beat, so the replica goes dark
                       and is evicted past timeout_s while its gateway
                       keeps serving (the deterministic stand-in for a
                       beat-channel partition); env-armable in replica
                       subprocesses via DL4JTPU_FAULT_REPLICA_BEAT

Environment arming: ``DL4JTPU_FAULT_<POINT>`` with dots mapped to
underscores, e.g. ``DL4JTPU_FAULT_CHECKPOINT_WRITE="kill:3"`` — this is
how subprocess crash tests arm the child without touching its code.

Stdlib-only on purpose: everything in the package may import this.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Set, Tuple


class FaultInjected(RuntimeError):
    """Raised at an armed injection point.

    Marked ``transient`` so retry helpers treat it like a flaky-transport
    error rather than a programming bug.
    """

    transient = True


class _Plan:
    __slots__ = ("action", "calls", "periodic", "always", "delay_ms",
                 "count", "fired")

    def __init__(self, action: str, calls: Set[int],
                 periodic: List[Tuple[int, int]], always: bool,
                 delay_ms: float = 0.0):
        self.action = action      # "fail" | "kill" | "delay"
        self.calls = calls        # 1-based call numbers covered
        self.periodic = periodic  # (start, every) pairs: start, start+every, ...
        self.always = always
        self.delay_ms = delay_ms  # sleep duration for "delay" plans
        self.count = 0            # calls seen at this point
        self.fired = 0            # calls that actually faulted

    def covers(self, n: int) -> bool:
        return (self.always or n in self.calls or
                any(n >= s and (n - s) % p == 0 for s, p in self.periodic))


def _parse(spec: str) -> _Plan:
    action, _, arg = spec.strip().partition(":")
    if action not in ("fail", "kill", "delay"):
        raise ValueError(f"unknown fault action {action!r} in spec {spec!r} "
                         "(expected 'fail:...', 'kill:...' or 'delay:...')")
    arg = arg.strip()
    delay_ms = 0.0
    if action == "delay":
        arg, at, ms = arg.partition("@")
        arg = arg.strip()
        try:
            delay_ms = float(ms)
        except ValueError:
            at = ""
        if not at or delay_ms < 0:
            raise ValueError(
                f"delay spec {spec!r} needs 'delay:SELECTOR@MS' with a "
                "non-negative millisecond count")
    if arg in ("", "*"):
        return _Plan(action, set(), [], always=True, delay_ms=delay_ms)
    calls: Set[int] = set()
    periodic: List[Tuple[int, int]] = []
    for part in arg.split(","):
        part = part.strip()
        lo, slash, every = part.partition("/")
        try:
            if slash:
                start, period = int(lo), int(every)
                if start < 1 or period < 1:
                    raise ValueError
                periodic.append((start, period))
                continue
            lo, dash, hi = part.partition("-")
            if dash:
                calls.update(range(int(lo), int(hi) + 1))
            else:
                calls.add(int(lo))
        except ValueError:
            raise ValueError(f"bad call selector {part!r} in fault spec {spec!r}")
    if not (calls or periodic) or (calls and min(calls) < 1):
        raise ValueError(f"fault spec {spec!r} must select 1-based call numbers")
    return _Plan(action, calls, periodic, always=False, delay_ms=delay_ms)


_lock = threading.Lock()
_plans: Dict[str, _Plan] = {}
_env_checked: Set[str] = set()          # points whose env var was consulted


def _env_var(point: str) -> str:
    return "DL4JTPU_FAULT_" + point.upper().replace(".", "_").replace("-", "_")


def inject(point: str, spec: str) -> None:
    """Arm `point` with a plan (replacing any existing plan and counters)."""
    plan = _parse(spec)
    with _lock:
        _plans[point] = plan
        _env_checked.add(point)         # explicit plan wins over env


def clear(point: Optional[str] = None) -> None:
    """Disarm one point (or all); cleared points do not re-arm from env."""
    with _lock:
        if point is None:
            _env_checked.update(_plans)
            _plans.clear()
        else:
            _plans.pop(point, None)
            _env_checked.add(point)


def reset() -> None:
    """Full reset, including env re-arming — test fixtures only."""
    with _lock:
        _plans.clear()
        _env_checked.clear()


def _advance(point: str) -> Optional[Tuple[str, float]]:
    with _lock:
        plan = _plans.get(point)
        if plan is None:
            if point in _env_checked:
                return None
            _env_checked.add(point)
            spec = os.environ.get(_env_var(point))
            if not spec:
                return None
            plan = _plans[point] = _parse(spec)
        plan.count += 1
        if plan.covers(plan.count):
            plan.fired += 1
            return plan.action, plan.delay_ms
        return None


def fire(point: str) -> None:
    """Injection hook for raising points.

    No-op unless an armed plan covers this call; then raises
    :class:`FaultInjected` (``fail``), SIGKILLs the process (``kill`` —
    deliberately unmaskable, for torn-write crash tests), or sleeps and
    returns (``delay`` — latency injection, never an error).
    """
    hit = _advance(point)
    if hit is None:
        return
    action, delay_ms = hit
    if action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if action == "delay":
        time.sleep(delay_ms / 1000.0)
        return
    raise FaultInjected(f"injected fault at {point!r} (call #{call_count(point)})")


def check(point: str) -> bool:
    """Non-raising variant for flag-style points (e.g. ``step.nonfinite``):
    returns True when the plan covers this call. A ``delay`` plan sleeps
    but returns False — it slows the caller without flipping the flag."""
    hit = _advance(point)
    if hit is None:
        return False
    action, delay_ms = hit
    if action == "delay":
        time.sleep(delay_ms / 1000.0)
        return False
    return True


def call_count(point: str) -> int:
    with _lock:
        plan = _plans.get(point)
        return plan.count if plan else 0


def fired_count(point: str) -> int:
    with _lock:
        plan = _plans.get(point)
        return plan.fired if plan else 0


@contextmanager
def injected(point: str, spec: str):
    """Scoped arming for tests: arms on entry, disarms on exit."""
    inject(point, spec)
    try:
        yield
    finally:
        clear(point)
