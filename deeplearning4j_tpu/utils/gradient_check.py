"""Gradient checking harness — the test backbone.

Reference parity: gradientcheck/GradientCheckUtil.java:49-80 — central finite
differences per parameter vs backprop gradient, with relative-error
tolerance; backbone of the reference's layer test suite
(GradientCheckTests, CNNGradientCheckTest, LSTMGradientCheckTests, ...).

Here the "backprop" side is jax autodiff of the network's loss; the check
still guards against wrong loss wiring, masking bugs, regularization terms,
and custom-layer math. Run in float64 (tests enable jax_enable_x64) so the
finite-difference noise floor stays below the tolerance, as the reference
does with double precision.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import params as param_utils


def gradient_check_mln(
    net,
    x,
    y,
    features_mask=None,
    labels_mask=None,
    epsilon: float = 1e-6,
    max_rel_error: float = 1e-3,
    min_abs_error: float = 1e-8,
    print_results: bool = False,
    max_params: Optional[int] = None,
    seed: int = 0,
) -> bool:
    """Central finite differences vs autodiff for every parameter of a
    MultiLayerNetwork (sampled down to `max_params` when given, for big nets).
    Returns True if all checked parameters pass; mirrors
    GradientCheckUtil.checkGradients."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    fm = None if features_mask is None else jnp.asarray(features_mask)
    lm = None if labels_mask is None else jnp.asarray(labels_mask)

    def loss_from_flat(flat):
        params = param_utils.unflatten_params(net.params_tree, flat)
        loss, _ = net._loss_pure(params, net.state_tree, x, y, fm, lm, None, False)
        return loss

    flat = param_utils.flatten_params(net.params_tree)
    analytic = np.asarray(jax.grad(loss_from_flat)(flat))
    flat_np = np.asarray(flat)

    n = flat_np.shape[0]
    if max_params is not None and max_params < n:
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, size=max_params, replace=False))
    else:
        idx = np.arange(n)

    n_fail = 0
    for i in idx:
        plus = flat_np.copy()
        plus[i] += epsilon
        minus = flat_np.copy()
        minus[i] -= epsilon
        num = (float(loss_from_flat(jnp.asarray(plus)))
               - float(loss_from_flat(jnp.asarray(minus)))) / (2 * epsilon)
        ana = float(analytic[i])
        denom = max(abs(num), abs(ana))
        rel = 0.0 if denom == 0 else abs(num - ana) / denom
        ok = rel <= max_rel_error or abs(num - ana) <= min_abs_error
        if not ok:
            n_fail += 1
            if print_results:
                print(f"param {i}: numeric={num:.8g} analytic={ana:.8g} rel={rel:.3g} FAIL")
        elif print_results:
            print(f"param {i}: numeric={num:.8g} analytic={ana:.8g} rel={rel:.3g} ok")
    if n_fail and not print_results:
        print(f"gradient check: {n_fail}/{len(idx)} parameters failed")
    return n_fail == 0


def gradient_check_fn(fn, params, epsilon: float = 1e-6,
                      max_rel_error: float = 1e-3,
                      min_abs_error: float = 1e-8,
                      max_params: Optional[int] = None, seed: int = 0) -> bool:
    """Generic scalar-fn gradient check over a pytree of params (used for
    ComputationGraph, custom layers, loss functions)."""
    flat = param_utils.flatten_params(params)

    def loss_from_flat(f):
        return fn(param_utils.unflatten_params(params, f))

    analytic = np.asarray(jax.grad(loss_from_flat)(flat))
    flat_np = np.asarray(flat)
    n = flat_np.shape[0]
    if max_params is not None and max_params < n:
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, size=max_params, replace=False))
    else:
        idx = np.arange(n)
    for i in idx:
        plus = flat_np.copy()
        plus[i] += epsilon
        minus = flat_np.copy()
        minus[i] -= epsilon
        num = (float(loss_from_flat(jnp.asarray(plus)))
               - float(loss_from_flat(jnp.asarray(minus)))) / (2 * epsilon)
        ana = float(analytic[i])
        denom = max(abs(num), abs(ana))
        rel = 0.0 if denom == 0 else abs(num - ana) / denom
        if rel > max_rel_error and abs(num - ana) > min_abs_error:
            return False
    return True
