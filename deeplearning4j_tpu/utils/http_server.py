"""Shared stdlib JSON-HTTP server scaffolding for the serving facades
(serving gateway, k-NN server, Keras backend server, remote stats
receiver) — one place for handler/json/start/stop/context-manager
mechanics.

Serving-grade hardening (docs/serving.md): requests are handled on a
BOUNDED thread pool (`pool_size` concurrent handlers — unbounded
thread-per-request falls over exactly when a gateway is overloaded,
which is when it matters), `stop()` is graceful (close the listening
socket so no new connection is accepted, then finish every in-flight
handler before returning), and any server can expose the process-global
metrics registry at ``GET /metrics`` with `expose_metrics=True` (the
Prometheus scrape surface, same exposition as the UIServer's).
"""
from __future__ import annotations

import json
import threading
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

# route tables: {path: handler(request_dict_or_None) -> (code, obj)}
Routes = Dict[str, Callable]


class _PooledHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose per-connection work runs on a bounded
    ThreadPoolExecutor instead of an unbounded thread-per-request."""

    def __init__(self, addr, handler_cls, pool_size: int):
        super().__init__(addr, handler_cls)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(pool_size)),
            thread_name_prefix="JsonHttpServer")

    def process_request(self, request, client_address):
        try:
            self._pool.submit(self.process_request_thread, request,
                              client_address)
        except RuntimeError:  # pool already shut down: closing race
            self.shutdown_request(request)

    def close_pool(self):
        # wait=True: every in-flight handler finishes before stop()
        # returns — the graceful half of graceful shutdown.
        self._pool.shutdown(wait=True)


class JsonHttpServer:
    """Bind GET/POST route tables; handlers return (status, json_obj).
    Handler exceptions become 400s (client-visible, server stays up)."""

    def __init__(self, get_routes: Routes, post_routes: Routes,
                 port: int = 0, host: str = "127.0.0.1",
                 raw_get_routes: Optional[Routes] = None,
                 pool_size: int = 8, expose_metrics: bool = False):
        self._get = dict(get_routes)
        self._post = dict(post_routes)
        # raw routes return (status, content_type, body_bytes) — the live
        # UI serves HTML through these; JSON routes stay JSON
        self._raw_get = dict(raw_get_routes or {})
        if expose_metrics and "/metrics" not in self._raw_get:
            self._raw_get["/metrics"] = _metrics_route
        self._port = int(port)
        self._host = host
        self._pool_size = int(pool_size)
        self._httpd: Optional[_PooledHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def start(self):
        get_routes, post_routes = self._get, self._post
        raw_get_routes = self._raw_get

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _dispatch(self, routes, payload, path=None):
                fn = routes.get(path if path is not None else self.path)
                if fn is None:
                    self._json(404, {"error": "unknown path"})
                    return
                try:
                    self._json(*fn(payload))
                except Exception as e:  # bad request must not kill server
                    self._json(400, {"error": str(e)})

            def do_GET(self):
                # GET handlers receive the parsed query string (or None
                # when there is none) — `/debug/requests?model=a&tier=b`
                # routes on the bare path like every other endpoint.
                path, _, query = self.path.partition("?")
                raw = raw_get_routes.get(path)
                if raw is not None:
                    try:
                        code, ctype, body = raw()
                    except Exception as e:
                        self._json(400, {"error": str(e)})
                        return
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                params = (dict(urllib.parse.parse_qsl(query))
                          if query else None)
                self._dispatch(get_routes, params, path=path)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n))
                except Exception as e:
                    self._json(400, {"error": f"bad JSON: {e}"})
                    return
                self._dispatch(post_routes, payload)

        self._httpd = _PooledHTTPServer((self._host, self._port), Handler,
                                        self._pool_size)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Graceful: stop accepting (shutdown + close the listening
        socket), then wait for every in-flight handler to finish."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd.close_pool()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def _metrics_route():
    """GET /metrics — Prometheus text exposition of the process-global
    registry (the same scrape surface UIServer exposes)."""
    from ..optimize.metrics import registry
    body = registry().prometheus_text().encode()
    return 200, "text/plain; version=0.0.4; charset=utf-8", body


def json_request(url: str, payload=None, timeout: float = 5.0):
    """One-call JSON client for the in-repo servers: POST `payload` (GET
    when None), parse the JSON reply. Always passes a socket timeout —
    the callers (heartbeat transport, stats router, tests) must never
    block forever on a half-dead peer. Raises urllib's errors on non-2xx
    or timeout; the caller decides whether that is transient."""
    import urllib.request
    data = None if payload is None else json.dumps(payload).encode()
    headers = {"Content-Type": "application/json"} if data else {}
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=float(timeout)) as r:
        return json.loads(r.read().decode())
