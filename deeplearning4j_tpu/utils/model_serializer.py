"""Model checkpointing: save/restore full training state.

Reference parity: util/ModelSerializer.java:37-127 — a ZIP container with
`configuration.json` (Jackson-serialized config), `coefficients.bin` (flat
params), `updaterState.bin`, `normalizer.bin`; restore at :137+; plus
ModelGuesser-style type sniffing on load.

TPU-native: same logical contents, npz-encoded pytrees instead of a single
flat buffer (leaves keyed by their tree path, so layout changes surface as
key mismatches rather than silent misloads). BatchNorm running stats
(state tree) are persisted too — in the reference they live inside params.
Restore rebuilds the network from configuration.json and loads arrays
on-device in one transfer.
"""
from __future__ import annotations

import io
import json
import os
import zipfile
import zlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import faults, serde

FORMAT_VERSION = 1


class CheckpointCorruptError(Exception):
    """The checkpoint archive is unreadable: truncated, missing required
    entries, CRC-failed, or carrying an unsupported format_version.

    Distinct from :class:`ValueError` (which restore raises for a *valid*
    archive whose arrays don't match the model — a config mismatch, not
    corruption), so callers can skip torn files and fall back to an older
    checkpoint without masking real bugs.
    """

CONFIG_ENTRY = "configuration.json"
META_ENTRY = "metadata.json"
PARAMS_ENTRY = "coefficients.npz"
UPDATER_ENTRY = "updaterState.npz"
STATE_ENTRY = "state.npz"
NORMALIZER_ENTRY = "normalizer.json"
RNG_ENTRY = "rngState.npz"  # round 3: exact resume for rng-consuming nets


def _tree_to_npz_bytes(tree) -> bytes:
    """npz-encode a pytree. Non-numpy-native dtypes (bfloat16 etc.) are
    stored as raw uint16/uint8 bits with the true dtype name recorded in the
    __dtypes__ sidecar, since np.load round-trips them as void."""
    leaves, _ = jax.tree_util.tree_flatten(tree)
    arrays = {}
    dtype_names = []
    for i, a in enumerate(leaves):
        na = np.asarray(a)
        dtype_names.append(na.dtype.name)
        if na.dtype.kind not in "biufc":  # ml_dtypes (bfloat16, fp8, ...)
            na = na.view(np.uint8 if na.dtype.itemsize == 1 else np.uint16)
        arrays[f"leaf{i:05d}"] = na
    arrays["__dtypes__"] = np.array(dtype_names)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _npz_bytes_to_tree(data: bytes, template):
    import ml_dtypes
    leaves, treedef = jax.tree_util.tree_flatten(template)
    with np.load(io.BytesIO(data)) as z:
        keys = sorted(k for k in z.files if k != "__dtypes__")
        if len(keys) != len(leaves):
            raise ValueError(
                f"Checkpoint has {len(keys)} arrays but the model expects "
                f"{len(leaves)} — config/architecture mismatch")
        dtype_names = ([str(s) for s in z["__dtypes__"]]
                       if "__dtypes__" in z.files else [None] * len(keys))
        loaded = []
        for k, name in zip(keys, dtype_names):
            arr = z[k]
            if name is not None and arr.dtype.name != name and \
                    arr.dtype.kind in "u":
                arr = arr.view(getattr(ml_dtypes, name))
            loaded.append(arr)
    for a, b in zip(leaves, loaded):
        if tuple(a.shape) != tuple(b.shape):
            raise ValueError(
                f"Checkpoint array shape {b.shape} != model shape {a.shape}")
    return treedef.unflatten([jnp.asarray(b, a.dtype)
                              for a, b in zip(leaves, loaded)])


# entries every readable checkpoint must carry (RNG/updater/normalizer
# are conditional; validation for those is presence-gated on metadata)
REQUIRED_ENTRIES = (META_ENTRY, CONFIG_ENTRY, PARAMS_ENTRY, STATE_ENTRY)

# read failures on individual ZIP members (CRC mismatch surfaces as
# BadZipFile from zipfile, deflate damage as zlib.error, short reads as
# EOFError/struct noise wrapped in these)
_READ_ERRORS = (zipfile.BadZipFile, zlib.error, EOFError, KeyError, OSError)


def save_model(model, path: str, save_updater: bool = True,
               normalizer=None) -> None:
    """Write a checkpoint ZIP (reference ModelSerializer.writeModel:39).

    Atomic: the archive is built in a same-directory temp file, fsynced,
    then `os.replace`d over `path` — a crash mid-write (exercised via the
    ``checkpoint.write`` fault point) leaves either the previous complete
    checkpoint or no file, never a torn archive at the final path.
    """
    from ..nn.graph.graph import ComputationGraph
    from ..nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        model_class = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_class = "ComputationGraph"
    else:
        raise ValueError(f"Cannot serialize {type(model).__name__}")
    model._check_init()

    meta = {
        "format_version": FORMAT_VERSION,
        "model_class": model_class,
        "dtype": np.dtype(model._dtype).name,
        "iteration": int(model.iteration),
        "epoch": int(model.epoch),
        "has_updater": bool(save_updater),
    }
    path = os.fspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            with zipfile.ZipFile(f, "w", zipfile.ZIP_DEFLATED) as zf:
                zf.writestr(CONFIG_ENTRY, model.conf.to_json())
                zf.writestr(META_ENTRY, json.dumps(meta))
                zf.writestr(PARAMS_ENTRY, _tree_to_npz_bytes(model.params_tree))
                # the bulk of the bytes are on disk but the central
                # directory is not: a kill here leaves a torn temp file,
                # which atomicity must keep away from the final path
                faults.fire("checkpoint.write")
                zf.writestr(STATE_ENTRY, _tree_to_npz_bytes(model.state_tree))
                if save_updater:
                    zf.writestr(UPDATER_ENTRY,
                                _tree_to_npz_bytes(model.opt_state))
                if model._rng is not None:
                    # the dropout key stream position: without it a resumed
                    # run's post-resume dropout masks diverge from an
                    # uninterrupted run
                    zf.writestr(RNG_ENTRY,
                                _tree_to_npz_bytes(jnp.asarray(model._rng)))
                if normalizer is not None:
                    zf.writestr(NORMALIZER_ENTRY, serde.to_json(normalizer))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # fsync the directory so the rename itself survives power loss
        try:
            dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # e.g. directories aren't fsync-able on some filesystems
    finally:
        try:
            os.unlink(tmp)
        except FileNotFoundError:
            pass


def validate_checkpoint(path: str, deep: bool = False) -> dict:
    """Up-front structural validation; returns the parsed metadata.

    Raises :class:`CheckpointCorruptError` naming the offending entry for
    anything unreadable; ``deep=True`` additionally CRC-checks every member
    (reads the whole archive — used by CheckpointManager before trusting a
    checkpoint, skipped on the restore path which reads everything anyway).
    """
    try:
        with zipfile.ZipFile(path, "r") as zf:
            names = set(zf.namelist())
            for entry in REQUIRED_ENTRIES:
                if entry not in names:
                    raise CheckpointCorruptError(
                        f"checkpoint {path!r}: missing required entry "
                        f"{entry!r} (truncated or not a model checkpoint)")
            if deep:
                bad = zf.testzip()
                if bad is not None:
                    raise CheckpointCorruptError(
                        f"checkpoint {path!r}: entry {bad!r} fails its CRC "
                        "(truncated or corrupt archive)")
            try:
                meta = json.loads(zf.read(META_ENTRY))
            except (ValueError, *_READ_ERRORS) as e:
                raise CheckpointCorruptError(
                    f"checkpoint {path!r}: entry {META_ENTRY!r} is "
                    f"unreadable ({e})") from e
    except zipfile.BadZipFile as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r}: not a readable ZIP archive ({e})") from e
    fv = meta.get("format_version")
    if not isinstance(fv, int) or not (1 <= fv <= FORMAT_VERSION):
        raise CheckpointCorruptError(
            f"checkpoint {path!r}: unsupported format_version {fv!r} in "
            f"{META_ENTRY!r} (this build reads versions 1..{FORMAT_VERSION})")
    if meta.get("model_class") not in ("MultiLayerNetwork",
                                       "ComputationGraph"):
        raise CheckpointCorruptError(
            f"checkpoint {path!r}: unknown model_class "
            f"{meta.get('model_class')!r} in {META_ENTRY!r}")
    return meta


def _read_entry(zf: zipfile.ZipFile, path: str, entry: str) -> bytes:
    try:
        return zf.read(entry)
    except _READ_ERRORS as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r}: entry {entry!r} is unreadable "
            f"({type(e).__name__}: {e})") from e


def restore_model(path: str, load_updater: bool = True):
    """Rebuild a network from a checkpoint (reference
    restoreMultiLayerNetwork/restoreComputationGraph:137+; model type is
    sniffed from metadata like ModelGuesser).

    Validates format_version and required entries up front and raises
    :class:`CheckpointCorruptError` for truncated/corrupt archives;
    array-vs-config mismatches still raise :class:`ValueError`.
    """
    from ..nn.conf.builders import MultiLayerConfiguration
    from ..nn.conf.graph_conf import ComputationGraphConfiguration
    from ..nn.graph.graph import ComputationGraph
    from ..nn.multilayer import MultiLayerNetwork

    meta = validate_checkpoint(path)
    with zipfile.ZipFile(path, "r") as zf:
        conf_json = _read_entry(zf, path, CONFIG_ENTRY).decode("utf-8")
        dtype = jnp.dtype(meta["dtype"])
        if meta["model_class"] == "MultiLayerNetwork":
            conf = MultiLayerConfiguration.from_json(conf_json)
            model = MultiLayerNetwork(conf).init(dtype=dtype)
        else:
            conf = ComputationGraphConfiguration.from_json(conf_json)
            model = ComputationGraph(conf).init(dtype=dtype)
        _load_state_from_zip(model, zf, path, meta, load_updater)
    return model


def _load_state_from_zip(model, zf: zipfile.ZipFile, path: str, meta: dict,
                         load_updater: bool) -> None:
    """Load params/state/updater/counters/RNG from an open checkpoint into
    an already-initialized model (shared by restore_model and in-place
    restore for auto-resume/rollback)."""
    model.params_tree = _npz_bytes_to_tree(
        _read_entry(zf, path, PARAMS_ENTRY), model.params_tree)
    model.state_tree = _npz_bytes_to_tree(
        _read_entry(zf, path, STATE_ENTRY), model.state_tree)
    names = zf.namelist()
    if load_updater and meta.get("has_updater") and UPDATER_ENTRY in names:
        model.opt_state = _npz_bytes_to_tree(
            _read_entry(zf, path, UPDATER_ENTRY), model.opt_state)
    model.iteration = meta.get("iteration", 0)
    model.epoch = meta.get("epoch", 0)
    if RNG_ENTRY in names and model._rng is not None:
        model._rng = _npz_bytes_to_tree(
            _read_entry(zf, path, RNG_ENTRY), jnp.asarray(model._rng))


def load_checkpoint_state(model, path: str, load_updater: bool = True) -> dict:
    """In-place restore: load a checkpoint's training state into an
    EXISTING initialized model of the same architecture (no rebuild, so
    precompiled dispatch tables and listeners survive). Returns the
    checkpoint metadata. Raises :class:`CheckpointCorruptError` for
    unreadable archives, :class:`ValueError` for architecture mismatches.
    """
    meta = validate_checkpoint(path)
    with zipfile.ZipFile(path, "r") as zf:
        _load_state_from_zip(model, zf, path, meta, load_updater)
    # any cached recurrent carry belongs to the pre-restore trajectory
    if hasattr(model, "_rnn_carry"):
        model._rnn_carry = None
    return meta


def restore_normalizer(path: str):
    with zipfile.ZipFile(path, "r") as zf:
        if NORMALIZER_ENTRY not in zf.namelist():
            return None
        return serde.from_json(zf.read(NORMALIZER_ENTRY).decode("utf-8"))


class ModelSerializer:
    """Reference-named facade (util/ModelSerializer.java API surface) over
    the module-level functions; the multi-host runner and user code use
    these names."""

    writeModel = write_model = staticmethod(save_model)
    restoreModel = staticmethod(restore_model)

    @staticmethod
    def restore_multi_layer_network(path: str, load_updater: bool = True):
        from ..nn.multilayer import MultiLayerNetwork
        model = restore_model(path, load_updater)
        if not isinstance(model, MultiLayerNetwork):
            raise ValueError(f"{path} holds a "
                             f"{type(model).__name__}, not a "
                             "MultiLayerNetwork")
        return model

    @staticmethod
    def restore_computation_graph(path: str, load_updater: bool = True):
        from ..nn.graph.graph import ComputationGraph
        model = restore_model(path, load_updater)
        if not isinstance(model, ComputationGraph):
            raise ValueError(f"{path} holds a "
                             f"{type(model).__name__}, not a "
                             "ComputationGraph")
        return model

    restoreMultiLayerNetwork = restore_multi_layer_network
    restoreComputationGraph = restore_computation_graph
