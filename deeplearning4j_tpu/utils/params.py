"""Flat parameter views over pytree params.

Reference parity: DL4J keeps ALL network parameters in one flat buffer with
per-layer views (MultiLayerNetwork.java:442-536 init/initGradientsView;
`params()` returns the flat vector). On TPU a flat buffer is an
anti-optimization — XLA lays out each tensor for the MXU — so the pytree is
the source of truth and these helpers materialize the flat view only at the
API boundary (checkpointing = coefficients.bin analog, `net.params()`,
parameter-averaging parity tests).

Ordering contract: layer index order, then insertion order of each layer's
param dict (W before b etc., matching each ParamInitializer's ordering),
row-major ('C') flattening per tensor.
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def num_params(params: Any) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def flatten_params(params: Any) -> Array:
    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate([jnp.ravel(p) for p in leaves])


def unflatten_params(template: Any, flat: Array) -> Any:
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out: List[Array] = []
    offset = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape))
        out.append(jnp.reshape(flat[offset:offset + n], leaf.shape).astype(leaf.dtype))
        offset += n
    if offset != flat.shape[0]:
        raise ValueError(
            f"Flat vector length {flat.shape[0]} != template size {offset}")
    return jax.tree_util.tree_unflatten(treedef, out)


def tree_copy(tree: Any) -> Any:
    """Deep-copy every array leaf. Load-bearing for buffer DONATION: the
    jitted train steps reuse params/opt/state buffers in place
    (donate_argnums), so any tree that crosses a network boundary (clone,
    transfer learning, early-stopping savers) MUST be copied here or its
    arrays die on the source net's next fit."""
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(jnp.copy, tree)
