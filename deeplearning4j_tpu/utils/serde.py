"""JSON serde for config dataclasses.

Reference parity: DL4J serializes every configuration (NeuralNetConfiguration,
MultiLayerConfiguration, ComputationGraphConfiguration, per-layer configs) to
JSON/YAML via a Jackson ObjectMapper with polymorphic subtype registration
(reference: deeplearning4j-nn nn/conf/NeuralNetConfiguration.java:126-127 and
nn/conf/ReflectionsHelper.java classpath scanning for custom layers).

TPU-native redesign: configs are plain Python dataclasses registered in an
explicit registry (no classpath scanning; `register` is the extension point
for custom layers/vertices/activations). `to_dict` emits an `"@class"` tag per
registered object so JSON round-trips reconstruct the exact subtype, matching
the behavioral contract tested by the reference's
nn/conf/NeuralNetConfigurationTest JSON round-trip tests.
"""
from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Callable, Dict, Type

_REGISTRY: Dict[str, Type] = {}
_ENUM_REGISTRY: Dict[str, Type] = {}


def register(cls=None, *, name: str | None = None):
    """Class decorator: make a dataclass (or Enum) JSON round-trippable.

    This is the custom-layer extension mechanism (the analog of DL4J's
    `NeuralNetConfiguration.registerSubtypes` / Reflections classpath scan).
    """

    def wrap(c):
        key = name or c.__name__
        if isinstance(c, type) and issubclass(c, enum.Enum):
            _ENUM_REGISTRY[key] = c
        else:
            _REGISTRY[key] = c
        c.__serde_name__ = key
        return c

    if cls is None:
        return wrap
    return wrap(cls)


def registered_class(name: str) -> Type:
    if name in _REGISTRY:
        return _REGISTRY[name]
    raise KeyError(
        f"No config class registered under {name!r}. Custom classes must be "
        f"decorated with @serde.register before deserialization."
    )


def to_dict(obj: Any) -> Any:
    """Recursively convert a registered dataclass tree to JSON-able data."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return {"@enum": type(obj).__serde_name__, "value": obj.name}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        # vars(), not getattr: a subclass INHERITS its parent's
        # __serde_name__, and serializing it under the parent's tag would
        # silently reconstruct the wrong class (dropping subclass fields)
        name = vars(type(obj)).get("__serde_name__")
        if name is None:
            raise TypeError(
                f"{type(obj).__name__} is a dataclass but not @serde.register'd"
            )
        out = {"@class": name}
        for f in dataclasses.fields(obj):
            if not f.metadata.get("serde_skip", False):
                out[f.name] = to_dict(getattr(obj, f.name))
        return out
    if isinstance(obj, (list, tuple)):
        return [to_dict(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): to_dict(v) for k, v in obj.items()}
    if callable(obj):
        raise TypeError(
            f"Cannot serialize callable {obj!r}; use a named/registered config "
            f"object instead of a bare function for round-trippable configs."
        )
    raise TypeError(f"Cannot serialize {type(obj)!r}")


def from_dict(data: Any) -> Any:
    if isinstance(data, dict):
        if "@enum" in data:
            return _ENUM_REGISTRY[data["@enum"]][data["value"]]
        if "@class" in data:
            cls = registered_class(data["@class"])
            field_names = {f.name for f in dataclasses.fields(cls)}
            kwargs = {
                k: from_dict(v)
                for k, v in data.items()
                if k != "@class" and k in field_names
            }
            return cls(**kwargs)
        return {k: from_dict(v) for k, v in data.items()}
    if isinstance(data, list):
        return [from_dict(x) for x in data]
    return data


def to_json(obj: Any, indent: int | None = 2) -> str:
    return json.dumps(to_dict(obj), indent=indent)


def from_json(s: str) -> Any:
    return from_dict(json.loads(s))
