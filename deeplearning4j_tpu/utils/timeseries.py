"""Time-series / sequence utilities.

Reference parity: util/TimeSeriesUtils.java (3d↔2d reshapes, time
reversal incl. masked variants, moving average), util/
MovingWindowMatrix.java (sliding sub-matrices), util/Viterbi.java
(most-likely hidden state sequence).

TPU-native note: Viterbi runs as a jitted lax.scan (max-product forward
pass + host backtrace) — sequence decoding shaped for the accelerator,
not a Python loop over timesteps.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# --------------------------------------------------------- TimeSeriesUtils
def reshape_3d_to_2d(arr) -> np.ndarray:
    """[batch, time, features] → [batch*time, features] (reference
    TimeSeriesUtils.reshape3dTo2d; NHWC-era layout, time-major rows)."""
    arr = np.asarray(arr)
    if arr.ndim != 3:
        raise ValueError(f"need rank 3, got {arr.shape}")
    return arr.reshape(-1, arr.shape[-1])

def reshape_2d_to_3d(arr, batch: int) -> np.ndarray:
    """Inverse of reshape_3d_to_2d (reference reshape2dTo3d)."""
    arr = np.asarray(arr)
    if arr.shape[0] % batch:
        raise ValueError(f"{arr.shape[0]} rows not divisible by batch "
                         f"{batch}")
    return arr.reshape(batch, arr.shape[0] // batch, arr.shape[-1])


def reverse_time_series(arr, mask=None) -> np.ndarray:
    """Reverse along time; with a [batch, time] mask, only the VALID
    prefix of each row reverses and padding stays in place (reference
    reverseTimeSeries(INDArray, mask))."""
    arr = np.asarray(arr)
    if mask is None:
        return arr[:, ::-1].copy()
    mask = np.asarray(mask)
    out = arr.copy()
    for b in range(arr.shape[0]):
        n = int(mask[b].sum())
        out[b, :n] = arr[b, :n][::-1]
    return out


def moving_average(arr, window: int) -> np.ndarray:
    """Trailing moving average over the last axis (reference
    TimeSeriesUtils.movingAverage): output length T - window + 1."""
    arr = np.asarray(arr, np.float64)
    if window < 1 or window > arr.shape[-1]:
        raise ValueError(f"window {window} out of range for {arr.shape}")
    c = np.cumsum(np.concatenate(
        [np.zeros(arr.shape[:-1] + (1,)), arr], axis=-1), axis=-1)
    return (c[..., window:] - c[..., :-window]) / window


def moving_window_matrix(matrix, window_rows: int,
                         add_rotate: bool = False) -> np.ndarray:
    """All vertical sliding windows of a 2-D matrix → [n_windows,
    window_rows, cols] (reference MovingWindowMatrix.windows();
    add_rotate appends the row-rotated variants like addRotate)."""
    m = np.asarray(matrix)
    if m.ndim != 2:
        raise ValueError("need a 2-D matrix")
    n = m.shape[0] - window_rows + 1
    if n <= 0:
        raise ValueError(f"window_rows {window_rows} > rows {m.shape[0]}")
    wins = np.stack([m[i:i + window_rows] for i in range(n)])
    if add_rotate:
        wins = np.concatenate([wins, np.stack(
            [np.roll(w, -1, axis=0) for w in wins])])
    return wins


# ------------------------------------------------------------------ Viterbi
@functools.partial(jax.jit, static_argnames=())
def _viterbi_forward(log_init, log_trans, log_emit):
    """Max-product forward pass: returns (best path scores [T, S],
    argmax backpointers [T, S])."""

    def step(prev_scores, emit_t):
        cand = prev_scores[:, None] + log_trans  # [S, S] from→to
        best_prev = jnp.argmax(cand, axis=0)
        scores = jnp.max(cand, axis=0) + emit_t
        return scores, (scores, best_prev)

    first = log_init + log_emit[0]
    _, (scores, back) = jax.lax.scan(step, first, log_emit[1:])
    scores = jnp.concatenate([first[None], scores])
    return scores, back


class Viterbi:
    """Most-likely hidden state sequence (reference util/Viterbi.java,
    generalized from its binary-state decoder to any HMM):
    decode(observations) over (initial, transition, emission) log-probs."""

    def __init__(self, initial, transition, emission):
        """initial [S], transition [S, S] (row from→to), emission [S, O] —
        probabilities (normalized per row); stored as logs."""
        eps = 1e-30
        self.log_init = jnp.log(jnp.asarray(initial, jnp.float32) + eps)
        self.log_trans = jnp.log(jnp.asarray(transition, jnp.float32) + eps)
        self.log_emit = jnp.log(jnp.asarray(emission, jnp.float32) + eps)

    def decode(self, observations) -> Tuple[np.ndarray, float]:
        """→ (state sequence [T], log-likelihood of the best path)."""
        obs = np.asarray(observations, np.int64)
        if obs.size == 0:
            return np.empty(0, np.int64), 0.0
        n_obs = self.log_emit.shape[1]
        if obs.min() < 0 or obs.max() >= n_obs:
            # jnp gather would silently CLAMP out-of-range indices
            raise ValueError(f"observation out of range [0, {n_obs})")
        emit_seq = self.log_emit.T[obs]  # [T, S]
        scores, back = _viterbi_forward(self.log_init, self.log_trans,
                                        jnp.asarray(emit_seq))
        scores = np.asarray(scores)
        back = np.asarray(back)
        T = obs.shape[0]
        path = np.empty(T, np.int64)
        path[-1] = int(np.argmax(scores[-1]))
        for t in range(T - 2, -1, -1):
            path[t] = back[t, path[t + 1]]
        return path, float(scores[-1].max())
