// Native host-ETL kernels for deeplearning4j_tpu.
//
// Reference parity: the reference keeps its hot host-side paths native —
// libnd4j does buffer math behind JNI, JavaCPP binds HDF5 for model
// import, and the MNIST/CSV readers feed DataSets through JVM-native IO.
// On TPU the device math belongs to XLA, but host ETL (the feed side of
// the async prefetch pipeline) still benefits from native code: pixel
// scaling/layout conversion and CSV float parsing dominate host time
// when the device step is fast.
//
// Build: make -C native   (g++ -O3 -shared -fPIC; no dependencies)
// Python binding: ctypes (deeplearning4j_tpu/native_etl.py); every entry
// point is plain C so no name mangling or pybind is involved.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>
#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// uint8 pixels -> float32 in [min_range, max_range] (the
// ImagePreProcessingScaler hot loop; dst may be the training batch
// buffer directly). OpenMP over chunks: this is a pure streaming loop,
// so threads split the bandwidth.
void u8_to_f32_scaled(const uint8_t* src, float* dst, int64_t n,
                      float max_pixel, float min_range, float max_range) {
    const float span = (max_range - min_range) / max_pixel;
#pragma omp parallel for schedule(static) if (n > 1 << 16)
    for (int64_t i = 0; i < n; ++i) {
        dst[i] = static_cast<float>(src[i]) * span + min_range;
    }
}

// float32 standardize in place: (x - mean[c]) / std[c] over trailing
// feature axis of size c_len (NormalizerStandardize.transform hot loop).
void f32_standardize(float* data, int64_t rows, int64_t c_len,
                     const float* mean, const float* stddev) {
#pragma omp parallel for schedule(static) if (rows * c_len > 1 << 16)
    for (int64_t r = 0; r < rows; ++r) {
        float* row = data + r * c_len;
        for (int64_t c = 0; c < c_len; ++c) {
            row[c] = (row[c] - mean[c]) / stddev[c];
        }
    }
}

// Parse a delimiter-separated buffer of ASCII floats. Returns the number
// parsed (<= max_out). Newlines count as delimiters; empty fields skip.
// (CSVRecordReader's inner loop without Python string objects.)
int64_t parse_csv_floats(const char* buf, int64_t len, char delimiter,
                         float* out, int64_t max_out) {
    int64_t count = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end && count < max_out) {
        // skip delimiters/newlines/spaces
        while (p < end && (*p == delimiter || *p == '\n' || *p == '\r' ||
                           *p == ' ')) {
            ++p;
        }
        if (p >= end) break;
        char* next = nullptr;
        float v = strtof(p, &next);
        if (next == p) {  // unparseable token: skip to next delimiter
            while (p < end && *p != delimiter && *p != '\n') ++p;
            continue;
        }
        out[count++] = v;
        p = next;
    }
    return count;
}

// Gather rows: out[i] = table[idx[i]] for embedding-style host-side
// assembly (word2vec negative-table sampling batches).
void gather_rows_f32(const float* table, const int32_t* idx, float* out,
                     int64_t n_rows, int64_t dim) {
    for (int64_t i = 0; i < n_rows; ++i) {
        std::memcpy(out + i * dim, table + static_cast<int64_t>(idx[i]) * dim,
                    dim * sizeof(float));
    }
}

// One-hot encode int labels into a zeroed float32 buffer [n, classes].
void one_hot_f32(const int32_t* labels, float* out, int64_t n,
                 int64_t classes) {
    std::memset(out, 0, sizeof(float) * n * classes);
    for (int64_t i = 0; i < n; ++i) {
        int64_t c = labels[i];
        if (c >= 0 && c < classes) {
            out[i * classes + c] = 1.0f;
        }
    }
}

// Bilinear resize of an HWC uint8 image (ImageRecordReader's
// scale-to-network-input step; half-pixel-center sampling like OpenCV's
// INTER_LINEAR, which is what DataVec's NativeImageLoader uses).
void u8_resize_bilinear_hwc(const uint8_t* src, int64_t h, int64_t w,
                            int64_t c, uint8_t* dst, int64_t oh,
                            int64_t ow) {
    const float sy = static_cast<float>(h) / static_cast<float>(oh);
    const float sx = static_cast<float>(w) / static_cast<float>(ow);
    // precompute the column sample positions/weights once per image
    std::vector<int64_t> x0s(ow), x1s(ow);
    std::vector<float> wxs(ow);
    for (int64_t x = 0; x < ow; ++x) {
        float fx = (static_cast<float>(x) + 0.5f) * sx - 0.5f;
        if (fx < 0) fx = 0;
        int64_t x0 = static_cast<int64_t>(fx);
        if (x0 > w - 1) x0 = w - 1;
        x0s[x] = x0;
        x1s[x] = x0 + 1 < w ? x0 + 1 : w - 1;
        wxs[x] = fx - static_cast<float>(x0);
    }
#pragma omp parallel for schedule(static) if (oh * ow * c > 1 << 15)
    for (int64_t y = 0; y < oh; ++y) {
        float fy = (static_cast<float>(y) + 0.5f) * sy - 0.5f;
        if (fy < 0) fy = 0;
        int64_t y0 = static_cast<int64_t>(fy);
        if (y0 > h - 1) y0 = h - 1;
        int64_t y1 = y0 + 1 < h ? y0 + 1 : h - 1;
        const float wy = fy - static_cast<float>(y0);
        const uint8_t* row0 = src + y0 * w * c;
        const uint8_t* row1 = src + y1 * w * c;
        uint8_t* drow = dst + y * ow * c;
        for (int64_t x = 0; x < ow; ++x) {
            const float wx = wxs[x];
            const uint8_t* p00 = row0 + x0s[x] * c;
            const uint8_t* p01 = row0 + x1s[x] * c;
            const uint8_t* p10 = row1 + x0s[x] * c;
            const uint8_t* p11 = row1 + x1s[x] * c;
            uint8_t* d = drow + x * c;
            for (int64_t ch = 0; ch < c; ++ch) {
                const float top = p00[ch] + (p01[ch] - p00[ch]) * wx;
                const float bot = p10[ch] + (p11[ch] - p10[ch]) * wx;
                const float v = top + (bot - top) * wy;
                d[ch] = static_cast<uint8_t>(v + 0.5f);
            }
        }
    }
}

// Cap this thread's OpenMP team size. Worker threads that already
// parallelize at the image level (ImageRecordReaderDataSetIterator's
// pool) call this with 1 so the per-row pragmas don't nest a second
// parallelism layer and oversubscribe the host.
void etl_set_omp_threads(int n) {
#ifdef _OPENMP
    omp_set_num_threads(n > 0 ? n : 1);
#else
    (void)n;
#endif
}

int etl_abi_version() { return 2; }

}  // extern "C"
