// Native host-ETL kernels for deeplearning4j_tpu.
//
// Reference parity: the reference keeps its hot host-side paths native —
// libnd4j does buffer math behind JNI, JavaCPP binds HDF5 for model
// import, and the MNIST/CSV readers feed DataSets through JVM-native IO.
// On TPU the device math belongs to XLA, but host ETL (the feed side of
// the async prefetch pipeline) still benefits from native code: pixel
// scaling/layout conversion and CSV float parsing dominate host time
// when the device step is fast.
//
// Build: make -C native   (g++ -O3 -shared -fPIC; no dependencies)
// Python binding: ctypes (deeplearning4j_tpu/native_etl.py); every entry
// point is plain C so no name mangling or pybind is involved.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

// uint8 pixels -> float32 in [min_range, max_range] (the
// ImagePreProcessingScaler hot loop; dst may be the training batch
// buffer directly).
void u8_to_f32_scaled(const uint8_t* src, float* dst, int64_t n,
                      float max_pixel, float min_range, float max_range) {
    const float span = (max_range - min_range) / max_pixel;
    for (int64_t i = 0; i < n; ++i) {
        dst[i] = static_cast<float>(src[i]) * span + min_range;
    }
}

// float32 standardize in place: (x - mean[c]) / std[c] over trailing
// feature axis of size c_len (NormalizerStandardize.transform hot loop).
void f32_standardize(float* data, int64_t rows, int64_t c_len,
                     const float* mean, const float* stddev) {
    for (int64_t r = 0; r < rows; ++r) {
        float* row = data + r * c_len;
        for (int64_t c = 0; c < c_len; ++c) {
            row[c] = (row[c] - mean[c]) / stddev[c];
        }
    }
}

// Parse a delimiter-separated buffer of ASCII floats. Returns the number
// parsed (<= max_out). Newlines count as delimiters; empty fields skip.
// (CSVRecordReader's inner loop without Python string objects.)
int64_t parse_csv_floats(const char* buf, int64_t len, char delimiter,
                         float* out, int64_t max_out) {
    int64_t count = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end && count < max_out) {
        // skip delimiters/newlines/spaces
        while (p < end && (*p == delimiter || *p == '\n' || *p == '\r' ||
                           *p == ' ')) {
            ++p;
        }
        if (p >= end) break;
        char* next = nullptr;
        float v = strtof(p, &next);
        if (next == p) {  // unparseable token: skip to next delimiter
            while (p < end && *p != delimiter && *p != '\n') ++p;
            continue;
        }
        out[count++] = v;
        p = next;
    }
    return count;
}

// Gather rows: out[i] = table[idx[i]] for embedding-style host-side
// assembly (word2vec negative-table sampling batches).
void gather_rows_f32(const float* table, const int32_t* idx, float* out,
                     int64_t n_rows, int64_t dim) {
    for (int64_t i = 0; i < n_rows; ++i) {
        std::memcpy(out + i * dim, table + static_cast<int64_t>(idx[i]) * dim,
                    dim * sizeof(float));
    }
}

// One-hot encode int labels into a zeroed float32 buffer [n, classes].
void one_hot_f32(const int32_t* labels, float* out, int64_t n,
                 int64_t classes) {
    std::memset(out, 0, sizeof(float) * n * classes);
    for (int64_t i = 0; i < n; ++i) {
        int64_t c = labels[i];
        if (c >= 0 && c < classes) {
            out[i * classes + c] = 1.0f;
        }
    }
}

int etl_abi_version() { return 1; }

}  // extern "C"
