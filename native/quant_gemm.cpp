// Native int8 GEMM for the quantized serving path.
//
// XLA's CPU backend (jaxlib 0.4.36) has no int8 dot emitter: an s8xs8
// dot_general materializes an s32 copy of the weight operand and runs
// the f32-style loop over it (~0.2x fp32 — see docs/design.md
// "Quantized serving"). This rig's Xeon has AVX512-VNNI, whose
// vpdpbusd does 64 u8xs8 MACs per instruction, so the honest way to an
// int8 serving win on CPU is the same route the repo already takes for
// host ETL: a tiny native library behind ctypes, probed at runtime and
// A/B'd against the XLA path before dispatch ships it.
//
// Contract (quant_matmul callers): out[b,n] = sum_k x[b,k] * w[n,k],
// x s8 [B,K] row-major, w s8 [N,K] row-major (weights stored transposed
// so each output channel is a unit-stride row), out s32 [B,N].
//
// vpdpbusd is unsigned x signed. We bias the WEIGHT operand on the fly
// (w_u8 = w ^ 0x80 == w + 128 in biased u8) and subtract the exact
// correction 128 * rowsum(x[b,:]) afterwards — no extra sidecar data
// and no precision loss (all-integer arithmetic).
//
// ISA safety: the base translation unit compiles with the Makefile's
// -mtune-only flags; the VNNI kernel lives behind a gcc target
// attribute and is only ever called after __builtin_cpu_supports
// checks, so the shared .so cannot SIGILL on an older host (same rule
// as etl.cpp's -mtune note). A portable scalar kernel is the fallback.

#include <cstdint>
#include <immintrin.h>
#ifdef _OPENMP
#include <omp.h>
#endif

// Optional XLA typed-FFI handler (jaxlib ships the header-only API
// under jaxlib/include — the Makefile probes for it and defines
// DL4JTPU_WITH_XLA_FFI when found). The ctypes int8_gemm entry costs
// ~1ms per call through jax.pure_callback (python trampoline + operand
// marshalling) — an order of magnitude MORE than the GEMM itself at
// serving shapes — so the serving path registers this handler as a
// real XLA custom call instead: XLA hands the kernel raw buffer
// pointers in-process and the trampoline disappears. The plain ctypes
// entry stays for probing, tests, and hosts without the headers.

namespace {

#if defined(__x86_64__) && defined(__GNUC__)
#define DL4JTPU_VNNI_BUILT 1

__attribute__((target("avx512f,avx512bw,avx512vl,avx512vnni")))
void gemm_vnni(const int8_t* x, const int8_t* w, int32_t* out,
               int64_t B, int64_t K, int64_t N) {
    const __m512i bias = _mm512_set1_epi8(static_cast<char>(0x80));
    const int64_t ktail = K % 64;
    const __mmask64 tmask =
        ktail ? ((~__mmask64{0}) >> (64 - ktail)) : 0;
    // Block over batch rows so each streamed weight vector feeds up to
    // 8 accumulators: w (the big operand) crosses memory ceil(B/8)
    // times while x (tiny, L2-resident) is re-read per channel.
    for (int64_t b0 = 0; b0 < B; b0 += 8) {
        const int bb = static_cast<int>(B - b0 < 8 ? B - b0 : 8);
        int32_t corr[8];
        for (int j = 0; j < bb; ++j) {
            const int8_t* xr = x + (b0 + j) * K;
            int32_t s = 0;
            for (int64_t k = 0; k < K; ++k) s += xr[k];
            corr[j] = 128 * s;
        }
#pragma omp parallel for schedule(static) if (N * K > (int64_t{1} << 18))
        for (int64_t n = 0; n < N; ++n) {
            const int8_t* wr = w + n * K;
            __m512i acc[8];
            for (int j = 0; j < bb; ++j) acc[j] = _mm512_setzero_si512();
            int64_t k = 0;
            for (; k + 64 <= K; k += 64) {
                const __m512i wu = _mm512_xor_si512(
                    _mm512_loadu_si512(wr + k), bias);
                for (int j = 0; j < bb; ++j) {
                    const __m512i xv = _mm512_loadu_si512(
                        x + (b0 + j) * K + k);
                    acc[j] = _mm512_dpbusd_epi32(acc[j], wu, xv);
                }
            }
            if (ktail) {
                const __m512i wu = _mm512_xor_si512(
                    _mm512_maskz_loadu_epi8(tmask, wr + k), bias);
                for (int j = 0; j < bb; ++j) {
                    const __m512i xv = _mm512_maskz_loadu_epi8(
                        tmask, x + (b0 + j) * K + k);
                    acc[j] = _mm512_dpbusd_epi32(acc[j], wu, xv);
                }
            }
            for (int j = 0; j < bb; ++j) {
                out[(b0 + j) * N + n] =
                    _mm512_reduce_add_epi32(acc[j]) - corr[j];
            }
        }
    }
}
#endif  // __x86_64__ && __GNUC__

void gemm_scalar(const int8_t* x, const int8_t* w, int32_t* out,
                 int64_t B, int64_t K, int64_t N) {
#pragma omp parallel for schedule(static) \
    if (B * N * K > (int64_t{1} << 18))
    for (int64_t b = 0; b < B; ++b) {
        const int8_t* xr = x + b * K;
        for (int64_t n = 0; n < N; ++n) {
            const int8_t* wr = w + n * K;
            int32_t s = 0;
            for (int64_t k = 0; k < K; ++k) {
                s += static_cast<int32_t>(xr[k])
                     * static_cast<int32_t>(wr[k]);
            }
            out[b * N + n] = s;
        }
    }
}

}  // namespace

extern "C" {

// Bump on any signature change; the ctypes loader rebuilds once on
// mismatch (same protocol as etl_abi_version). v2: XLA FFI handler.
int32_t quant_abi_version() { return 2; }

// 1 when the XLA typed-FFI handler is compiled into this .so (the
// Python side falls back to jax.pure_callback when it is not).
int32_t int8_gemm_ffi_available() {
#ifdef DL4JTPU_WITH_XLA_FFI
    return 1;
#else
    return 0;
#endif
}

// 1 when the AVX512-VNNI kernel is compiled in AND the running CPU
// supports it; the Python probe reports which path a measurement used.
int32_t int8_gemm_vnni_available() {
#ifdef DL4JTPU_VNNI_BUILT
    return __builtin_cpu_supports("avx512f")
           && __builtin_cpu_supports("avx512bw")
           && __builtin_cpu_supports("avx512vl")
           && __builtin_cpu_supports("avx512vnni") ? 1 : 0;
#else
    return 0;
#endif
}

// out[b,n] = sum_k x[b,k] * w[n,k]; picks VNNI when the CPU has it.
void int8_gemm(const int8_t* x, const int8_t* w, int32_t* out,
               int64_t B, int64_t K, int64_t N) {
#ifdef DL4JTPU_VNNI_BUILT
    if (int8_gemm_vnni_available()) {
        gemm_vnni(x, w, out, B, K, N);
        return;
    }
#endif
    gemm_scalar(x, w, out, B, K, N);
}

}  // extern "C"

#ifdef DL4JTPU_WITH_XLA_FFI
#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

static ffi::Error Int8GemmFfiImpl(ffi::Buffer<ffi::S8> x,
                                  ffi::Buffer<ffi::S8> w,
                                  ffi::ResultBuffer<ffi::S32> out) {
    const auto xd = x.dimensions();
    const auto wd = w.dimensions();
    if (xd.size() != 2 || wd.size() != 2 || xd[1] != wd[1]) {
        return ffi::Error::InvalidArgument(
            "int8_gemm wants x[B,K] and w[N,K] (weights transposed)");
    }
    int8_gemm(x.typed_data(), w.typed_data(), out->typed_data(),
              xd[0], xd[1], wd[0]);
    return ffi::Error::Success();
}

// Exported handler symbol; native_quant.py wraps it in a PyCapsule and
// registers it as the "dl4jtpu_int8_gemm" custom-call target.
XLA_FFI_DEFINE_HANDLER_SYMBOL(
    dl4jtpu_int8_gemm_ffi, Int8GemmFfiImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::S8>>()
        .Arg<ffi::Buffer<ffi::S8>>()
        .Ret<ffi::Buffer<ffi::S32>>());
#endif  // DL4JTPU_WITH_XLA_FFI
