#!/usr/bin/env bash
# CI entry point (reference §2.12 runtests.sh role): build the optional
# native ETL library, then run the suite on the virtual 8-device CPU mesh
# (tests/conftest.py forces the platform), mirroring how the reference's
# Travis loop ran `mvn clean test` per matrix entry.
set -euo pipefail
cd "$(dirname "$0")"

make -C native || echo "native ETL build unavailable; numpy fallbacks"

python -m pytest tests/ -q "$@"
