#!/usr/bin/env bash
# CI entry point (reference §2.12 runtests.sh role): build the optional
# native ETL library, then run the suite on the virtual 8-device CPU mesh
# (tests/conftest.py forces the platform), mirroring how the reference's
# Travis loop ran `mvn clean test` per matrix entry.
set -euo pipefail
cd "$(dirname "$0")"

make -C native || echo "native ETL build unavailable; numpy fallbacks"

# jaxlint gate (docs/static_analysis.md): AST analysis of the whole
# package against the committed analysis/baseline.json. Fails fast on
# any NEW trace-purity / host-sync / recompile / donation / lock
# finding — before spending minutes on the pytest suite.
JAX_PLATFORMS=cpu python tests/smoke_analysis.py

# Attention-kernel smoke (docs/perf_attention.md): interpret-mode fwd+bwd
# parity of the fused Pallas flash kernel vs dense_attention, plus the
# pallas/blockwise/dense dispatch fallback contract off-TPU (no crash,
# counter incremented, one-shot warning). Cheap (seconds) — gates before
# the suite like the jaxlint step.
JAX_PLATFORMS=cpu python tests/smoke_attention.py

# Pooling + fusion smoke (docs/perf_googlenet.md round 6): mask max-pool
# backward vs select-and-scatter autodiff, depthwise-conv avg pool vs
# reduce_window, the pooling_impl dispatch contract, and the sibling-
# conv fusion pass bitwise-forward on an initialized graph. Seconds —
# gates before the suite like the attention smoke.
JAX_PLATFORMS=cpu python tests/smoke_pooling.py

# Packed-varlen smoke (docs/perf_data_pipeline.md §PackToBucket, ISSUE
# 13): segment-masked flash kernel parity in interpret mode, the
# first-fit packing arithmetic, packed-score == unpacked-score
# exactness on a tiny net, and the packing metric families. Seconds.
JAX_PLATFORMS=cpu python tests/smoke_packing.py

python -m pytest tests/ -q "$@"

# Observability smoke (docs/observability.md): a real 2-epoch fit with
# span tracing on, then scrape GET /metrics off a live UIServer and
# assert train_iterations_total is nonzero. Fails the CI run if the
# registry, the endpoint, or the trace ring regresses end-to-end.
JAX_PLATFORMS=cpu python tests/smoke_observability.py

# Compile-cache smoke (docs/perf_compile_cache.md): run the tiny lenet
# bench twice against one temp persistent-cache dir and assert the
# second process reports cache HITS (warm start from disk, no XLA
# recompile) with both runs under the wall ceiling.
JAX_PLATFORMS=cpu python tests/smoke_compile_cache.py

# Resilience smoke (docs/robustness.md): SIGKILL a fitting child
# mid-checkpoint-write via the checkpoint.write fault point, auto-resume
# in a second process, and assert bitwise-identical params vs an
# uninterrupted same-seed control run.
JAX_PLATFORMS=cpu python tests/smoke_resilience.py

# Serving smoke (docs/serving.md): warmup a gateway, drive concurrent
# HTTP /predict traffic through a live checkpoint hot-swap, and assert
# zero dropped/errored requests, post-swap predictions bitwise from the
# new checkpoint, ZERO XLA compiles after warmup, and the serving
# metric families on the scrape surface.
JAX_PLATFORMS=cpu python tests/smoke_serving.py

# Serving chaos smoke (docs/serving.md §resilience): same gateway under
# a deterministic 20% serve.forward failure storm with an aggressive
# circuit breaker — every response typed (ok / batch_failed /
# breaker_open / shed), the breaker opens and recovers, zero compiles
# after warmup, zero hung requests (hard in-process alarm).
JAX_PLATFORMS=cpu python tests/smoke_chaos_serving.py

# Multi-model serving smoke (docs/serving.md §multi-model): three
# same-geometry heads fused into ONE channel-concatenated forward plus
# a batch-tier independent model, concurrent per-member HTTP traffic
# through a live PER-MEMBER hot-swap — all member requests 200, zero
# compiles after warmup, batch tier only ever sheds TYPED, starvation
# counter frozen without queued work. Hard signal.alarm guard.
JAX_PLATFORMS=cpu python tests/smoke_multimodel.py

# Request flight-recorder smoke (docs/observability.md §request flight
# recorder): recorder armed via env flag, concurrent HTTP through a
# fused pair + packed-admission model — every 200 response embeds a
# trace with monotonic non-overlapping phases summing to wall within
# 10%, zero compiles after warmup, and the exemplar ring captures
# EXACTLY the one chaos-delayed request with the delay attributed to
# the device phase. Hard signal.alarm guard.
JAX_PLATFORMS=cpu python tests/smoke_request_trace.py

# Serving control-loop smoke (docs/observability.md §"The serving
# control loop"): a live gateway with a deliberately mis-tuned linger
# under a tight tier SLO, AutoTuner at fast cadence, a batch-tier
# flood joining mid-run — >= 1 schema-valid ledgered move, zero
# guardrail violations, the linger measurably tightened, /debug/tuner
# rendering the decision trail over HTTP, and no freeze on a clean
# run. Hard signal.alarm guard.
JAX_PLATFORMS=cpu python tests/smoke_autotuner.py

# Cluster-health smoke (docs/robustness.md §cluster-health): fake-clock
# watchdog transitions (PeerLost/Desync), typed barrier timeout, and a
# real SIGTERM'd child writing a grace checkpoint then resuming
# bitwise-identically — under a hard signal.alarm so a watchdog
# regression can never wedge the gate itself.
JAX_PLATFORMS=cpu python tests/smoke_cluster_health.py

# Quantized hot-swap smoke (docs/serving.md §quantized): drive
# concurrent in-process traffic through a live `swap(quantize="int8")`
# promotion — zero non-typed failures, zero compiles after the
# quantized warm, post-swap drift within the canary budget, the
# precision="int8" label on entry/gauge/scrape — then a tight-budget
# gateway where the SAME swap canary-rejects, bumps the
# canary_rejected{precision="int8"} counter, and keeps serving the old
# fp32 tree bitwise. Canary both ways, one gate.
JAX_PLATFORMS=cpu python tests/smoke_quant_swap.py

# Decode smoke (docs/serving.md §decode): a gateway serving BOTH decode
# families (paged-KV transformer + streaming LSTM) under concurrent
# mixed-length HTTP /generate traffic — every response token-exact vs
# the naive full-recompute reference, typed 400/404 chain, a
# serve.decode_step chaos window isolated to exactly one rider with KV
# blocks drained, ZERO compiles after warmup, decode metric families
# scraped. Hard signal.alarm guard.
JAX_PLATFORMS=cpu python tests/smoke_decode.py

# Bench scoreboard smoke (docs/observability.md §bench-scoreboard): wedge
# a real bench child mid-measurement via the bench.child delay fault and
# assert the fail-safe plane holds — exit 0, the artifact parses with
# degraded: true rows and the registry snapshot embedded, and the ledger
# row is schema-valid. Under a hard signal.alarm like the chaos smokes.
JAX_PLATFORMS=cpu python tests/smoke_scoreboard.py

# Replica federation smoke (docs/serving.md §"Replica federation"): a
# front-end with two spawned replica subprocesses over real HTTP, a
# predict storm, a SIGKILL of one replica mid-traffic — every response
# 200 or typed, the dead replica evicted with the failover counters
# fired, the survivor still answering, every federation metric family
# in the /metrics scrape. Hard signal.alarm guard.
JAX_PLATFORMS=cpu python tests/smoke_federation.py
