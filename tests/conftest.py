"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's "distributed without a cluster" strategy (Spark tests
run local[N] in-JVM, BaseSparkTest.java:89): multi-chip sharding is exercised
on N virtual CPU devices via --xla_force_host_platform_device_count, so the
full tp/dp test matrix runs on any host. Real-TPU benchmarking happens via
bench.py, not the test suite.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

import pytest  # noqa: E402


@pytest.fixture
def rng_key():
    return jax.random.PRNGKey(0)
