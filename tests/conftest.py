"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's "distributed without a cluster" strategy (Spark tests
run local[N] in-JVM, BaseSparkTest.java:89): multi-chip sharding is exercised
on N virtual CPU devices via --xla_force_host_platform_device_count, so the
full tp/dp test matrix runs on any host. Real-TPU benchmarking happens via
bench.py, not the test suite.

Gotcha (learned the hard way): a sitecustomize hook may import jax and
register an accelerator plugin BEFORE this file runs, making JAX_PLATFORMS
env vars a no-op. jax.config.update after import still works because backend
initialization is lazy — and we hard-assert the device count so a silent
single-device fallback can never fake a passing distributed suite again.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (may already be imported by sitecustomize)

jax.config.update("jax_platforms", "cpu")
assert jax.device_count() == 8, (
    f"Test suite requires the 8-device virtual CPU mesh, got "
    f"{jax.devices()} — platform forcing failed")

import pytest  # noqa: E402


@pytest.fixture
def rng_key():
    return jax.random.PRNGKey(0)
