"""Worker for the kill-and-resume elastic test.

    python elastic_worker.py <pid> <nproc> <port> <ckpt_dir> <crash_at>

Trains a 2-process MLN with auto-checkpointing every 2 steps. When
crash_at >= 0, process 1 hard-exits (os._exit — no cleanup, simulating
preemption) the moment model.iteration reaches crash_at; the job is
then restarted by the test with crash_at=-1 and must auto-resume from
the newest checkpoint to the same final parameters as an uninterrupted
run. Deterministic: the crash point is a fixed step count, data order
is fixed, and checkpoints are atomic."""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=2"

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
ckpt_dir, crash_at = sys.argv[4], int(sys.argv[5])

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (DenseLayer, InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration, Nesterovs,
                                OutputLayer)
from deeplearning4j_tpu.parallel import MultiHostRunner  # noqa: E402


def build_net():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Nesterovs(0.1, momentum=0.9))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


class CrashAt:
    """Hard-exit THIS process at a fixed optimizer step (preemption)."""

    def __init__(self, step):
        self.step = step

    def iteration_done(self, model, iteration):
        if self.step >= 0 and iteration >= self.step:
            print(f"CRASHING {pid} at {iteration}", flush=True)
            sys.stdout.flush()
            os._exit(3)


runner = MultiHostRunner(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid).initialize()

net = build_net()
if crash_at >= 0 and pid == 1:
    net.listeners.append(CrashAt(crash_at))

rng = np.random.default_rng(0)
x = rng.standard_normal((96, 8)).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=96)]
# interleaved partitions (same contract as multihost_worker.partition):
# global batch b = concat(proc0 rows, proc1 rows)
xs = x.reshape(6, 16, 8)[:, pid * 8:(pid + 1) * 8].reshape(48, 8)
ys = y.reshape(6, 16, 3)[:, pid * 8:(pid + 1) * 8].reshape(48, 3)

from deeplearning4j_tpu.parallel.multihost import CheckpointManager  # noqa: E402

latest = CheckpointManager(ckpt_dir).latest()
print(f"RESUME_FROM {pid} {latest[0] if latest else -1}", flush=True)

# 2 epochs x 6 batches = 12 optimizer steps, checkpoint every 2
runner.fit(net, xs, ys, epochs=2, batch_size=8,
           checkpoint_dir=ckpt_dir, checkpoint_every=2)
runner.materialize_local(net)
print(f"FINAL {pid} {float(np.abs(net.params()).sum()):.6f} "
      f"iter={net.iteration}", flush=True)
runner.barrier("done")
print(f"DONE {pid}", flush=True)
