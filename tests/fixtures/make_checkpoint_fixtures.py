"""Generate serialization-regression fixtures: checkpoints in the CURRENT
format + recorded predictions, committed so future format changes must
keep loading them (the reference's RegressionTest080.java family —
SURVEY.md §4 'serialization regression' is a load-bearing test family).

Run: python tests/fixtures/make_checkpoint_fixtures.py
Regenerate ONLY when intentionally breaking format compatibility (and
keep the old fixtures loading via a version shim if you do)."""
import os

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (LSTM, Adam, BatchNormalization,  # noqa: E402
                                ComputationGraph, ConvolutionLayer,
                                ConvolutionMode, DataSet, DenseLayer,
                                InputType, MergeVertex, MultiLayerNetwork,
                                NeuralNetConfiguration, NormalizerStandardize,
                                OutputLayer, PoolingType, RnnOutputLayer,
                                SubsamplingLayer)
from deeplearning4j_tpu.utils.model_serializer import save_model  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "checkpoints")
os.makedirs(OUT, exist_ok=True)
rng = np.random.default_rng(99)
recorded = {}


def record(name, net, x):
    recorded[f"{name}_x"] = x
    recorded[f"{name}_y"] = np.asarray(net.output(x))


# 1. CNN MultiLayerNetwork (conv + pool + BN + dense) trained a few steps,
#    with updater state and a fitted normalizer in the zip.
cnn = (NeuralNetConfiguration.builder().seed(11).updater(Adam(1e-3))
       .list()
       .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=6,
                               convolution_mode=ConvolutionMode.SAME,
                               activation="relu"))
       .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                               pooling_type=PoolingType.MAX))
       .layer(BatchNormalization())
       .layer(DenseLayer(n_out=16, activation="relu"))
       .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
       .set_input_type(InputType.convolutional(12, 12, 1)).build())
net = MultiLayerNetwork(cnn).init()
x = rng.standard_normal((16, 12, 12, 1)).astype(np.float32)
y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
net.fit(x, y, epochs=3, batch_size=8)
norm = NormalizerStandardize().fit(DataSet(x.reshape(16, -1),
                                           np.zeros((16, 1), np.float32)))
save_model(net, os.path.join(OUT, "mln_cnn.zip"), normalizer=norm)
record("mln_cnn", net, x[:4])

# 2. Recurrent MultiLayerNetwork (LSTM) — exercises scan-state layers.
rnn = (NeuralNetConfiguration.builder().seed(12).updater(Adam(1e-3))
       .list()
       .layer(LSTM(n_out=8, activation="tanh"))
       .layer(RnnOutputLayer(n_out=3, activation="softmax", loss="mcxent"))
       .set_input_type(InputType.recurrent(5)).build())
rnet = MultiLayerNetwork(rnn).init()
xr = rng.standard_normal((6, 7, 5)).astype(np.float32)
yr = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (6, 7))]
rnet.fit(DataSet(xr, yr), epochs=3, batch_size=6)
save_model(rnet, os.path.join(OUT, "mln_rnn.zip"))
record("mln_rnn", rnet, xr[:2])

# 3. ComputationGraph with a merge vertex.
gconf = (NeuralNetConfiguration.builder().seed(13).updater(Adam(1e-3))
         .graph_builder()
         .add_inputs("in")
         .add_layer("a", DenseLayer(n_out=8, activation="relu"), "in")
         .add_layer("b", DenseLayer(n_out=8, activation="tanh"), "in")
         .add_vertex("m", MergeVertex(), "a", "b")
         .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                       loss="mcxent"), "m")
         .set_outputs("out")
         .set_input_types(InputType.feed_forward(6)).build())
g = ComputationGraph(gconf).init()
xg = rng.standard_normal((12, 6)).astype(np.float32)
yg = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 12)]
g.fit(xg, yg, epochs=3, batch_size=6, use_async=False)
save_model(g, os.path.join(OUT, "graph_merge.zip"))
record("graph_merge", g, xg[:3])

np.savez(os.path.join(OUT, "expected.npz"), **recorded)
print("Wrote", sorted(os.listdir(OUT)))
