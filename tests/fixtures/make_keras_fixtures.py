"""Generate Keras .h5 fixtures + recorded predictions for import tests.

Run once (TF/Keras only needed here, not at test time):
    python tests/fixtures/make_keras_fixtures.py
Writes tests/fixtures/keras/*.h5 and expected.npz — the analog of the
reference's committed fixture models for KerasModelEndToEndTest.java."""
import os

os.environ["CUDA_VISIBLE_DEVICES"] = "-1"
# oneDNN fast-math perturbs conv outputs by ~1e-2; recorded expectations
# must be plain-f32 so import predict-equality can assert tightly
os.environ["TF_ENABLE_ONEDNN_OPTS"] = "0"

import numpy as np  # noqa: E402

import keras  # noqa: E402
from keras import layers  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "keras")
os.makedirs(OUT, exist_ok=True)

rng = np.random.default_rng(42)
expected = {}


def save(model, name, x):
    model.save(os.path.join(OUT, f"{name}.h5"))
    y = model.predict(x, verbose=0)
    expected[f"{name}_x"] = x
    expected[f"{name}_y"] = y


keras.utils.set_random_seed(7)

# 1. Sequential MLP (compiled → has training_config)
mlp = keras.Sequential([
    keras.Input((8,)),
    layers.Dense(16, activation="relu", name="d1"),
    layers.Dense(8, activation="tanh", name="d2"),
    layers.Dense(3, activation="softmax", name="out"),
])
mlp.compile(loss="categorical_crossentropy", optimizer="adam")
save(mlp, "mlp", rng.standard_normal((5, 8)).astype(np.float32))

# 2. Sequential CNN: conv/pool/BN/flatten/dense on 12x12x1 channels_last
cnn = keras.Sequential([
    keras.Input((12, 12, 1)),
    layers.Conv2D(8, 3, padding="same", activation="relu", name="c1"),
    layers.MaxPooling2D(2, name="p1"),
    layers.Conv2D(16, 3, padding="valid", activation="linear", name="c2"),
    layers.BatchNormalization(name="bn"),
    layers.Activation("relu", name="a1"),
    layers.ZeroPadding2D(1, name="zp"),
    layers.AveragePooling2D(2, name="p2"),
    layers.Flatten(name="fl"),
    layers.Dropout(0.25, name="dr"),
    layers.Dense(10, activation="softmax", name="out"),
])
cnn.compile(loss="categorical_crossentropy", optimizer="sgd")
# Give BN non-trivial moving stats by running a couple of train steps.
xtr = rng.standard_normal((32, 12, 12, 1)).astype(np.float32)
ytr = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)]
cnn.fit(xtr, ytr, epochs=2, batch_size=16, verbose=0)
save(cnn, "cnn", rng.standard_normal((4, 12, 12, 1)).astype(np.float32))

# 3. Sequential stacked LSTM → global pooling → dense
lstm = keras.Sequential([
    keras.Input((6, 5)),
    layers.LSTM(12, return_sequences=True, name="l1"),
    layers.LSTM(8, return_sequences=True, name="l2"),
    layers.GlobalAveragePooling1D(name="gp"),
    layers.Dense(4, activation="softmax", name="out"),
])
lstm.compile(loss="categorical_crossentropy", optimizer="adam")
save(lstm, "lstm", rng.standard_normal((3, 6, 5)).astype(np.float32))

# 4. Functional: two branches, Concatenate + Add merges
inp = keras.Input((8,), name="in0")
a = layers.Dense(16, activation="relu", name="da")(inp)
b = layers.Dense(16, activation="tanh", name="db")(inp)
cat = layers.Concatenate(name="cat")([a, b])
add = layers.Add(name="add")([a, b])
both = layers.Concatenate(name="cat2")([cat, add])
outf = layers.Dense(3, activation="softmax", name="out")(both)
func = keras.Model(inp, outf)
func.compile(loss="categorical_crossentropy", optimizer="adam")
save(func, "functional", rng.standard_normal((5, 8)).astype(np.float32))

# 5. Functional: LSTM(return_sequences=False) → last-time-step semantics
inp2 = keras.Input((7, 4), name="seq_in")
h = layers.LSTM(10, return_sequences=False, name="lstm")(inp2)
out2 = layers.Dense(2, activation="softmax", name="out")(h)
lstm_last = keras.Model(inp2, out2)
lstm_last.compile(loss="categorical_crossentropy", optimizer="adam")
save(lstm_last, "lstm_last", rng.standard_normal((3, 7, 4)).astype(np.float32))

# 6. Sequential with the Dense → Activation('softmax') tail idiom
act_tail = keras.Sequential([
    keras.Input((8,)),
    layers.Dense(12, activation="relu", name="h"),
    layers.Dense(3, name="logits"),
    layers.Activation("softmax", name="sm"),
])
act_tail.compile(loss="categorical_crossentropy", optimizer="adam")
save(act_tail, "act_tail", rng.standard_normal((5, 8)).astype(np.float32))

# 7. Non-linear terminal Dense followed by an Activation (no fold legal)
relu_tail = keras.Sequential([
    keras.Input((8,)),
    layers.Dense(3, activation="relu", name="scores"),
    layers.Activation("softmax", name="sm"),
])
relu_tail.compile(loss="categorical_crossentropy", optimizer="adam")
save(relu_tail, "relu_tail", rng.standard_normal((5, 8)).astype(np.float32))

# 8. channels_first (theano-dim-ordering era) sequential CNN. TF-CPU
# cannot RUN channels_first convs, but it can build+save them; the
# recorded predictions come from the mathematically equivalent
# channels_last model (same conv kernels — Keras stores HWIO for both
# orderings — and the dense kernel rows permuted from (c,h,w) to
# (h,w,c) flatten order). The .h5 on disk is a REAL channels_first
# model; the equivalence below is exactly what the importer must do.
C, H, W = 2, 10, 8
cf = keras.Sequential([
    keras.Input((C, H, W)),
    layers.Conv2D(4, 3, padding="same", activation="relu",
                  data_format="channels_first", name="cfc"),
    layers.MaxPooling2D(2, data_format="channels_first", name="cfp"),
    layers.Flatten(data_format="channels_first", name="cff"),
    layers.Dense(5, activation="softmax", name="cfo"),
])
cf.compile(loss="categorical_crossentropy", optimizer="adam")
cf.save(os.path.join(OUT, "cnn_cf.h5"))

cl = keras.Sequential([
    keras.Input((H, W, C)),
    layers.Conv2D(4, 3, padding="same", activation="relu", name="clc"),
    layers.MaxPooling2D(2, name="clp"),
    layers.Flatten(name="clf"),
    layers.Dense(5, activation="softmax", name="clo"),
])
cl.get_layer("clc").set_weights(cf.get_layer("cfc").get_weights())
ck, cb = cf.get_layer("cfo").get_weights()
ph, pw = H // 2, W // 2
perm = np.arange(4 * ph * pw).reshape(4, ph, pw).transpose(1, 2, 0).reshape(-1)
cl.get_layer("clo").set_weights([ck[perm], cb])
x_cf = rng.standard_normal((5, C, H, W)).astype(np.float32)
y_cf = cl.predict(x_cf.transpose(0, 2, 3, 1), verbose=0)
expected["cnn_cf_x"] = x_cf
expected["cnn_cf_y"] = y_cf

np.savez(os.path.join(OUT, "expected.npz"), **expected)
print("Wrote fixtures to", OUT)
for k in sorted(expected):
    print(" ", k, expected[k].shape)
