"""Train + commit the zoo LeNet pretrained artifact.

Run once on CPU:
    python tests/fixtures/make_pretrained_fixture.py
Writes tests/fixtures/pretrained/lenet_mnist.zip (a REAL trained
checkpoint — the zero-egress stand-in for the reference's hosted
pretrained weights, ZooModel.java:40-81) and manifest.json with its
sha256 + the accuracy it reached on the deterministic synthetic MNIST
test split (fetchers.synthesize_mnist_idx, seed 42)."""
import hashlib
import json
import os
import sys
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.data.fetchers import MnistDataSetIterator  # noqa: E402
from deeplearning4j_tpu.data.normalizers import ImagePreProcessingScaler  # noqa: E402
from deeplearning4j_tpu.models import LeNet  # noqa: E402
from deeplearning4j_tpu.utils.model_serializer import save_model  # noqa: E402

OUT = os.path.join(HERE, "pretrained")
os.makedirs(OUT, exist_ok=True)

data_dir = tempfile.mkdtemp()
train_it = MnistDataSetIterator(64, train=True, flatten=False,
                                path=data_dir, synthesize=True)
train_it.pre_processor = ImagePreProcessingScaler()
net = LeNet().init()
net.fit(train_it, epochs=3)

test_it = MnistDataSetIterator(256, train=False, flatten=False,
                               path=data_dir)
test_it.pre_processor = ImagePreProcessingScaler()
correct = total = 0
for ds in test_it:
    pred = net.predict(ds.features)
    correct += int((pred == ds.labels.argmax(1)).sum())
    total += len(pred)
acc = correct / total
print(f"synthetic-MNIST test accuracy: {acc:.3f} ({correct}/{total})")
assert acc > 0.9, "refusing to commit an untrained artifact"

path = os.path.join(OUT, "lenet_mnist.zip")
save_model(net, path, save_updater=False)  # inference artifact: 1/3 size
sha = hashlib.sha256(open(path, "rb").read()).hexdigest()
with open(os.path.join(OUT, "manifest.json"), "w") as f:
    json.dump({"file": "lenet_mnist.zip", "sha256": sha,
               "test_accuracy": acc,
               "dataset": "synthesize_mnist_idx(seed=42) test split"},
              f, indent=2)
print("sha256:", sha)
