"""Worker for the cluster-health chaos tests (test_cluster_health_gloo.py).

    python health_worker.py <pid> <nproc> <port> <ckpt_dir> <mode> <arg>

Modes:
    run    train to completion (clean reference, and the resume leg of
           the grace test); prints FINAL + PSHA (sha256 of the params
           bytes — the bitwise-identity witness).
    kill   process 1 SIGKILLs itself at step <arg>; the survivor's
           heartbeat watchdog must convert the ensuing silent hang into
           a typed PeerLostError and hard-exit with code 17.
    grace  slow the steps down (so the parent can SIGTERM mid-run);
           on SIGTERM every process must agree on a stop step, write one
           coordinated grace checkpoint, and exit 0.

The health plane is armed via the DL4JTPU_HEARTBEAT_* env family set by
the parent test (short timeouts). Deterministic: fixed seeds, fixed data
order, fixed crash step.
"""
import os
import sys
import time

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=2"

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
ckpt_dir, mode, arg = sys.argv[4], sys.argv[5], int(sys.argv[6])

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (DenseLayer, InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration, Nesterovs,
                                OutputLayer)
from deeplearning4j_tpu.parallel import MultiHostRunner  # noqa: E402


def build_net():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Nesterovs(0.1, momentum=0.9))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


class KillSelfAt:
    """SIGKILL THIS process at a fixed optimizer step — the no-cleanup
    death (no atexit, no socket close) the watchdog exists to detect."""

    def __init__(self, step):
        self.step = step

    def iteration_done(self, model, iteration):
        if iteration >= self.step:
            print(f"KILLED {pid} at {iteration}", flush=True)
            import signal as _signal
            os.kill(os.getpid(), _signal.SIGKILL)


class SlowStep:
    """Pace the loop so the parent can SIGTERM between step boundaries."""

    def iteration_done(self, model, iteration):
        print(f"STEP {pid} {iteration}", flush=True)
        time.sleep(0.25)


runner = MultiHostRunner(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid).initialize()

net = build_net()
if mode == "kill" and pid == 1:
    net.listeners.append(KillSelfAt(arg))
if mode == "grace":
    net.listeners.append(SlowStep())

rng = np.random.default_rng(0)
x = rng.standard_normal((96, 8)).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=96)]
# interleaved partitions (same contract as elastic_worker.py)
xs = x.reshape(6, 16, 8)[:, pid * 8:(pid + 1) * 8].reshape(48, 8)
ys = y.reshape(6, 16, 3)[:, pid * 8:(pid + 1) * 8].reshape(48, 3)

from deeplearning4j_tpu.parallel.multihost import StepCheckpointManager  # noqa: E402

latest = StepCheckpointManager(ckpt_dir).latest()
print(f"RESUME_FROM {pid} {latest[0] if latest else -1}", flush=True)
print(f"START {pid}", flush=True)

try:
    # 2 epochs x 6 batches = 12 optimizer steps, checkpoint every 4
    runner.fit(net, xs, ys, epochs=2, batch_size=8,
               checkpoint_dir=ckpt_dir, checkpoint_every=4)
except SystemExit as e:
    # the preemption-grace path: checkpoint written, clean exit
    print(f"GRACE_EXIT {pid} step={runner.last_grace_step} code={e.code}",
          flush=True)
    raise

runner.materialize_local(net)
import hashlib  # noqa: E402

digest = hashlib.sha256(
    np.ascontiguousarray(np.asarray(net.params())).tobytes()).hexdigest()
print(f"FINAL {pid} {float(np.abs(net.params()).sum()):.6f} "
      f"iter={net.iteration}", flush=True)
print(f"PSHA {pid} {digest}", flush=True)
runner.stop_health()
runner.barrier("done")
print(f"DONE {pid}", flush=True)
