"""Worker entry for the multi-host test: one Spark-executor-analog process.

Invoked by test_multihost.py as
    python multihost_worker.py <process_id> <num_processes> <port>
Each process contributes 2 CPU devices and its own data partition; the
final parameter vector is printed for cross-process / vs-single-device
comparison. (The reference's analogous test trains Spark local[N] vs a
single machine — TestCompareParameterAveragingSparkVsSingleMachine.)"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=2"

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (DenseLayer, InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration, Nesterovs, OutputLayer)
from deeplearning4j_tpu.parallel import MultiHostRunner  # noqa: E402


def build_net():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Nesterovs(0.1, momentum=0.9))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def partition(p):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=64)]
    # Global batch k = concat(proc0 rows, proc1 rows): interleave halves so
    # each process's batch b of size 16 is rows [b*32+p*16 : b*32+(p+1)*16].
    xs = x.reshape(2, 32, 8)[:, p * 16:(p + 1) * 16].reshape(32, 8)
    ys = y.reshape(2, 32, 3)[:, p * 16:(p + 1) * 16].reshape(32, 3)
    return xs, ys


runner = MultiHostRunner(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid).initialize()
assert jax.device_count() == 2 * nproc, jax.device_count()

# Phase 1: synchronous DP (averaging_frequency=1), 2 epochs of 2 batches.
net = build_net()
xs, ys = partition(pid)
runner.fit(net, xs, ys, epochs=2, batch_size=16)
runner.materialize_local(net)
print(f"SYNC {pid} {float(np.abs(net.params()).sum()):.6f}", flush=True)

# Phase 2: local SGD (averaging_frequency=2) across hosts.
net2 = build_net()
runner.fit(net2, xs, ys, epochs=2, batch_size=16, averaging_frequency=2)
runner.materialize_local(net2)
print(f"LOCAL {pid} {float(np.abs(net2.params()).sum()):.6f}", flush=True)

runner.barrier("done")
print(f"DONE {pid}", flush=True)
