"""Worker entry for the multi-host test: one Spark-executor-analog process.

Invoked by test_multihost.py as
    python multihost_worker.py <process_id> <num_processes> <port>
Each process contributes 2 CPU devices and its own data partition; the
final parameter vector is printed for cross-process / vs-single-device
comparison. (The reference's analogous test trains Spark local[N] vs a
single machine — TestCompareParameterAveragingSparkVsSingleMachine.)"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=2"

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (DenseLayer, InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration, Nesterovs, OutputLayer)
from deeplearning4j_tpu.parallel import MultiHostRunner  # noqa: E402


def build_net():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Nesterovs(0.1, momentum=0.9))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def partition(p):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=64)]
    # Global batch k = concat(proc0 rows, proc1 rows): interleave halves so
    # each process's batch b of size 16 is rows [b*32+p*16 : b*32+(p+1)*16].
    xs = x.reshape(2, 32, 8)[:, p * 16:(p + 1) * 16].reshape(32, 8)
    ys = y.reshape(2, 32, 3)[:, p * 16:(p + 1) * 16].reshape(32, 3)
    return xs, ys


runner = MultiHostRunner(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid).initialize()
assert jax.device_count() == 2 * nproc, jax.device_count()

# Phase 1: synchronous DP (averaging_frequency=1), 2 epochs of 2 batches.
net = build_net()
xs, ys = partition(pid)
runner.fit(net, xs, ys, epochs=2, batch_size=16)
runner.materialize_local(net)
print(f"SYNC {pid} {float(np.abs(net.params()).sum()):.6f}", flush=True)

# Phase 2: local SGD (averaging_frequency=2) across hosts.
net2 = build_net()
runner.fit(net2, xs, ys, epochs=2, batch_size=16, averaging_frequency=2)
runner.materialize_local(net2)
print(f"LOCAL {pid} {float(np.abs(net2.params()).sum()):.6f}", flush=True)

# Phase 3: ComputationGraph with conv + BN state across hosts (the
# round-2 gap: multihost coverage was MLN-dense-only), plus a
# checkpoint-save-under-multihost assertion.
import tempfile  # noqa: E402

from deeplearning4j_tpu import (ActivationLayer, Adam,  # noqa: E402
                                ComputationGraph)
from deeplearning4j_tpu import DenseLayer as _Dense  # noqa: E402
from deeplearning4j_tpu import OutputLayer as _Out  # noqa: E402
from deeplearning4j_tpu.nn.layers.convolution import (  # noqa: E402
    BatchNormalization, ConvolutionLayer, ConvolutionMode)
from deeplearning4j_tpu.data.dataset import MultiDataSet  # noqa: E402


def build_graph():
    g = (NeuralNetConfiguration.builder().seed(9).updater(Adam(0.01))
         .graph_builder()
         .add_inputs("in"))
    g.add_layer("conv", ConvolutionLayer(
        kernel_size=(3, 3), n_out=4,
        convolution_mode=ConvolutionMode.SAME, conv_algo="direct"), "in")
    g.add_layer("bn", BatchNormalization(), "conv")
    g.add_layer("act", ActivationLayer(activation="relu"), "bn")
    g.add_layer("dense", _Dense(n_out=8, activation="relu"), "act")
    g.add_layer("out", _Out(n_out=3, activation="softmax",
                            loss="mcxent"), "dense")
    g.set_outputs("out")
    from deeplearning4j_tpu import InputType as _IT
    g.set_input_types(_IT.convolutional(6, 6, 2))
    return ComputationGraph(g.build()).init()


graph = build_graph()
rng = np.random.default_rng(1)
gx = rng.standard_normal((32, 6, 6, 2)).astype(np.float32)
gy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=32)]
# same interleave contract as partition(): each process feeds its rows
gxs = gx.reshape(2, 16, 6, 6, 2)[:, pid * 8:(pid + 1) * 8].reshape(
    16, 6, 6, 2)
gys = gy.reshape(2, 16, 3)[:, pid * 8:(pid + 1) * 8].reshape(16, 3)
runner.fit(graph, MultiDataSet([gxs], [gys]), epochs=2, batch_size=8)
runner.materialize_local(graph)
psum = float(sum(np.abs(np.asarray(a)).sum()
                 for a in jax.tree_util.tree_leaves(graph.params_tree)))
# BN running stats must have moved off init (mean 0 / var 1) — the
# conv+BN state actually trained under multihost DP
bn_mean = float(np.abs(np.asarray(
    graph.state_tree["bn"]["mean"])).sum())
print(f"GRAPH {pid} {psum:.6f}", flush=True)
print(f"BNSTATE {pid} {bn_mean:.6f}", flush=True)

# chief-only checkpoint write + all-process readback equality
ckpt = os.path.join(tempfile.gettempdir(),
                    f"mh_ckpt_{port}.zip")  # port-unique per test run
runner.save_checkpoint(graph, ckpt)
assert os.path.exists(ckpt), "checkpoint missing after save barrier"
from deeplearning4j_tpu.utils.model_serializer import restore_model  # noqa: E402
re_model = restore_model(ckpt)
re_sum = float(sum(np.abs(np.asarray(a)).sum()
                   for a in jax.tree_util.tree_leaves(re_model.params_tree)))
print(f"CKPT {pid} {re_sum:.6f}", flush=True)
runner.barrier("ckpt-read")  # both processes read before chief removes
if pid == 0:
    os.remove(ckpt)

# Phase 4: distributed evaluation (evaluation flatmap + merge role) —
# every process scores its partition; the merged Evaluation must count
# ALL rows and agree across processes.
ev = runner.evaluate(net, xs, ys, batch_size=16)
print(f"EVAL {pid} {ev.num_examples()} {ev.accuracy():.6f}", flush=True)

runner.barrier("done")
print(f"DONE {pid}", flush=True)
