"""Worker entry for the multi-host test: one Spark-executor-analog process.

Invoked by test_multihost.py as
    python multihost_worker.py <process_id> <num_processes> <port>
Each process contributes 2 CPU devices and its own data partition; the
final parameter vector is printed for cross-process / vs-single-device
comparison. (The reference's analogous test trains Spark local[N] vs a
single machine — TestCompareParameterAveragingSparkVsSingleMachine.)"""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=2"

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (DenseLayer, InputType, MultiLayerNetwork,  # noqa: E402
                                NeuralNetConfiguration, Nesterovs, OutputLayer)
from deeplearning4j_tpu.parallel import MultiHostRunner  # noqa: E402


def build_net():
    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Nesterovs(0.1, momentum=0.9))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    return MultiLayerNetwork(conf).init()


def partition(p):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=64)]
    # Global batch k = concat(proc0 rows, proc1 rows): interleave halves so
    # each process's batch b of size 16 is rows [b*32+p*16 : b*32+(p+1)*16].
    xs = x.reshape(2, 32, 8)[:, p * 16:(p + 1) * 16].reshape(32, 8)
    ys = y.reshape(2, 32, 3)[:, p * 16:(p + 1) * 16].reshape(32, 3)
    return xs, ys


runner = MultiHostRunner(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid).initialize()
assert jax.device_count() == 2 * nproc, jax.device_count()

# Phase 1: synchronous DP (averaging_frequency=1), 2 epochs of 2 batches.
net = build_net()
xs, ys = partition(pid)
runner.fit(net, xs, ys, epochs=2, batch_size=16)
runner.materialize_local(net)
print(f"SYNC {pid} {float(np.abs(net.params()).sum()):.6f}", flush=True)

# Phase 2: local SGD (averaging_frequency=2) across hosts.
net2 = build_net()
runner.fit(net2, xs, ys, epochs=2, batch_size=16, averaging_frequency=2)
runner.materialize_local(net2)
print(f"LOCAL {pid} {float(np.abs(net2.params()).sum()):.6f}", flush=True)

# Phase 3: ComputationGraph with conv + BN state across hosts (the
# round-2 gap: multihost coverage was MLN-dense-only), plus a
# checkpoint-save-under-multihost assertion.
import tempfile  # noqa: E402

from deeplearning4j_tpu import (ActivationLayer, Adam,  # noqa: E402
                                ComputationGraph)
from deeplearning4j_tpu import DenseLayer as _Dense  # noqa: E402
from deeplearning4j_tpu import OutputLayer as _Out  # noqa: E402
from deeplearning4j_tpu.nn.layers.convolution import (  # noqa: E402
    BatchNormalization, ConvolutionLayer, ConvolutionMode)
from deeplearning4j_tpu.data.dataset import MultiDataSet  # noqa: E402


def build_graph():
    g = (NeuralNetConfiguration.builder().seed(9).updater(Adam(0.01))
         .graph_builder()
         .add_inputs("in"))
    g.add_layer("conv", ConvolutionLayer(
        kernel_size=(3, 3), n_out=4,
        convolution_mode=ConvolutionMode.SAME, conv_algo="direct"), "in")
    g.add_layer("bn", BatchNormalization(), "conv")
    g.add_layer("act", ActivationLayer(activation="relu"), "bn")
    g.add_layer("dense", _Dense(n_out=8, activation="relu"), "act")
    g.add_layer("out", _Out(n_out=3, activation="softmax",
                            loss="mcxent"), "dense")
    g.set_outputs("out")
    from deeplearning4j_tpu import InputType as _IT
    g.set_input_types(_IT.convolutional(6, 6, 2))
    return ComputationGraph(g.build()).init()


graph = build_graph()
rng = np.random.default_rng(1)
gx = rng.standard_normal((32, 6, 6, 2)).astype(np.float32)
gy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=32)]
# same interleave contract as partition(): each process feeds its rows
gxs = gx.reshape(2, 16, 6, 6, 2)[:, pid * 8:(pid + 1) * 8].reshape(
    16, 6, 6, 2)
gys = gy.reshape(2, 16, 3)[:, pid * 8:(pid + 1) * 8].reshape(16, 3)
runner.fit(graph, MultiDataSet([gxs], [gys]), epochs=2, batch_size=8)
runner.materialize_local(graph)
psum = float(sum(np.abs(np.asarray(a)).sum()
                 for a in jax.tree_util.tree_leaves(graph.params_tree)))
# BN running stats must have moved off init (mean 0 / var 1) — the
# conv+BN state actually trained under multihost DP
bn_mean = float(np.abs(np.asarray(
    graph.state_tree["bn"]["mean"])).sum())
print(f"GRAPH {pid} {psum:.6f}", flush=True)
print(f"BNSTATE {pid} {bn_mean:.6f}", flush=True)

# chief-only checkpoint write + all-process readback equality
ckpt = os.path.join(tempfile.gettempdir(),
                    f"mh_ckpt_{port}.zip")  # port-unique per test run
runner.save_checkpoint(graph, ckpt)
assert os.path.exists(ckpt), "checkpoint missing after save barrier"
from deeplearning4j_tpu.utils.model_serializer import restore_model  # noqa: E402
re_model = restore_model(ckpt)
re_sum = float(sum(np.abs(np.asarray(a)).sum()
                   for a in jax.tree_util.tree_leaves(re_model.params_tree)))
print(f"CKPT {pid} {re_sum:.6f}", flush=True)
runner.barrier("ckpt-read")  # both processes read before chief removes
if pid == 0:
    os.remove(ckpt)

# Phase 4: distributed evaluation (evaluation flatmap + merge role) —
# every process scores its partition; the merged Evaluation must count
# ALL rows and agree across processes.
ev = runner.evaluate(net, xs, ys, batch_size=16)
print(f"EVAL {pid} {ev.num_examples()} {ev.accuracy():.6f}", flush=True)


def _tree_abs_sum(tree, mesh):
    """|params| sum over a possibly cross-process-sharded tree: jitted
    SPMD reduction to a replicated scalar (lockstep on all processes)."""
    import jax.numpy as jnp
    total = 0.0
    with mesh:
        for leaf in jax.tree_util.tree_leaves(tree):
            total += float(jax.jit(
                lambda a: jnp.sum(jnp.abs(a.astype(jnp.float32))))(leaf))
    return total


# Phase 5: TENSOR PARALLELISM across the process boundary (round-5
# VERDICT item 3: docs/parallelism.md claims "MultiHostRunner around any
# of the above" — for TP the model axis spans hosts, changing collective
# routing, so it must be a test, not a claim). Mesh: 1 data x 4 model
# over 2 processes x 2 devices; both processes feed the IDENTICAL
# global batch (the place_global contract).
from deeplearning4j_tpu.parallel import (TensorParallelWrapper,  # noqa: E402
                                         tensor_parallel_mesh)
from deeplearning4j_tpu.data.dataset import DataSet  # noqa: E402

tp_net = build_net()
tp_mesh = tensor_parallel_mesh(model_devices=4, data_devices=1,
                               devices=jax.devices())
w = TensorParallelWrapper(tp_net, tp_mesh)
rng = np.random.default_rng(5)
tx = rng.standard_normal((16, 8)).astype(np.float32)
ty = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=16)]
for _ in range(3):
    w.fit_batch(DataSet(tx, ty))
# sharding evidence: the dense W [8,16] shards (None, "model") and its
# shards span BOTH processes (addressable < total)
w0 = tp_net.params_tree[0]["W"]
spec = tuple(w0.sharding.spec)
n_total = len(w0.sharding.device_set)
n_addr = len(w0.addressable_shards)
print(f"TPSHARD {pid} spec={spec} addr={n_addr}/{n_total}", flush=True)
print(f"TP {pid} {_tree_abs_sum(tp_net.params_tree, tp_mesh):.6f}",
      flush=True)

# Phase 5b: checkpoint while TP-placed: collective gather on ALL
# processes, then the chief-only write + all-process readback.
w.materialize_local()
ckpt_tp = os.path.join(tempfile.gettempdir(), f"mh_tp_ckpt_{port}.zip")
runner.save_checkpoint(tp_net, ckpt_tp)
re_tp = restore_model(ckpt_tp)
re_tp_sum = float(sum(np.abs(np.asarray(a)).sum()
                      for a in jax.tree_util.tree_leaves(
                          re_tp.params_tree)))
print(f"TPCKPT {pid} {re_tp_sum:.6f}", flush=True)
runner.barrier("tp-ckpt-read")
if pid == 0:
    os.remove(ckpt_tp)

# Phase 6: SEQUENCE PARALLELISM across the process boundary: time axis
# sharded 4-way over the 2x2 global device set, ring attention crossing
# the gloo boundary.
from deeplearning4j_tpu import RnnOutputLayer, Sgd  # noqa: E402
from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer  # noqa: E402
from deeplearning4j_tpu.parallel import (SequenceParallelWrapper,  # noqa: E402
                                         seq_parallel_mesh)


def build_attn():
    conf = (NeuralNetConfiguration.builder().seed(21)
            .updater(Sgd(0.1)).list()
            .layer(SelfAttentionLayer(n_out=16, n_heads=4, causal=True))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(8)).build())
    return MultiLayerNetwork(conf).init()


sp_net = build_attn()
sp_mesh = seq_parallel_mesh(seq_devices=4, devices=jax.devices())
sw = SequenceParallelWrapper(sp_net, sp_mesh)
rng = np.random.default_rng(6)
sx = rng.standard_normal((4, 16, 8)).astype(np.float32)
sy = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 16))]
probe = sw._shard_bt(sx, True)  # the [batch, time] placement itself
print(f"SPSHARD {pid} spec={tuple(probe.sharding.spec)} "
      f"addr={len(probe.addressable_shards)}/"
      f"{len(probe.sharding.device_set)}", flush=True)
for _ in range(2):
    sw.fit_batch(DataSet(sx, sy))
print(f"SP {pid} {_tree_abs_sum(sp_net.params_tree, sp_mesh):.6f}",
      flush=True)

runner.barrier("done")
print(f"DONE {pid}", flush=True)
