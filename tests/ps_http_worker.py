"""Worker process for the HTTP parameter-server test.

    python ps_http_worker.py <url> <worker_id>

Builds the same-seed model, trains its data shard against the remote
parameter server over HTTP (the dl4j-spark-parameterserver executor
role), and prints the number of applied pushes."""
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

url, wid = sys.argv[1], int(sys.argv[2])
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu import (Adam, DataSet, DenseLayer, InputType,  # noqa: E402
                                MultiLayerNetwork, NeuralNetConfiguration,
                                OutputLayer)
from deeplearning4j_tpu.parallel.param_server import remote_worker_fit  # noqa: E402

conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(0.05))
        .list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(2))
        .build())
net = MultiLayerNetwork(conf).init()

rng = np.random.default_rng(0)
means = np.array([[2.0, 0.0], [-2.0, 1.5], [0.0, -2.5]], np.float32)
x = np.concatenate([rng.normal(means[k], 0.6, (128, 2))
                    for k in range(3)]).astype(np.float32)
y = np.eye(3, dtype=np.float32)[np.repeat(np.arange(3), 128)]
order = rng.permutation(len(x))
x, y = x[order], y[order]
half = len(x) // 2
xs = x[wid * half:(wid + 1) * half]
ys = y[wid * half:(wid + 1) * half]

applied = remote_worker_fit(net, url, DataSet(xs, ys), epochs=8,
                            batch_size=64, seed=100 + wid)
print(f"APPLIED {wid} {applied}", flush=True)
