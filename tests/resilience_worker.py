"""Subprocess body for the kill-and-resume tests (test_resilience.py's
slow TestKillResume and tests/smoke_resilience.py).

Usage: resilience_worker.py <ckpt_dir> <out_npz|/dev/null> <fresh|resume>

Trains a fixed deterministic tiny net for 3 epochs x 8 batches with a
per-iteration CheckpointManager in <ckpt_dir>. ``fresh`` starts from
scratch (the driver may arm DL4JTPU_FAULT_CHECKPOINT_WRITE="kill:N" to
SIGKILL this process mid-checkpoint-write); ``resume`` restores the
newest valid checkpoint and completes the run. On success, writes final
params/iteration/epoch to <out_npz> and prints DONE.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu import (Adam, DenseLayer, InputType,
                                MultiLayerNetwork, NeuralNetConfiguration,
                                OutputLayer)
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.optimize.resilience import CheckpointManager


def main():
    ckpt_dir, out, mode = sys.argv[1:4]
    assert mode in ("fresh", "resume"), mode

    conf = (NeuralNetConfiguration.builder().seed(7)
            .updater(Adam(0.05)).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(42)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=64)]

    mgr = CheckpointManager(ckpt_dir, save_every_n_iterations=1,
                            keep_last=5)
    net.fit(DataSet(x, y), epochs=3, batch_size=8,
            checkpoint=mgr, resume=(mode == "resume"))

    if out != "/dev/null":
        np.savez(out, params=np.asarray(net.params()),
                 iteration=int(net.iteration), epoch=int(net.epoch))
    print("DONE", int(net.iteration), int(net.epoch))


if __name__ == "__main__":
    main()
