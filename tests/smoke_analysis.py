"""jaxlint smoke: the shipped tree must be clean against the committed
baseline (docs/static_analysis.md).

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is the commit gate itself, invoked exactly the way CI and humans
invoke it: the module CLI over the whole package with the packaged
baseline). Exits nonzero on any NEW finding, on a broken baseline file,
and — loudly but separately — prints stale baseline entries so they get
pruned rather than accumulate.

Usage: python tests/smoke_analysis.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    from deeplearning4j_tpu.analysis.baseline import (Baseline,
                                                      default_baseline_path)
    from deeplearning4j_tpu.analysis.cli import main as jaxlint_main
    from deeplearning4j_tpu.analysis.rules import RULES

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "deeplearning4j_tpu")

    # the committed baseline must parse and carry a justification per entry
    bl = Baseline.load(default_baseline_path())
    missing = [e.location for e in bl.entries if not e.justification]
    if missing:
        print(f"smoke_analysis: FAIL: {len(missing)} baseline entries "
              f"lack a justification: {missing[:5]}")
        return 1

    assert len(RULES) >= 10, "rule registry shrank below the contract"

    rc = jaxlint_main([pkg])
    if rc != 0:
        print("smoke_analysis: FAIL: new jaxlint findings above the "
              "committed baseline (see output above); fix them or "
              "baseline them with a justification")
        return 1

    print(f"smoke_analysis: OK ({len(RULES)} rules, "
          f"{len(bl.entries)} baselined findings, 0 new)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
