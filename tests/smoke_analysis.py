"""jaxlint smoke: the shipped tree must be clean against the committed
baseline (docs/static_analysis.md).

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is the commit gate itself, invoked exactly the way CI and humans
invoke it: the module CLI over the whole package with the packaged
baseline). Exits nonzero on any NEW finding, on a broken baseline file,
and — loudly but separately — prints stale baseline entries so they get
pruned rather than accumulate.

Usage: python tests/smoke_analysis.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _lockcheck_smoke() -> int:
    """Cheap lockcheck exercise: record one two-lock nesting, confirm
    the runtime edge matches the static JL402 graph. Pure threading
    bookkeeping — no device work."""
    import textwrap
    import threading

    from deeplearning4j_tpu.analysis import lockcheck
    from deeplearning4j_tpu.analysis import rules

    src = textwrap.dedent("""
        import threading
        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def ab(self):
                with self._a:
                    with self._b:
                        pass
    """)
    with lockcheck.recording():
        ns = {}
        exec(src, ns)
        p = ns["Pair"]()
        lockcheck.adopt(p, "Pair")
        p.ab()
    if isinstance(threading.Lock(), lockcheck.LockProxy):
        print("smoke_analysis: FAIL: lockcheck left threading patched")
        return 1
    report = lockcheck.cross_check(
        lockcheck.observed_edges(), rules.lock_edges_from_source(src))
    if report.confirmed != {("Pair._a", "Pair._b")} or not report.ok():
        print(f"smoke_analysis: FAIL: lockcheck cross-check mismatch: "
              f"confirmed={report.confirmed} cycles={report.cycles}")
        return 1
    return 0


def main() -> int:
    from deeplearning4j_tpu.analysis.baseline import (Baseline,
                                                      default_baseline_path)
    from deeplearning4j_tpu.analysis.cli import main as jaxlint_main
    from deeplearning4j_tpu.analysis.rules import RULES, RULES_BY_ID

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    pkg = os.path.join(repo, "deeplearning4j_tpu")

    # the committed baseline must parse and carry a justification per entry
    bl = Baseline.load(default_baseline_path())
    missing = [e.location for e in bl.entries if not e.justification]
    if missing:
        print(f"smoke_analysis: FAIL: {len(missing)} baseline entries "
              f"lack a justification: {missing[:5]}")
        return 1

    assert len(RULES) >= 19, "rule registry shrank below the contract"
    # the v2 concurrency / serving-discipline families must stay enabled
    for rid in ("JL402", "JL403", "JL404", "JL501", "JL502", "JL503"):
        assert rid in RULES_BY_ID, f"rule {rid} missing from the registry"

    rc = jaxlint_main([pkg])
    if rc != 0:
        print("smoke_analysis: FAIL: new jaxlint findings above the "
              "committed baseline (see output above); fix them or "
              "baseline them with a justification")
        return 1

    if _lockcheck_smoke() != 0:
        return 1

    print(f"smoke_analysis: OK ({len(RULES)} rules, "
          f"{len(bl.entries)} baselined findings, 0 new, "
          f"lockcheck cross-check confirmed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
