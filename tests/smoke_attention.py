"""Attention-kernel smoke: interpret-mode gate for the fused Pallas
flash kernel and its dispatch (docs/perf_attention.md, ISSUE 7).

Runs the REAL kernels (fwd AND bwd) in interpret mode on CPU against
the dense_attention reference, then exercises the dispatch: the auto
rule, the requested-pallas clean fallback off-TPU (no crash, counter
incremented, one-shot warning), and the selection counter family on the
metrics registry.

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is the end-to-end kernel gate, kept out of the pytest budget).
Exits nonzero on any failed expectation.

Usage: JAX_PLATFORMS=cpu python tests/smoke_attention.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import attention as att
    from deeplearning4j_tpu.ops import flash_attention as fa
    from deeplearning4j_tpu.optimize.metrics import registry

    rng = np.random.default_rng(0)
    B, T, H, D = 2, 64, 4, 16
    mk = lambda: jnp.asarray(rng.standard_normal((B, T, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    g = mk()
    km = jnp.asarray(rng.random((B, T)) > 0.3, jnp.float32)

    # 1) fwd parity, causal + mask
    got = fa.flash_attention(q, k, v, causal=True, key_mask=km,
                             q_block=16, kv_block=16, interpret=True)
    want = att.dense_attention(q, k, v, causal=True, key_mask=km)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    print("smoke_attention: fwd parity ok")

    # 2) bwd parity through the custom_vjp Pallas backward kernels
    gf = jax.grad(lambda q, k, v: jnp.sum(fa.flash_attention(
        q, k, v, causal=True, q_block=16, kv_block=16,
        interpret=True) * g), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(att.dense_attention(
        q, k, v, causal=True) * g), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
    print("smoke_attention: bwd parity ok")

    # 3) dispatch: auto rule + requested-pallas clean fallback off-TPU
    assert att.select_attention_impl(64, 16) == "dense"
    assert att.select_attention_impl(4096, 128,
                                     interpret=True) == "pallas"
    fallback = att.select_attention_impl(4096, 128, requested="pallas")
    assert fallback in ("blockwise", "dense"), fallback
    out = att.single_device_attention(q, k, v, causal=True,
                                      impl="pallas")  # no TPU: no crash
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(att.dense_attention(q, k, v, causal=True)),
        rtol=1e-5, atol=1e-5)
    print("smoke_attention: dispatch fallback ok (%s)" % fallback)

    # 4) the selection counter family is on the scrape surface
    text = registry().prometheus_text()
    if "attention_kernel_selected_total" not in text:
        print("smoke_attention: counter family missing from registry")
        return 1
    print("smoke_attention: selection counter on scrape surface")
    return 0


if __name__ == "__main__":
    sys.exit(main())
