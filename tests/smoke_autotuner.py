"""Smoke: the serving control loop closes end to end on a live gateway.

Builds a deliberately mis-tuned gateway (standard-tier `app` stuck
with a fat collector linger under a tight tier SLO, flight recorder
armed), arms the AutoTuner at a fast cadence against a temp ledger,
and drives a chaos-shifted workload — a batch-tier `bulk` flood joins
mid-run. Asserts the loop actually closed:

* the tuner made >= 1 ledgered move, and every ledger row is
  schema-valid (the auditable-trail contract)
* NO move ever left its knob's [lo, hi] guardrails, and the live
  config agrees with the ledger's final word for each knob
* the tuner measurably tightened the mis-tuned linger (the standing
  bench row's win, in miniature)
* GET /debug/tuner renders the state + knob table + decision trail
  over live HTTP, and GET /metrics carries the tuner families
* a clean run never froze: serving_tuner_frozen == 0

Run: JAX_PLATFORMS=cpu python tests/smoke_autotuner.py
Run by runtests.sh as a separate step (no test_ prefix on purpose).
"""
import json
import os
import signal
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

HARD_TIMEOUT_S = 120

RUN_S = 4.0
SHIFT_S = 1.5
LINGER_MS = 8.0
STANDARD_SLO_MS = 6.0


def _alarm(signum, frame):
    print(f"SMOKE FAIL: autotuner smoke exceeded {HARD_TIMEOUT_S}s "
          "hard timeout", flush=True)
    os._exit(2)


signal.signal(signal.SIGALRM, _alarm)
signal.alarm(HARD_TIMEOUT_S)


class _EchoStub:
    """Device-free forward: the smoke measures the control loop, not
    XLA (the chaos-suite stub idiom)."""

    _initialized = True

    def output(self, x):
        return np.asarray(x) * 2.0


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read())


def main() -> int:
    from deeplearning4j_tpu.optimize.metrics import registry
    from deeplearning4j_tpu.serving import ServingGateway, SLOMonitor
    from deeplearning4j_tpu.serving import flight_recorder
    from deeplearning4j_tpu.serving.autotuner import (read_ledger,
                                                      validate_entry)

    failures = []
    rng = np.random.default_rng(0)
    payloads = [rng.standard_normal((1, 8)).astype(np.float32)
                for _ in range(8)]

    with tempfile.TemporaryDirectory(prefix="dl4jtpu_smoke_at_") as tmp:
        ledger = os.path.join(tmp, "autotune_ledger.jsonl")
        flight_recorder.enable()
        gw = ServingGateway()
        gw.add_model("app", _EchoStub(), batch_limit=8,
                     batch_timeout_ms=LINGER_MS, tier="standard")
        gw.add_model("bulk", _EchoStub(), batch_limit=16,
                     batch_timeout_ms=LINGER_MS, tier="batch")
        gw.pool.reconfigure_scheduler(
            tier_slo_ms={"standard": STANDARD_SLO_MS, "batch": 500.0})
        tuner = gw.attach_tuner(
            ledger_path=ledger, interval_s=0.2, settle_ticks=1,
            breach_freeze_factor=10.0,
            monitor=SLOMonitor(gw.pool, window_s=1.5, min_samples=3))
        try:
            with gw:  # live HTTP — /debug/tuner must render mid-flight
                stop = time.perf_counter() + RUN_S
                shift_at = time.perf_counter() + SHIFT_S
                errs = []

                def app_client():
                    try:
                        i = 0
                        while time.perf_counter() < stop:
                            gw.predict("app", payloads[i % len(payloads)])
                            i += 1
                    except Exception as e:  # TierShedError included: typed
                        if "TierShed" not in type(e).__name__:
                            errs.append(repr(e))

                def bulk_client():
                    try:
                        i = 0
                        while time.perf_counter() < shift_at:
                            time.sleep(0.02)
                        while time.perf_counter() < stop:
                            try:
                                gw.predict("bulk",
                                           payloads[i % len(payloads)])
                            except Exception as e:
                                if "TierShed" not in type(e).__name__:
                                    raise
                                time.sleep(0.001)
                            i += 1
                    except Exception as e:
                        errs.append(repr(e))

                ts = [threading.Thread(target=app_client)
                      for _ in range(2)]
                ts.append(threading.Thread(target=bulk_client))
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                if errs:
                    failures.append(f"client errors: {errs[:3]}")

                code, dbg = _get_json(gw.url + "/debug/tuner")
                if code != 200 or dbg.get("enabled") is not True:
                    failures.append(
                        f"/debug/tuner: code={code} enabled="
                        f"{dbg.get('enabled')!r}, wanted 200/True")
                if not isinstance(dbg.get("trail"), list) or \
                        not dbg["trail"]:
                    failures.append("/debug/tuner trail is empty — the "
                                    "decision trail never rendered")
                if not dbg.get("knobs"):
                    failures.append("/debug/tuner knob table is empty")
                guardrails = {k["name"]: (k["lo"], k["hi"])
                              for k in dbg.get("knobs", [])}

                with urllib.request.urlopen(gw.url + "/metrics",
                                            timeout=10) as r:
                    scrape = r.read().decode()
                for fam in ("serving_tuner_moves_total",
                            "serving_tuner_frozen",
                            "serving_slo_verdict"):
                    if fam not in scrape:
                        failures.append(
                            f"/metrics scrape missing {fam!r}")
        finally:
            tuner.stop()
            gw.pool.shutdown()
            flight_recorder.disable()

        rows = read_ledger(ledger)
        moves = [r for r in rows if r.get("kind") == "move"]
        if not moves:
            failures.append("tuner made ZERO ledgered moves in "
                            f"{RUN_S}s at 0.2s cadence")
        for r in rows:
            problems = validate_entry(r)
            if problems:
                failures.append(f"ledger row seq={r.get('seq')} failed "
                                f"schema: {problems}")
        for m in moves:
            lo_hi = guardrails.get(m["knob"])
            if lo_hi is None:
                failures.append(f"move on unknown knob {m['knob']!r}")
            elif not (lo_hi[0] <= m["new"] <= lo_hi[1]):
                failures.append(
                    f"GUARDRAIL VIOLATION: move seq={m['seq']} set "
                    f"{m['knob']}={m['new']} outside {lo_hi}")

        final_linger = tuner_final_linger = None
        for k in (tuner.describe())["knobs"]:
            if k["name"] == "linger_ms:app":
                tuner_final_linger = k["value"]
        final_linger = tuner_final_linger
        if final_linger is None:
            failures.append("linger_ms:app knob missing from describe()")
        elif final_linger >= LINGER_MS:
            failures.append(f"tuner never tightened the mis-tuned linger "
                            f"({final_linger} >= {LINGER_MS})")

        frozen = registry().gauge("serving_tuner_frozen").value()
        if frozen != 0.0:
            failures.append(f"clean run ended frozen "
                            f"(serving_tuner_frozen={frozen})")

    signal.alarm(0)
    if failures:
        print("SMOKE FAIL: serving control loop")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"SMOKE OK: control loop closed — {len(moves)} ledgered "
          f"move(s), all inside guardrails, linger {LINGER_MS} -> "
          f"{final_linger}, /debug/tuner trail live, never froze")
    return 0


if __name__ == "__main__":
    sys.exit(main())
