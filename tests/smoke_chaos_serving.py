"""Serving chaos smoke: live-traffic resilience acceptance check
(docs/serving.md, docs/robustness.md).

Builds a tiny warmed MLP gateway with an aggressive circuit breaker
(threshold 1, 50 ms cooldown), arms the ``serve.forward`` fault point
with ``fail:2/5`` (a deterministic 20% forward-failure rate), and
drives concurrent HTTP /predict traffic through the storm. Asserts:

* EVERY response is a typed terminal status — 200 ok, 500
  batch_failed, 503 breaker_open, 503 shed, or 429 queue_full; never a
  hang, never an untyped 5xx,
* the breaker opened at least once under the storm and RECOVERS after
  the faults are cleared (final /predict is 200, /health back to ok),
* ZERO XLA compile events after warmup (chaos rides the AOT
  executables too),
* the Prometheus scrape carries the resilience metric families.

A hard wall-clock alarm guards the whole run: a wedged future or hung
collector fails the smoke instead of hanging CI.

Run by runtests.sh as a separate step (no test_ prefix on purpose).
Usage: JAX_PLATFORMS=cpu python tests/smoke_chaos_serving.py
"""
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import (Adam, DenseLayer, InputType,  # noqa: E402
                                MultiLayerNetwork, NeuralNetConfiguration,
                                OutputLayer, WeightInit)
from deeplearning4j_tpu.optimize.metrics import registry  # noqa: E402
from deeplearning4j_tpu.optimize.telemetry import CompilationTracker  # noqa: E402
from deeplearning4j_tpu.serving import ServingGateway  # noqa: E402
from deeplearning4j_tpu.utils import faults  # noqa: E402

HARD_TIMEOUT_S = 120
FAULT_SPEC = "fail:2/5"  # forwards 2, 7, 12, ... fail: deterministic 20%

REQUIRED_FAMILIES = (
    "serving_requests_total", "serving_batch_failures_total",
    "serving_breaker_state", "serving_breaker_transitions_total",
    "serving_shed_total", "serving_queue_depth",
)

# (code, status, reason) triples a chaos request may legally end with.
TYPED_OUTCOMES = {
    (200, "ok", None),
    (500, "error", "batch_failed"),
    (500, "error", "nonfinite"),
    (503, "unavailable", "breaker_open"),
    (503, "shed", "deadline"),
    (429, "shed", "queue_full"),
}


def _alarm(_sig, _frm):
    print(f"SMOKE FAIL: hard timeout ({HARD_TIMEOUT_S}s) — a request or "
          "the collector hung under chaos", file=sys.stderr)
    os._exit(2)


def make_net(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def post(url, payload):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def main() -> int:
    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(HARD_TIMEOUT_S)
    failures = []

    gw = ServingGateway()
    # threshold 1 + 50ms cooldown: every injected failure opens the
    # breaker, every cooldown probes — the full state machine cycles
    # many times within one short storm.
    gw.add_model("default", make_net(), batch_limit=8, queue_limit=64,
                 breaker_threshold=1, breaker_reset_s=0.05)
    gw.warmup()  # AOT: every pow2 bucket precompiled up front
    entry = gw.pool.get("default")
    open0 = registry().counter(
        "serving_breaker_transitions_total", "").value(
        model="default", to="open")

    outcomes, errors = [], []

    def client(i):
        # 5-row requests: two can never share the 8-row warmed cap, so
        # every coalesced batch is one request and an injected failure
        # surfaces typed to its caller (not healed by retry-alone).
        x = np.random.default_rng(i).standard_normal(
            (5, 4)).astype(np.float32)
        try:
            for _ in range(10):
                code, body = post(gw.url + "/predict",
                                  {"features": x.tolist()})
                outcomes.append((code, body.get("status"),
                                 body.get("reason")))
                if (code, body.get("status")) == (503, "unavailable"):
                    time.sleep(0.01)  # give the breaker its cooldown
        except Exception as e:  # transport-level breakage = smoke fail
            errors.append(e)

    faults.inject("serve.forward", FAULT_SPEC)
    with gw, CompilationTracker() as trk:
        ts = [threading.Thread(target=client, args=(i,))
              for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=90)
        hung = sum(t.is_alive() for t in ts)
        if hung:
            failures.append(f"{hung} client thread(s) hung under chaos")

        # ---- recovery: clear the chaos, wait out one cooldown, and the
        # gateway must serve cleanly and report healthy again.
        faults.clear("serve.forward")
        time.sleep(0.1)
        probe = np.random.default_rng(99).standard_normal(
            (2, 4)).astype(np.float32)
        code, body = post(gw.url + "/predict",
                          {"features": probe.tolist()})
        if (code, body.get("status")) != (200, "ok"):
            failures.append(f"post-chaos predict not 200/ok: {code} {body}")
        with urllib.request.urlopen(gw.url + "/health") as r:
            health = json.loads(r.read())
        if health.get("status") != "ok" or health.get("degraded"):
            failures.append(f"/health not back to ok after the storm: "
                            f"{health}")
        with urllib.request.urlopen(gw.url + "/metrics") as r:
            metrics_text = r.read().decode()

    if errors:
        failures.append(f"{len(errors)} client(s) hit transport errors: "
                        f"{errors[:3]}")
    untyped = [o for o in outcomes if o not in TYPED_OUTCOMES]
    if untyped:
        failures.append(f"{len(untyped)} response(s) outside the typed "
                        f"outcome set: {untyped[:5]}")
    n_ok = sum(1 for o in outcomes if o[0] == 200)
    n_failed = sum(1 for o in outcomes if o[2] == "batch_failed")
    n_breaker = sum(1 for o in outcomes if o[2] == "breaker_open")
    if len(outcomes) != 8 * 10:
        failures.append(f"only {len(outcomes)}/80 requests terminated")
    if n_ok == 0:
        failures.append("no request succeeded during the storm")
    if n_failed == 0:
        failures.append("no request saw a typed batch_failed under a "
                        "20% injected failure rate")
    opened = registry().counter(
        "serving_breaker_transitions_total", "").value(
        model="default", to="open") - open0
    if opened < 1:
        failures.append("breaker never opened under the storm")
    if entry.breaker.state != "closed":
        failures.append(f"breaker did not recover: {entry.breaker.state}")
    if entry.engine.total_batch_failures == 0:
        failures.append("engine counted zero batch failures")
    if trk.count != 0:
        failures.append(f"{trk.count} XLA compile(s) after warmup — "
                        "chaos must ride the AOT executables")
    for fam in REQUIRED_FAMILIES:
        if fam not in metrics_text:
            failures.append(f"metric family {fam} missing from /metrics")

    signal.alarm(0)
    if failures:
        for f in failures:
            print(f"SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"serving chaos smoke OK: {len(outcomes)} requests all typed "
          f"({n_ok} ok / {n_failed} batch_failed / {n_breaker} "
          f"breaker_open), breaker opened {int(opened)}x and recovered, "
          f"0 compiles after warmup, all {len(REQUIRED_FAMILIES)} "
          "resilience families scraped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
