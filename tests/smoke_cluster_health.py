"""Cluster-health smoke for runtests.sh (docs/robustness.md
§cluster-health) — the PR-8 chaos-smoke pattern: a hard signal.alarm
bounds the whole script so a watchdog regression can never wedge the CI
gate itself.

Three legs, all gloo-free (the 2-process chaos rows are slow-marked
pytest tests):

  1. fake-clock watchdog transitions: dead peer -> PeerLostError,
     frozen-but-beating peer -> ClusterDesyncError
  2. timed_collective converts a wedged collective into a typed
     BarrierTimeoutError
  3. the REAL preemption path: a child process is SIGTERM'd mid-fit,
     must write a grace checkpoint and exit 0, and the restarted run
     must reach bitwise-identical final parameters
"""
import os
import signal
import subprocess
import sys
import tempfile
import threading

signal.alarm(300)  # the gate must never wedge, whatever breaks below

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.parallel import cluster_health as ch  # noqa: E402

# ---- leg 1: watchdog state machine on a fake clock ------------------------
clock_t = [0.0]
clock = lambda: clock_t[0]  # noqa: E731
transport = ch.InProcessBeatTransport(clock)
cfg = ch.HealthConfig(interval_s=1, timeout_s=5, stall_timeout_s=10)
fails = []
m0 = ch.ClusterHealthMonitor(0, 2, transport, config=cfg, clock=clock,
                             on_failure=fails.append)
m1 = ch.ClusterHealthMonitor(1, 2, transport, config=cfg, clock=clock,
                             on_failure=fails.append)
m0._started_at = m1._started_at = clock()
assert m0.poll_once() is None and m1.poll_once() is None
clock_t[0] = 6.0  # peer 1 goes silent past timeout_s
err = m0.poll_once()
assert isinstance(err, ch.PeerLostError) and err.peers == [1], err
assert fails == [err]
print(f"[smoke_cluster_health] peer-lost: {type(err).__name__} "
      f"peers={err.peers}")

# frozen-but-beating peer: fresh transport, monitor 1 beats but never steps
transport2 = ch.InProcessBeatTransport(clock)
fails2 = []
a = ch.ClusterHealthMonitor(0, 2, transport2, config=cfg, clock=clock,
                            on_failure=fails2.append)
b = ch.ClusterHealthMonitor(1, 2, transport2, config=cfg, clock=clock,
                            on_failure=fails2.append)
a._started_at = b._started_at = clock()
step = 0
derr = None
for _ in range(13):
    clock_t[0] += 1.0
    step += 1
    a.notify_step(step)  # a advances; b beats but stays frozen
    derr = a.poll_once()
    assert b.poll_once() is None
    if derr is not None:
        break
assert isinstance(derr, ch.ClusterDesyncError) and derr.peers == [1], derr
print(f"[smoke_cluster_health] desync: {type(derr).__name__} "
      f"peers={derr.peers}")

# ---- leg 2: timed collective fails typed instead of hanging ---------------
release = threading.Event()
try:
    ch.timed_collective(release.wait, name="smoke-barrier", timeout_s=0.1)
    raise AssertionError("wedged collective did not time out")
except ch.BarrierTimeoutError as e:
    print(f"[smoke_cluster_health] timed barrier: {e}")
finally:
    release.set()

# ---- leg 3: real SIGTERM -> grace checkpoint -> bitwise resume ------------
CHILD = r'''
import os, signal, sys
sys.path.insert(0, sys.argv[4])
import jax
jax.config.update("jax_platforms", "cpu")
import hashlib
import numpy as np
from deeplearning4j_tpu import (DenseLayer, InputType, MultiLayerNetwork,
                                NeuralNetConfiguration, Nesterovs,
                                OutputLayer)
from deeplearning4j_tpu.parallel import MultiHostRunner

ckpt_dir, term_at = sys.argv[1], int(sys.argv[2])
conf = (NeuralNetConfiguration.builder().seed(7)
        .updater(Nesterovs(0.1, momentum=0.9)).list()
        .layer(DenseLayer(n_out=16, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(8)).build())
net = MultiLayerNetwork(conf).init()

class TermAt:
    def iteration_done(self, model, iteration):
        if term_at >= 0 and iteration == term_at:
            os.kill(os.getpid(), signal.SIGTERM)

net.listeners.append(TermAt())
rng = np.random.default_rng(0)
x = rng.standard_normal((48, 8)).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=48)]
runner = MultiHostRunner().initialize()
try:
    runner.fit(net, x, y, epochs=2, batch_size=8,
               checkpoint_dir=ckpt_dir, checkpoint_every=100)
except SystemExit as e:
    print(f"GRACE step={runner.last_grace_step}", flush=True)
    raise
sha = hashlib.sha256(
    np.ascontiguousarray(np.asarray(net.params())).tobytes()).hexdigest()
print(f"FINAL iter={net.iteration} sha={sha}", flush=True)
'''


def run_child(ckpt_dir, term_at):
    return subprocess.run(
        [sys.executable, "-c", CHILD, ckpt_dir, str(term_at), "x",
         os.path.dirname(os.path.dirname(os.path.abspath(__file__)))],
        capture_output=True, text=True, timeout=240)


with tempfile.TemporaryDirectory() as tmp:
    clean = run_child(os.path.join(tmp, "clean"), -1)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    ref_sha = clean.stdout.split("sha=")[1].strip()

    grace_dir = os.path.join(tmp, "grace")
    graced = run_child(grace_dir, 3)
    assert graced.returncode == 0, \
        f"grace exit must be 0, got {graced.returncode}:\n" \
        f"{graced.stdout}{graced.stderr}"
    assert "GRACE step=3" in graced.stdout, graced.stdout
    assert any(f.startswith("checkpoint_step")
               for f in os.listdir(grace_dir)), os.listdir(grace_dir)

    resumed = run_child(grace_dir, -1)
    assert resumed.returncode == 0, resumed.stdout + resumed.stderr
    res_sha = resumed.stdout.split("sha=")[1].strip()
    assert res_sha == ref_sha, \
        f"resume after grace not bitwise-identical:\n{ref_sha}\n{res_sha}"
    print(f"[smoke_cluster_health] grace: SIGTERM at step 3 -> exit 0, "
          f"checkpoint written, resume bitwise-identical (sha {ref_sha[:12]})")

print("[smoke_cluster_health] OK")
