"""Compile-cache smoke: the warm-start acceptance check, end to end.

Runs the tiny lenet bench workload TWICE as fresh subprocesses sharing
one temporary persistent-cache directory. The cold run populates the
cache (misses); the warm run must report cache HITS > 0 — proving a new
process deserializes XLA executables from disk instead of recompiling —
and both runs must finish under a wall-clock ceiling and emit valid
JSON (the bench-survivability contract).

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is a cross-process end-to-end smoke, not a pytest unit). Exits
nonzero on any failed expectation.

Usage: JAX_PLATFORMS=cpu python tests/smoke_compile_cache.py
Env:   DL4JTPU_SMOKE_CEILING_S  per-run wall ceiling, default 300.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(cache_dir: str, ceiling: float):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               DL4JTPU_COMPILE_CACHE_DIR=cache_dir)
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "lenet_tiny",
         "--once"],
        capture_output=True, text=True, env=env, cwd=REPO,
        timeout=ceiling + 60)
    wall = time.monotonic() - t0
    if out.returncode != 0:
        print(f"SMOKE FAIL: bench rc={out.returncode}\n"
              f"{out.stderr[-3000:]}", file=sys.stderr)
        sys.exit(1)
    try:
        row = json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print(f"SMOKE FAIL: bench stdout is not JSON:\n"
              f"{out.stdout[-2000:]}", file=sys.stderr)
        sys.exit(1)
    return row, wall


def main() -> int:
    ceiling = float(os.environ.get("DL4JTPU_SMOKE_CEILING_S", "300"))
    failures = []
    with tempfile.TemporaryDirectory(prefix="dl4jtpu_cc_smoke_") as d:
        cold, cold_wall = run_once(d, ceiling)
        warm, warm_wall = run_once(d, ceiling)

    for name, row, wall in (("cold", cold, cold_wall),
                            ("warm", warm, warm_wall)):
        if wall > ceiling:
            failures.append(f"{name} run took {wall:.0f}s "
                            f"(ceiling {ceiling:.0f}s)")
        cc = row.get("compile_cache") or {}
        if not cc.get("enabled"):
            failures.append(f"{name} run: compile cache not enabled "
                            f"({cc})")
        if not (isinstance(row.get("value"), (int, float))
                and row["value"] > 0):
            failures.append(f"{name} run: bad metric value "
                            f"{row.get('value')!r}")

    cold_cc = cold.get("compile_cache") or {}
    warm_cc = warm.get("compile_cache") or {}
    if not cold_cc.get("misses", 0) > 0:
        failures.append("cold run reported no cache misses "
                        f"({cold_cc}) — cache not actually in the loop")
    if not warm_cc.get("hits", 0) > 0:
        failures.append("warm run reported no cache hits "
                        f"({warm_cc}) — persistent cache did not "
                        "survive across processes")

    if failures:
        for f in failures:
            print(f"SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"compile-cache smoke OK: cold {cold_wall:.0f}s "
          f"(misses={cold_cc.get('misses')}, entries="
          f"{cold_cc.get('entries')}), warm {warm_wall:.0f}s "
          f"(hits={warm_cc.get('hits')}, misses={warm_cc.get('misses')})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
