"""Decode-plane smoke: the zero-compile / typed-outcome acceptance
check for continuous batching, end to end over real HTTP
(docs/serving.md §decode).

Builds a gateway with BOTH decode families — a causal TransformerDecoder
("lm", paged-KV token arm) and a streaming LSTM ("stream",
rnn_time_step arm) — warms the full signature grid, then asserts:

* concurrent mixed-length /generate traffic returns 200 with tokens
  EXACTLY matching the naive full-recompute reference (the KV cache is
  an optimization, never an approximation),
* ZERO XLA compiles after warmup (prefill packing + every pow2 row/KV
  bucket ride the warmed executables),
* the typed error chain over HTTP: missing prompt -> 400 bad_prompt,
  out-of-vocab -> 400, unknown model -> 404,
* chaos: a serve.decode_step fault (batch attempt + first solo retry)
  kills EXACTLY one rider with a 500 batch_failed while its batchmate
  finishes every token; KV blocks drain to zero and the engine keeps
  serving afterwards,
* the decode metric families reach the Prometheus scrape surface.

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is a concurrency/e2e smoke, not a pytest unit). Exits nonzero on
any failed expectation.

Usage: JAX_PLATFORMS=cpu python tests/smoke_decode.py
"""
import json
import os
import signal
import sys
import threading
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import (LSTM, InputType,  # noqa: E402
                                MultiLayerNetwork, NeuralNetConfiguration,
                                RnnOutputLayer, Sgd)
from deeplearning4j_tpu.optimize.telemetry import CompilationTracker  # noqa: E402
from deeplearning4j_tpu.serving import ServingGateway  # noqa: E402
from deeplearning4j_tpu.serving.decode import (TransformerDecoder,  # noqa: E402
                                               naive_generate)
from deeplearning4j_tpu.utils import faults  # noqa: E402

REQUIRED_FAMILIES = (
    "serving_decode_tokens_total", "serving_decode_steps_total",
    "serving_decode_prefills_total", "serving_inter_token_ms_bucket",
    "serving_kv_blocks_in_use", "serving_kv_utilization",
)

PACK = 32


def post(url, payload):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def make_stream_net():
    conf = (NeuralNetConfiguration.builder().seed(3).updater(Sgd(0.1))
            .list()
            .layer(LSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=4, activation="identity",
                                  loss="mse"))
            .set_input_type(InputType.recurrent(4)).build())
    return MultiLayerNetwork(conf).init()


def main() -> int:
    signal.alarm(420)  # hard ceiling: a hung decode loop must not wedge CI
    failures = []

    lm = TransformerDecoder(vocab=64, layers=2, heads=2, head_dim=8,
                            ff=64, max_context=64, seed=0)
    gw = ServingGateway()
    gw.add_decode_model("lm", lm, pack_bucket=PACK, kv_block_tokens=8,
                        kv_max_blocks=64, max_decode_batch=4)
    gw.add_decode_model("stream", make_stream_net(), feature_dim=4,
                        max_decode_batch=4)
    gw.warmup()
    lm_cache = gw.pool.get("lm").engine.adapter.cache

    # Naive full-recompute references, computed OUTSIDE the tracker
    # window — only the gateway's own work is compile-silent-checked.
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, 64, n).tolist()
               for n in (3, 9, 17, 5, 12, 7)]
    want = [naive_generate(lm, p, 12, pad_to=PACK) for p in prompts]

    statuses, errors = [], []

    def lm_client(i):
        try:
            code, body = post(gw.url + "/generate",
                              {"model": "lm", "prompt": prompts[i],
                               "max_new_tokens": 12})
            ok = code == 200 and body.get("tokens") == want[i]
            statuses.append((code, body.get("status"), ok))
        except Exception as e:
            errors.append(e)

    def stream_client(i):
        x = np.random.default_rng(100 + i).standard_normal(
            (2 + i, 4)).astype(np.float32)
        try:
            code, body = post(gw.url + "/generate",
                              {"model": "stream", "prompt": x.tolist(),
                               "max_new_tokens": 6})
            shape = np.asarray(body.get("tokens")).shape
            statuses.append((code, body.get("status"), shape == (6, 4)))
        except Exception as e:
            errors.append(e)

    with gw, CompilationTracker() as trk:
        ts = ([threading.Thread(target=lm_client, args=(i,))
               for i in range(len(prompts))]
              + [threading.Thread(target=stream_client, args=(i,))
                 for i in range(2)])
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)

        bad = [s for s in statuses if s[:2] != (200, "ok") or not s[2]]
        if bad or len(statuses) != len(prompts) + 2:
            failures.append(f"steady traffic: {len(bad)} bad of "
                            f"{len(statuses)} (want {len(prompts) + 2} "
                            f"200/ok/exact): {bad[:5]}")

        # ---- typed error chain over HTTP
        code, body = post(gw.url + "/generate", {"model": "lm"})
        if (code, body.get("reason")) != (400, "bad_prompt"):
            failures.append(f"missing prompt: want 400/bad_prompt, "
                            f"got {code}/{body.get('reason')}")
        code, body = post(gw.url + "/generate",
                          {"model": "lm", "prompt": [1, 999]})
        if (code, body.get("reason")) != (400, "bad_prompt"):
            failures.append(f"out-of-vocab: want 400/bad_prompt, "
                            f"got {code}/{body.get('reason')}")
        code, _ = post(gw.url + "/generate",
                       {"model": "nope", "prompt": [1]})
        if code != 404:
            failures.append(f"unknown model: want 404, got {code}")

        # ---- chaos: batch step + first solo retry fail -> exactly one
        # rider dies typed, the batchmate finishes every token
        chaos = []

        def chaos_client(i):
            code, body = post(gw.url + "/generate",
                              {"model": "lm", "prompt": prompts[i],
                               "max_new_tokens": 12})
            chaos.append((code, body.get("reason"),
                          body.get("tokens") == want[i]))

        with faults.injected("serve.decode_step", "fail:3,4"):
            cts = [threading.Thread(target=chaos_client, args=(i,))
                   for i in range(2)]
            for t in cts:
                t.start()
            for t in cts:
                t.join(timeout=120)
        died = [c for c in chaos if c[0] == 500]
        lived = [c for c in chaos if c[0] == 200]
        if not (len(died) == 1 and died[0][1] == "batch_failed"
                and len(lived) == 1 and lived[0][2]):
            failures.append(f"chaos: want one 500/batch_failed + one "
                            f"exact 200, got {chaos}")
        if lm_cache.blocks_in_use() != 0:
            failures.append(f"KV blocks leaked after chaos: "
                            f"{lm_cache.blocks_in_use()} in use")

        # engine keeps serving after the fault window
        code, body = post(gw.url + "/generate",
                          {"model": "lm", "prompt": prompts[0],
                           "max_new_tokens": 12})
        if code != 200 or body.get("tokens") != want[0]:
            failures.append(f"post-chaos generate broken: {code}")

        with urllib.request.urlopen(gw.url + "/metrics") as r:
            metrics_text = r.read().decode()

    if errors:
        failures.append(f"{len(errors)} client(s) raised: {errors[:3]}")
    if trk.count != 0:
        failures.append(f"{trk.count} XLA compile(s) after warmup — "
                        "steady-state decode must compile nothing")
    for fam in REQUIRED_FAMILIES:
        if fam not in metrics_text:
            failures.append(f"metric family {fam} missing from /metrics")

    if failures:
        for f in failures:
            print(f"SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"decode smoke OK: {len(prompts)} transformer + 2 stream "
          f"requests token-exact over HTTP, typed 400/404 chain, chaos "
          f"isolated to one rider, 0 compiles after warmup, all "
          f"{len(REQUIRED_FAMILIES)} decode metric families scraped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
