"""Replica-federation smoke for runtests.sh (docs/serving.md §"Replica
federation") — the chaos-smoke pattern: a hard signal.alarm bounds the
whole script so a federation regression can never wedge the CI gate.

One end-to-end drill over real HTTP: a front-end with TWO spawned
replica subprocesses, a concurrent predict storm, a SIGKILL of one
replica mid-traffic. The gate demands:

  * every storm response is 200 or a TYPED error body (a connection
    error or an untyped body to the FRONT-END is a failure)
  * the killed replica is evicted from the routable set and the
    eviction + failover counters fired
  * the survivor keeps answering (200s continue after the kill)
  * every federation metric family is present in the /metrics scrape

Replica startup costs a jax import + warmup compile each on the 1-core
rig, so the alarm is generous; the deterministic state-machine coverage
lives in tests/test_federation.py's fast (fake-transport) tests.
"""
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

signal.alarm(420)  # the gate must never wedge, whatever breaks below

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from deeplearning4j_tpu.optimize.metrics import registry  # noqa: E402
from deeplearning4j_tpu.parallel.cluster_health import HealthConfig  # noqa: E402
from deeplearning4j_tpu.serving.federation import (DEAD,  # noqa: E402
                                                   FederationFrontEnd,
                                                   spawn_replica)

REQUIRED_FAMILIES = (
    "serving_replicas",
    "serving_replica_evictions_total",
    "serving_failover_retries_total",
    "serving_replica_dispatch_total",
)

REPLICA_ENV = {"JAX_PLATFORMS": "cpu",
               "DL4JTPU_REPLICA_N_IN": "4",
               "DL4JTPU_REPLICA_HIDDEN": "8",
               "DL4JTPU_REPLICA_N_OUT": "3",
               "DL4JTPU_REPLICA_BATCH_LIMIT": "8",
               "DL4JTPU_REPLICA_BATCH_TIMEOUT_MS": "2.0"}


def post(url, payload, timeout=30.0):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(url, body,
                                 {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def main() -> int:
    failures = []
    x = np.random.default_rng(0).standard_normal((2, 4)).astype(
        np.float32).tolist()
    fe = FederationFrontEnd(
        health=HealthConfig(interval_s=0.25, timeout_s=2.0))
    fe.start()
    procs = []
    try:
        procs = [spawn_replica(i, fe.url, env=REPLICA_ENV)
                 for i in range(2)]
        if not fe.wait_for_replicas(2, timeout=240):
            failures.append("fleet never became healthy")
            return _report(failures)

        results, errors = [], []
        stop = threading.Event()
        killed_at = [None]

        def client():
            while not stop.is_set():
                t = time.monotonic()
                try:
                    results.append(
                        (t, post(fe.url + "/predict",
                                 {"model": "default", "features": x})))
                except Exception as e:  # non-typed front-end failure
                    errors.append(e)

        ts = [threading.Thread(target=client) for _ in range(4)]
        for t in ts:
            t.start()
        time.sleep(1.0)                          # storm established
        killed_at[0] = time.monotonic()
        procs[1].kill()                          # SIGKILL mid-traffic
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with fe._lock:
                if fe._replicas[1].state == DEAD:
                    break
            time.sleep(0.05)
        time.sleep(1.0)                          # survivor keeps serving
        stop.set()
        for t in ts:
            t.join(timeout=30)

        if errors:
            failures.append(f"{len(errors)} non-typed failure(s) at the "
                            f"front-end: {errors[:3]}")
        if not results:
            failures.append("storm produced no responses")
        untyped = [(c, b) for _, (c, b) in results
                   if c != 200 and "reason" not in b and "error" not in b]
        if untyped:
            failures.append(f"untyped error bodies: {untyped[:3]}")
        post_kill_ok = [1 for t, (c, _) in results
                        if c == 200 and t > killed_at[0] + 0.5]
        if not post_kill_ok:
            failures.append("no 200s after the SIGKILL — the survivor "
                            "did not keep serving")
        with fe._lock:
            state = fe._replicas[1].state
        if state != DEAD:
            failures.append(f"killed replica never evicted "
                            f"(state={state!r})")
        if registry().counter(
                "serving_replica_evictions_total", "").total() < 1:
            failures.append("eviction counter never fired")
        n200 = sum(1 for _, (c, _) in results if c == 200)
        print(f"[smoke_federation] storm: {len(results)} responses "
              f"({n200} ok), {len(errors)} non-typed, replica 1 {state}")

        with urllib.request.urlopen(fe.url + "/metrics",
                                    timeout=10) as r:
            scrape = r.read().decode()
        missing = [f for f in REQUIRED_FAMILIES if f not in scrape]
        if missing:
            failures.append(f"metric families missing from the scrape: "
                            f"{missing}")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)
        fe.stop()
    return _report(failures)


def _report(failures) -> int:
    if failures:
        print("[smoke_federation] FAILED:")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("[smoke_federation] OK: SIGKILL mid-storm -> typed failover, "
          "eviction, survivor serving, families scraped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
