"""Multi-model serving smoke (docs/serving.md §multi-model, ISSUE 14):
the fused-group + WFQ acceptance check, end to end over real HTTP.

Builds a gateway serving THREE same-geometry heads as one
FusedModelGroup (tier critical) plus one independent batch-tier model,
warmup()s every pow2 bucket, then — under a CompilationTracker — drives
concurrent per-member HTTP /predict traffic through a live PER-MEMBER
checkpoint hot-swap. Asserts:

* every member request returns 200 (zero drops/errors across the
  member swap; batch-tier requests may only ever shed TYPED),
* the member swap reports swapped=True, post-swap predictions for that
  member are the new checkpoint's, and its groupmates' outputs are
  untouched,
* ZERO XLA compile events after warmup (fused steady state + member
  swap both ride the shared AOT executables),
* starvation is bounded: ``serving_starvation_total`` never moves
  without queued work (idle scrape delta == 0),
* the multi-model metric families are on the scrape surface.

A hard wall-clock alarm guards the whole run: a wedged scheduler slot
or hung request fails the smoke instead of wedging CI.

Run by runtests.sh as a separate step (no test_ prefix on purpose).
Usage: JAX_PLATFORMS=cpu python tests/smoke_multimodel.py
"""
import json
import os
import signal
import sys
import tempfile
import threading
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import (Adam, DenseLayer, InputType,  # noqa: E402
                                MultiLayerNetwork, NeuralNetConfiguration,
                                OutputLayer, WeightInit)
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph  # noqa: E402
from deeplearning4j_tpu.optimize.metrics import registry  # noqa: E402
from deeplearning4j_tpu.optimize.resilience import CheckpointManager  # noqa: E402
from deeplearning4j_tpu.optimize.telemetry import CompilationTracker  # noqa: E402
from deeplearning4j_tpu.serving import FusedModelGroup, ServingGateway  # noqa: E402

HARD_TIMEOUT_S = 240
MEMBERS = ("a", "b", "c")
REQUIRED_FAMILIES = (
    "serving_sched_dispatch_total", "serving_tier_slo_ms",
    "serving_latency_ms_bucket", "serving_requests_total",
)


def _alarm(_sig, _frm):
    print("SMOKE FAIL: hard wall-clock alarm fired — a request or the "
          "scheduler slot is wedged", file=sys.stderr)
    os._exit(2)


def graph_net(seed):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .add_layer("dense", DenseLayer(n_out=16, activation="tanh"),
                       "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "dense")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    return ComputationGraph(conf).init()


def make_mlp(seed=42):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def post(url, payload):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def starvation_total():
    return registry().counter("serving_starvation_total", "").total()


def main() -> int:
    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(HARD_TIMEOUT_S)
    failures = []
    members = [(nm, graph_net(seed))
               for nm, seed in zip(MEMBERS, (1, 2, 3))]
    donor = graph_net(88)
    probe = np.random.default_rng(99).standard_normal(
        (2, 4)).astype(np.float32)
    solo = {nm: np.asarray(net.output(probe)) for nm, net in members}
    want_b = np.asarray(donor.output(probe))

    with tempfile.TemporaryDirectory(prefix="dl4jtpu_mm_smoke_") as d:
        mgr = CheckpointManager(d)
        mgr.save(donor)

        gw = ServingGateway()
        grp = gw.add_fused_group("trio", members, batch_limit=8,
                                 checkpoints={"b": mgr},
                                 tier="critical", weight=2.0)
        if not isinstance(grp, FusedModelGroup):
            print("SMOKE FAIL: fusion fell back to independent dispatch "
                  "for same-geometry members", file=sys.stderr)
            return 1
        gw.add_model("low", make_mlp(9), tier="batch", batch_limit=8)
        gw.warmup()  # AOT: every pow2 bucket of both engines

        statuses, errors = [], []
        stop = threading.Event()

        def client(i):
            nm = MEMBERS[i % len(MEMBERS)] if i % 4 else "low"
            x = np.random.default_rng(i).standard_normal(
                (1 + (i % 5), 4)).astype(np.float32)
            try:
                while not stop.is_set():
                    code, body = post(gw.url + "/predict",
                                      {"model": nm,
                                       "features": x.tolist()})
                    statuses.append((nm, code, body.get("status")))
            except Exception as e:
                errors.append(e)

        with gw, CompilationTracker() as trk:
            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(8)]
            for t in ts:
                t.start()
            # live PER-MEMBER hot-swap while every member takes traffic
            code, swap = post(gw.url + "/swap", {"model": "b"})
            if code != 200 or swap.get("swapped") is not True:
                failures.append(f"member swap failed: {code} {swap}")
            stop.set()
            for t in ts:
                t.join(timeout=60)

            # post-swap: b serves the donor, a and c are untouched
            for nm, want in (("a", solo["a"]), ("b", want_b),
                             ("c", solo["c"])):
                code, body = post(gw.url + "/predict",
                                  {"model": nm,
                                   "features": probe.tolist()})
                got = np.asarray(body.get("predictions"), np.float32)
                if code != 200 or not np.allclose(got, want, rtol=0,
                                                  atol=1e-6):
                    failures.append(
                        f"post-swap member {nm!r} wrong (code={code})")

            # bounded starvation: an idle scrape window moves nothing
            s0 = starvation_total()
            for _ in range(3):
                post(gw.url + "/predict",
                     {"model": "a", "features": probe.tolist()})
            if starvation_total() != s0:
                failures.append(
                    "serving_starvation_total grew without queued work")

            with urllib.request.urlopen(gw.url + "/metrics") as r:
                metrics_text = r.read().decode()
            code, models = 200, json.loads(urllib.request.urlopen(
                gw.url + "/models").read())
        gw.pool.shutdown()

    if errors:
        failures.append(f"{len(errors)} client(s) errored: {errors[:3]}")
    member_bad = [s for s in statuses
                  if s[0] in MEMBERS and (s[1], s[2]) != (200, "ok")]
    if member_bad:
        failures.append(f"{len(member_bad)} fused-member requests not "
                        f"200/ok across the swap: {member_bad[:5]}")
    low = [s for s in statuses if s[0] == "low"]
    low_bad = [s for s in low
               if (s[1], s[2]) not in ((200, "ok"), (503, "shed"))]
    if low_bad:
        failures.append(f"{len(low_bad)} batch-tier requests neither ok "
                        f"nor TYPED shed: {low_bad[:5]}")
    if len(statuses) < 20:
        failures.append(f"only {len(statuses)} requests completed")
    if trk.count != 0:
        failures.append(f"{trk.count} XLA compile(s) after warmup — "
                        "fused steady state must compile nothing")
    fused = [m for m in models["models"] if m.get("fused_group")]
    if len(fused) != len(MEMBERS):
        failures.append(f"/models lists {len(fused)} fused members, "
                        f"wanted {len(MEMBERS)}")
    for fam in REQUIRED_FAMILIES:
        if fam not in metrics_text:
            failures.append(f"metric family {fam} missing from /metrics")

    signal.alarm(0)
    if failures:
        for f in failures:
            print(f"SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    shed = len([s for s in low if s[1] == 503])
    print(f"multimodel smoke OK: {len(statuses)} requests across 3 fused "
          f"members + 1 batch-tier model through a live member hot-swap, "
          f"0 compiles after warmup, {shed} typed batch-tier sheds, "
          f"starvation bounded, all {len(REQUIRED_FAMILIES)} families "
          "scraped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
