"""Observability smoke: run a real 2-epoch fit with tracing on, scrape
GET /metrics off a live UIServer, and assert the registry saw training.

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is an end-to-end smoke over live HTTP, not a pytest unit). Exits
nonzero on any failed expectation.

Usage: JAX_PLATFORMS=cpu python tests/smoke_observability.py
"""
import os
import re
import sys
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    from deeplearning4j_tpu import (DenseLayer, InputType,
                                    MultiLayerNetwork,
                                    NeuralNetConfiguration, OutputLayer,
                                    Sgd)
    from deeplearning4j_tpu.optimize import tracing
    from deeplearning4j_tpu.ui.server import UIServer

    tracing.enable(fence_every=4)
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=64)]
    net.fit(x, y, epochs=2, batch_size=16)

    server = UIServer(port=0).start()
    try:
        with urllib.request.urlopen(server.url + "/metrics",
                                    timeout=10) as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
    finally:
        server.stop()
        tracing.disable()

    failures = []
    if "text/plain" not in ctype:
        failures.append(f"unexpected /metrics content type: {ctype!r}")
    m = re.search(r"^train_iterations_total (\d+(?:\.\d+)?)$", text,
                  re.MULTILINE)
    if not m:
        failures.append("train_iterations_total missing from /metrics")
    elif float(m.group(1)) <= 0:
        failures.append(f"train_iterations_total is {m.group(1)}, "
                        "expected nonzero after a 2-epoch fit")
    families = {ln.split()[2] for ln in text.splitlines()
                if ln.startswith("# TYPE ")}
    if len(families) < 10:
        failures.append(f"only {len(families)} metric families exposed "
                        f"({sorted(families)}); expected >= 10")
    for needed in ("device_bytes_in_use", "device_peak_bytes_in_use",
                   "xla_compilations_total", "train_epochs_total"):
        if needed not in families:
            failures.append(f"{needed} missing from /metrics")

    spans = {e["name"] for e in tracing.export_trace_events()["traceEvents"]}
    for needed in ("fit", "epoch", "step"):
        if needed not in spans:
            failures.append(f"span {needed!r} missing from trace ring")

    if failures:
        for f in failures:
            print(f"SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"observability smoke OK: {len(families)} metric families, "
          f"train_iterations_total={m.group(1)}, spans={sorted(spans)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
