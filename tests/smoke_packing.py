"""Packed varlen smoke (ISSUE 13): interpret-mode gate for the
segment-masked flash kernel, the PackToBucket packing arithmetic, and
the packed-layer exactness contract — the fast slice of
tests/test_segment_attention.py / test_packing.py, kept out of the
pytest budget like the other smokes.

1) Segment-masked flash (interpret) fwd+bwd parity vs dense with the
   same segment ids.
2) first_fit_pack + pack_sequences layout invariants (pure numpy).
3) A tiny packed_segments net: packed score == unpacked ragged score
   EXACTLY, and per-segment outputs bitwise-match solo forwards.
4) The packing metric families register and update.

Usage: JAX_PLATFORMS=cpu python tests/smoke_packing.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import attention as att
    from deeplearning4j_tpu.ops import flash_attention as fa

    rng = np.random.default_rng(0)
    B, T, H, D = 2, 32, 2, 8
    mk = lambda: jnp.asarray(rng.standard_normal((B, T, H, D)),
                             jnp.float32)
    q, k, v = mk(), mk(), mk()
    g = mk()
    seg_row = np.zeros(T, np.int32)
    seg_row[:13], seg_row[13:25], seg_row[25:] = 1, 2, 3
    seg = jnp.asarray(np.broadcast_to(seg_row, (B, T)).copy())

    # 1) segment-masked kernel parity, fwd + bwd
    got = fa.flash_attention(q, k, v, causal=True, segment_ids=seg,
                             q_block=16, kv_block=16, interpret=True)
    want = att.dense_attention(q, k, v, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(
            fn(q, k, v) * g)

    gflash = jax.grad(loss(lambda q, k, v: fa.flash_attention(
        q, k, v, causal=True, segment_ids=seg, q_block=16, kv_block=16,
        interpret=True)), argnums=(0, 1, 2))(q, k, v)
    gdense = jax.grad(loss(lambda q, k, v: att.dense_attention(
        q, k, v, causal=True, segment_ids=seg)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gflash, gdense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
    print("smoke_packing: segment kernel fwd+bwd parity ok")

    # 2) packing arithmetic
    from deeplearning4j_tpu.data.padding import (first_fit_pack,
                                                 pack_sequences)
    lens = [5, 7, 3, 6, 2]
    bins = first_fit_pack(lens, 8)
    assert all(sum(lens[i] for i in b) <= 8 for b in bins)
    feat = rng.standard_normal((5, 8, 4)).astype(np.float32)
    lab = rng.standard_normal((5, 8, 3)).astype(np.float32)
    pf, pl, pseg, plm, pos = pack_sequences(feat, lab, lens, 8, bins=bins)
    assert int((pseg > 0).sum()) == sum(lens)
    assert int(plm.sum()) == sum(lens)
    print("smoke_packing: first-fit/pack_sequences layout ok")

    # 3) packed-layer exactness on a tiny net
    from deeplearning4j_tpu import (Adam, InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration, RnnOutputLayer)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import (ExistingDataSetIterator,
                                                   PackToBucketIterator)
    from deeplearning4j_tpu.nn.layers.attention import SelfAttentionLayer
    F = 4
    conf = (NeuralNetConfiguration.builder().seed(5).updater(Adam(1e-3))
            .list()
            .layer(SelfAttentionLayer(n_out=8, n_heads=2, causal=True,
                                      packed_segments=True))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(F)).build())
    net = MultiLayerNetwork(conf).init()
    lens = [3, 5, 2]
    t = 6
    feats = rng.standard_normal((3, t, F)).astype(np.float32)
    mask = (np.arange(t)[None, :] < np.asarray(lens)[:, None]
            ).astype(np.float32)
    feats *= mask[..., None]
    labels = np.eye(3, dtype=np.float32)[
        rng.integers(0, 3, (3, t))] * mask[..., None]
    ragged = DataSet(feats, labels, mask, mask)
    unpacked_score = net.score(ragged)
    packed_ds = next(iter(PackToBucketIterator(
        ExistingDataSetIterator([ragged]), bucket_len=8)))
    packed_score = net.score(packed_ds)
    assert packed_score == unpacked_score, \
        f"packed {packed_score!r} != unpacked {unpacked_score!r}"
    out = np.asarray(net.output(np.asarray(packed_ds.features),
                                features_mask=np.asarray(
                                    packed_ds.features_mask)))
    solo0 = np.asarray(net.output(feats[:1, :3]))
    assert np.all(out[:1, :3] == solo0), "packed != solo (bitwise)"
    print("smoke_packing: packed score/output exactness ok")

    # 4) metric families live
    from deeplearning4j_tpu.data.padding import register_packing_metrics
    from deeplearning4j_tpu.optimize.metrics import registry
    register_packing_metrics()
    reg = registry()
    assert reg.counter("packed_requests_total").value(source="fit") > 0
    assert 0.0 < reg.gauge("packing_efficiency").value(source="fit") <= 1.0
    print("smoke_packing: metric families ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
