"""Pooling + fusion smoke: CPU gate for the round-6 GoogLeNet attacks
(ISSUE 10, docs/perf_googlenet.md round 6).

Exercises the REAL code paths end to end: the argmax-equality-mask
max-pool backward against XLA's select-and-scatter VJP, the depthwise-
conv average pool against reduce_window, the dispatch selector (auto
rule, probe, counter family), and the sibling-conv fusion pass applied
to an initialized graph (bitwise forward across the rewrite).

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is the end-to-end gate, kept out of the pytest budget). Exits
nonzero on any failed expectation.

Usage: JAX_PLATFORMS=cpu python tests/smoke_pooling.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import pooling
    from deeplearning4j_tpu.optimize.metrics import registry

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 9, 9, 3)), jnp.float32)
    geo = dict(window=(3, 3), strides=(2, 2), pads=((1, 1), (1, 1)))

    # 1) mask backward vs select-and-scatter autodiff
    y_sns = pooling.max_pool(x, impl="sns", **geo)
    y_mask = pooling.max_pool(x, impl="mask", **geo)
    if not np.array_equal(np.asarray(y_sns), np.asarray(y_mask)):
        print("smoke_pooling: mask forward not bitwise")
        return 1
    g_sns = jax.grad(lambda a: jnp.sum(
        pooling.max_pool(a, impl="sns", **geo) ** 2))(x)
    g_mask = jax.grad(lambda a: jnp.sum(
        pooling.max_pool(a, impl="mask", **geo) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_mask), np.asarray(g_sns),
                               rtol=2e-6, atol=2e-6)
    print("smoke_pooling: mask backward parity ok")

    # 2) avg conv-vs-window, fwd + bwd
    a_w = pooling.avg_pool(x, impl="window", **geo)
    a_c = pooling.avg_pool(x, impl="conv", **geo)
    np.testing.assert_allclose(np.asarray(a_c), np.asarray(a_w),
                               rtol=2e-6, atol=2e-6)
    ga_w = jax.grad(lambda a: jnp.sum(
        pooling.avg_pool(a, impl="window", **geo)))(x)
    ga_c = jax.grad(lambda a: jnp.sum(
        pooling.avg_pool(a, impl="conv", **geo)))(x)
    np.testing.assert_allclose(np.asarray(ga_c), np.asarray(ga_w),
                               rtol=2e-6, atol=2e-6)
    print("smoke_pooling: avg conv/window parity ok")

    # 3) dispatch: auto rule, override, probe, counter family
    pooling.register_metrics()
    # measured per-backend rule: mask on CPU (this gate), sns on TPU
    if pooling.select_pooling_impl("max", (3, 3), (2, 2)) != "mask":
        print("smoke_pooling: auto rule drifted from the measured default")
        return 1
    if pooling.select_pooling_impl("max", (3, 3), (2, 2),
                                   requested="mask") != "mask":
        print("smoke_pooling: mask unavailable on this backend")
        return 1
    text = registry().prometheus_text()
    if "pooling_impl_selected_total" not in text:
        print("smoke_pooling: counter family missing from registry")
        return 1
    print("smoke_pooling: dispatch + counter family ok")

    # 4) sibling-conv fusion on an initialized graph: bitwise forward
    from deeplearning4j_tpu import (ComputationGraph, InputType,
                                    NeuralNetConfiguration, OutputLayer, Sgd)
    from deeplearning4j_tpu.nn.graph import fusion
    from deeplearning4j_tpu.nn.graph.vertices import MergeVertex
    from deeplearning4j_tpu.nn.layers.convolution import (ConvolutionLayer,
                                                          GlobalPoolingLayer,
                                                          PoolingType)

    g = (NeuralNetConfiguration.builder().seed(3).activation("relu")
         .updater(Sgd(0.1)).graph_builder().add_inputs("input"))
    for i, n in enumerate((3, 4, 2)):
        g.add_layer(f"b-cnn{i + 1}",
                    ConvolutionLayer(n_out=n, kernel_size=(1, 1)), "input")
    g.add_vertex("merge", MergeVertex(), "b-cnn1", "b-cnn2", "b-cnn3")
    g.add_layer("pool", GlobalPoolingLayer(pooling_type=PoolingType.AVG),
                "merge")
    g.add_layer("output", OutputLayer(n_out=2, activation="softmax",
                                      loss="mcxent"), "pool")
    g.set_outputs("output")
    g.set_input_types(InputType.convolutional(6, 6, 4))
    net = ComputationGraph(g.build()).init()
    fused = fusion.fuse_graph(net)
    if "b-cnn1+b-cnn2+b-cnn3" not in fused.conf.nodes:
        print("smoke_pooling: fusion pass found no group")
        return 1
    xg = jnp.asarray(rng.standard_normal((2, 6, 6, 4)), jnp.float32)
    if not np.array_equal(np.asarray(net.output(xg)),
                          np.asarray(fused.output(xg))):
        print("smoke_pooling: fused forward not bitwise")
        return 1
    if "sibling_conv_fusion_total" not in registry().prometheus_text():
        print("smoke_pooling: fusion counter family missing")
        return 1
    print("smoke_pooling: sibling-conv fusion bitwise forward ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
