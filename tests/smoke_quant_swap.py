"""Quantized hot-swap smoke: the canary-both-ways acceptance check
under live concurrent traffic (docs/serving.md §quantized).

Builds a dense-MLP gateway with a golden batch and a drift budget,
warmup()s every pow2 bucket, then — while concurrent clients hammer
/predict in-process — drives the quantized swap plane both ways:

* promote-on-pass: `swap(quantize="int8")` under a loose
  `canary_max_drift` promotes, the result / entry / gauge / /metrics
  exposition all carry precision="int8", and post-swap outputs stay
  within the budget of the fp32 reference,
* zero non-typed failures across the swap: every client request either
  answers or raises a typed serving error (none expected here),
* zero XLA compiles once the quantized warm completes (the int8 tree's
  first trace through PrecompiledDispatch happens inside the seeding
  pass below, NOT on the steady-state clock),
* canary_rejected-on-drift: a second gateway with a tight budget
  refuses the same quantized swap with the typed SwapError, bumps
  serving_swaps_total{outcome="canary_rejected",precision="int8"}, and
  the old fp32 tree keeps serving bitwise.

Run by runtests.sh as a separate step (no test_ prefix on purpose —
this is a concurrency/e2e smoke, not a pytest unit). Exits nonzero on
any failed expectation.

Usage: JAX_PLATFORMS=cpu python tests/smoke_quant_swap.py
"""
import os
import sys
import tempfile
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu import (Adam, DenseLayer, InputType,  # noqa: E402
                                MultiLayerNetwork, NeuralNetConfiguration,
                                OutputLayer, WeightInit)
from deeplearning4j_tpu.optimize.metrics import registry  # noqa: E402
from deeplearning4j_tpu.optimize.resilience import CheckpointManager  # noqa: E402
from deeplearning4j_tpu.optimize.telemetry import CompilationTracker  # noqa: E402
from deeplearning4j_tpu.serving import ServingGateway, SwapError  # noqa: E402

DRIFT_BUDGET = 0.05  # loose: int8 on this net drifts ~3e-3


def make_net(seed=42, train_seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="mcxent"))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(train_seed)
    x = rng.standard_normal((16, 8)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    net.fit(x, y, epochs=1, batch_size=16)
    return net


def main() -> int:
    failures = []
    rng = np.random.default_rng(0)
    golden = rng.standard_normal((4, 8)).astype(np.float32)
    payloads = [rng.standard_normal((1 + (i % 5), 8)).astype(np.float32)
                for i in range(12)]

    with tempfile.TemporaryDirectory(prefix="dl4jtpu_quant_smoke_") as d:
        mgr = CheckpointManager(d)
        mgr.save(make_net())

        # ---- leg 1: promote-on-pass under live traffic ----------------
        gw = ServingGateway()
        gw.add_model("default", make_net(), checkpoints=mgr,
                     batch_limit=8, golden_batch=golden,
                     canary_max_drift=DRIFT_BUDGET)
        gw.warmup()
        ref = np.asarray(gw.predict("default", golden))
        # Seed the int8 executables OUTSIDE the compile-silence window:
        # the quantized tree's first trace rides PrecompiledDispatch's
        # jit fall-through legitimately; steady state must not compile.
        assert gw.swap("default", quantize="int8")["swapped"] is True
        for p in payloads:
            gw.predict("default", p)
        assert gw.swap("default")["swapped"] is True  # back to fp32

        stop = threading.Event()
        errors, answered = [], []

        def client(i):
            k = i % len(payloads)
            while not stop.is_set():
                try:
                    out = np.asarray(gw.predict("default", payloads[k]))
                    if not np.isfinite(out).all():
                        errors.append(AssertionError("non-finite output"))
                        return
                    answered.append(1)
                except Exception as e:  # any error across a passing
                    errors.append(e)   # swap is a failure, typed or not
                    return

        with CompilationTracker() as trk:
            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(6)]
            for t in ts:
                t.start()
            import time
            time.sleep(0.2)  # live traffic flowing
            try:
                res = gw.swap("default", quantize="int8")
                if res.get("swapped") is not True or \
                        res.get("precision") != "int8":
                    failures.append(f"int8 swap did not promote: {res}")
            except SwapError as e:
                failures.append(f"int8 swap rejected unexpectedly: {e}")
            time.sleep(0.2)  # keep hammering post-swap
            stop.set()
            for t in ts:
                t.join(timeout=30)
            got = np.asarray(gw.predict("default", golden))

        if errors:
            failures.append(f"{len(errors)} client error(s) across the "
                            f"quantized swap: {errors[:3]}")
        if len(answered) < 20:
            failures.append(f"only {len(answered)} requests answered")
        if trk.count != 0:
            failures.append(f"{trk.count} XLA compile(s) after the "
                            "quantized warm — steady state must ride "
                            "the cached executables")
        drift = float(np.max(np.abs(got - ref)))
        if drift > DRIFT_BUDGET:
            failures.append(f"post-swap drift {drift:.4g} exceeds the "
                            f"{DRIFT_BUDGET} budget the canary passed")
        entry = gw.pool.get("default")
        if entry.precision != "int8":
            failures.append(f"entry precision {entry.precision!r} != int8")
        metrics_text = registry().prometheus_text()
        if 'precision="int8"' not in metrics_text:
            failures.append('precision="int8" label missing from the '
                            "metrics exposition")
        gw.pool.shutdown()

        # ---- leg 2: canary_rejected-on-drift, old tree keeps serving --
        gw = ServingGateway()
        gw.add_model("default", make_net(), checkpoints=mgr,
                     batch_limit=8, golden_batch=golden,
                     canary_max_drift=1e-9)
        gw.warmup()
        ref = np.asarray(gw.predict("default", golden))
        rej = registry().counter("serving_swaps_total")
        before = rej.value(model="default", outcome="canary_rejected",
                           precision="int8")
        try:
            gw.swap("default", quantize="int8")
            failures.append("tight-budget int8 swap was not rejected")
        except SwapError as e:
            if "canary" not in str(e):
                failures.append(f"rejection is not the canary's: {e}")
        after = rej.value(model="default", outcome="canary_rejected",
                          precision="int8")
        if after != before + 1:
            failures.append("canary_rejected{precision=int8} counter "
                            f"did not move ({before} -> {after})")
        if gw.pool.get("default").precision != "fp32":
            failures.append("precision changed on a rejected swap")
        got = np.asarray(gw.predict("default", golden))
        if not np.array_equal(got, ref):
            failures.append("old fp32 outputs not bitwise after the "
                            "rejected swap")
        gw.pool.shutdown()

    if failures:
        for f in failures:
            print(f"SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"quant swap smoke OK: {len(answered)} requests served across "
          f"a live int8 promotion (drift {drift:.2e} within "
          f"{DRIFT_BUDGET}), 0 compiles post-warm, and the tight-budget "
          "canary rejected with rollback")
    return 0


if __name__ == "__main__":
    sys.exit(main())
