"""Request flight-recorder smoke (docs/observability.md §"Request
flight recorder", ISSUE 15): the recorder's acceptance check end to end
over real HTTP, with the recorder armed via its env flag.

Builds a gateway serving a two-member fused group (tier critical) plus
a packed-admission attention model (tier standard), warms every bucket,
then — under a CompilationTracker — drives concurrent /predict traffic
from threaded clients. Asserts:

* every response is 200 and embeds a ``trace`` whose phases are
  monotonic, contiguous (non-overlapping by construction: each phase
  starts where the previous ended) and sum to the reported wall
  latency within 10%,
* ZERO XLA compile events after warmup (the recorder's device fence is
  an output-side np.asarray — it must not perturb the compiled path),
* the exemplar ring stays EMPTY under healthy traffic and captures
  exactly the one request delayed past its SLO via the ``delay:``
  chaos grammar at serve.forward — with the delay attributed to the
  ``device`` phase,
* GET /debug/requests?model=... filters server-side and GET /trace
  exports Chrome-traceable serve/* events.

A hard wall-clock alarm guards the whole run. Run by runtests.sh as a
separate step (no test_ prefix on purpose).
Usage: JAX_PLATFORMS=cpu python tests/smoke_request_trace.py
"""
import json
import os
import signal
import sys
import threading
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("DL4JTPU_FLIGHT_RECORDER", "32")  # noqa: E402

from deeplearning4j_tpu import (Adam, DenseLayer, InputType,  # noqa: E402
                                MultiLayerNetwork, NeuralNetConfiguration,
                                OutputLayer, RnnOutputLayer, WeightInit)
from deeplearning4j_tpu.nn.graph.graph import ComputationGraph  # noqa: E402
from deeplearning4j_tpu.nn.layers.attention import (  # noqa: E402
    SelfAttentionLayer)
from deeplearning4j_tpu.optimize.telemetry import (  # noqa: E402
    CompilationTracker)
from deeplearning4j_tpu.serving import (ServingGateway,  # noqa: E402
                                        flight_recorder)
from deeplearning4j_tpu.serving.model_pool import ModelPool  # noqa: E402
from deeplearning4j_tpu.serving.scheduler import DeviceScheduler  # noqa: E402
from deeplearning4j_tpu.utils import faults  # noqa: E402

HARD_TIMEOUT_S = 300
FEAT = 8
BUCKET = 16
# generous SLOs so healthy 1-core traffic never breaches: the ONLY
# exemplar this smoke may produce is the chaos-delayed request
TIER_SLO_MS = {"critical": 2000.0, "standard": 2000.0, "batch": 8000.0}
CHAOS_DELAY_MS = 2400  # > the critical SLO -> guaranteed exemplar
PHASES = list(flight_recorder.ONESHOT_PHASES)


def _alarm(_sig, _frm):
    print("SMOKE FAIL: hard wall-clock alarm fired — a request or the "
          "scheduler slot is wedged", file=sys.stderr)
    os._exit(2)


def graph_net(seed):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .add_layer("dense", DenseLayer(n_out=16, activation="tanh"),
                       "in")
            .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                          loss="mcxent"), "dense")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    return ComputationGraph(conf).init()


def packed_net(seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(1e-3)).list()
            .layer(SelfAttentionLayer(n_out=8, n_heads=2, causal=True,
                                      packed_segments=True))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss="mcxent"))
            .set_input_type(InputType.recurrent(FEAT)).build())
    return MultiLayerNetwork(conf).init()


def post(url, payload):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def check_trace(trace, failures, who):
    """Monotonic + contiguous phases that sum to wall within 10%."""
    phases = trace.get("phases") or []
    names = [p["phase"] for p in phases]
    if names != PHASES:
        failures.append(f"{who}: phases {names} != {PHASES}")
        return
    cursor = 0.0
    for p in phases:
        if p["ms"] < 0.0:
            failures.append(f"{who}: negative phase {p}")
            return
        if abs(p["start_ms"] - cursor) > 0.05:
            failures.append(
                f"{who}: phase {p['phase']} starts at {p['start_ms']:.3f}"
                f"ms, previous ended at {cursor:.3f}ms (overlap/gap)")
            return
        cursor = p["start_ms"] + p["ms"]
    wall = trace.get("wall_ms", 0.0)
    total = sum(p["ms"] for p in phases)
    # phases end at the unpack mark; wall adds only the caller wake-up
    if total > wall + 0.05 or (wall - total) > 0.10 * wall + 5.0:
        failures.append(
            f"{who}: phase sum {total:.2f}ms vs wall {wall:.2f}ms "
            "outside the 10% budget")


def main() -> int:
    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(HARD_TIMEOUT_S)
    failures = []

    pool = ModelPool(DeviceScheduler(tier_slo_ms=dict(TIER_SLO_MS)))
    gw = ServingGateway(pool)
    if not flight_recorder.is_enabled():
        print("SMOKE FAIL: env flag did not arm the recorder",
              file=sys.stderr)
        return 1

    gw.add_fused_group("duo", [("a", graph_net(1)), ("b", graph_net(2))],
                       batch_limit=8, tier="critical", weight=2.0)
    gw.add_model("p", packed_net(), tier="standard", batch_limit=8,
                 batch_timeout_ms=10.0, packed_admission=True,
                 pack_bucket=BUCKET)
    gw.warmup("a")
    gw.warmup("p", max_bucket=1, time_steps=BUCKET)

    rng = np.random.default_rng(7)
    fused_x = [rng.standard_normal((1 + i % 4, 4)).astype(np.float32)
               for i in range(6)]
    packed_x = [rng.standard_normal((1, 2 + i % 6, FEAT)).astype(np.float32)
                for i in range(6)]

    responses = []
    errors = []

    def client(i):
        nm = ("a", "b", "p")[i % 3]
        try:
            for j in range(6):
                x = packed_x[j] if nm == "p" else fused_x[j]
                code, body = post(gw.url + "/predict",
                                  {"model": nm, "features": x.tolist()})
                responses.append((nm, code, body))
        except Exception as e:  # noqa: BLE001 - smoke collects everything
            errors.append(e)

    with gw, CompilationTracker() as trk:
        ts = [threading.Thread(target=client, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)

        # healthy traffic: every response 200 with a well-formed trace,
        # and the exemplar ring is still empty
        for nm, code, body in responses:
            if code != 200 or body.get("status") != "ok":
                failures.append(
                    f"{nm}: {code}/{body.get('status')} under healthy "
                    "load")
            elif "trace" not in body:
                failures.append(f"{nm}: 200 response without a trace")
            else:
                check_trace(body["trace"], failures, nm)
        code, dbg = get(gw.url + "/debug/requests")
        if code != 200 or dbg.get("count") != 0:
            failures.append("exemplar ring not empty under healthy "
                            f"traffic: {dbg}")

        # chaos window: delay ONE request past its SLO at serve.forward
        # — it must become the only exemplar, attributed to `device`
        with faults.injected("serve.forward",
                             f"delay:1@{CHAOS_DELAY_MS}"):
            code, slow_body = post(gw.url + "/predict",
                                   {"model": "a",
                                    "features": fused_x[0].tolist()})
        if code != 200:
            failures.append(f"chaos-delayed request failed: {code}")
        slow_trace = slow_body.get("trace") or {}
        for nm, x in (("b", fused_x[1]), ("p", packed_x[0])):
            code, body = post(gw.url + "/predict",
                              {"model": nm, "features": x.tolist()})
            if code != 200:
                failures.append(f"post-chaos {nm} request failed: {code}")

        code, dbg = get(gw.url + "/debug/requests?model=a&tier=critical")
        exm = dbg.get("requests", [])
        if code != 200 or len(exm) != 1:
            failures.append("expected exactly the chaos-delayed request "
                            f"as exemplar, got {dbg.get('count')}")
        elif exm[0].get("id") != slow_trace.get("id"):
            failures.append(
                f"exemplar id {exm[0].get('id')} != delayed request "
                f"trace id {slow_trace.get('id')}")
        else:
            dev = sum(p["ms"] for p in exm[0]["phases"]
                      if p["phase"] == "device")
            if dev < 0.8 * CHAOS_DELAY_MS:
                failures.append(
                    f"delay at serve.forward attributed {dev:.1f}ms to "
                    f"device, expected >= {0.8 * CHAOS_DELAY_MS:.0f}ms")
        code, dbg = get(gw.url + "/debug/requests?model=p")
        if code != 200 or dbg.get("count") != 0:
            failures.append("model filter leaked foreign exemplars: "
                            f"{dbg}")

        with urllib.request.urlopen(gw.url + "/trace") as r:
            events = json.loads(r.read()).get("traceEvents", [])
        serve_evs = [e for e in events if e.get("cat") == "serve"]
        if not any(e.get("name") == "serve/device" for e in serve_evs):
            failures.append("/trace exports no serve/device spans")
    gw.pool.shutdown()
    flight_recorder.disable()

    if errors:
        failures.append(f"{len(errors)} client(s) errored: {errors[:3]}")
    if len(responses) != 36:
        failures.append(f"only {len(responses)}/36 requests completed")
    if trk.count != 0:
        failures.append(f"{trk.count} XLA compile(s) after warmup — the "
                        "recorder must not perturb the compiled path")

    signal.alarm(0)
    if failures:
        for f in failures:
            print(f"SMOKE FAIL: {f}", file=sys.stderr)
        return 1
    print(f"request-trace smoke OK: {len(responses)} traced requests "
          "across a fused pair + packed model, phases contiguous and "
          "within 10% of wall, 0 compiles after warmup, exemplar ring "
          "captured exactly the chaos-delayed request (device-phase "
          "attribution), /trace exports serve spans")
    return 0


if __name__ == "__main__":
    sys.exit(main())
