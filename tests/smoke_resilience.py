"""Kill-and-resume CI smoke (docs/robustness.md), wired into runtests.sh.

Three subprocesses driving tests/resilience_worker.py:

  1. a fresh training run SIGKILLed (via the ``checkpoint.write`` fault
     point's ``kill`` action) in the middle of its 13th checkpoint write
     — a torn temp file, never a torn checkpoint;
  2. an auto-resume run (``fit(..., checkpoint=mgr, resume=True)``) that
     restores the newest valid checkpoint and completes the schedule;
  3. an uninterrupted control run with the same seed and data order.

PASS requires the resumed run to reach bitwise-identical params and the
same iteration count as the control — crash-safe checkpointing, torn-file
skip, and RNG-stream restore verified end to end across real process
death.
"""
import os
import signal
import subprocess
import sys
import tempfile

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "resilience_worker.py")


def run(args, extra_env=None, expect_sigkill=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    r = subprocess.run([sys.executable, WORKER, *args], env=env,
                       capture_output=True, text=True, timeout=600)
    if expect_sigkill:
        assert r.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, got rc={r.returncode}\n{r.stderr}")
    else:
        assert r.returncode == 0, f"rc={r.returncode}\n{r.stderr}"
    return r


def main():
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "ckpt")
        out_resumed = os.path.join(tmp, "resumed.npz")
        out_straight = os.path.join(tmp, "straight.npz")

        run([ckpt, "/dev/null", "fresh"],
            extra_env={"DL4JTPU_FAULT_CHECKPOINT_WRITE": "kill:13"},
            expect_sigkill=True)
        n_ckpt = len([f for f in os.listdir(ckpt) if f.endswith(".zip")])
        print(f"PASS: fresh run SIGKILLed mid-checkpoint-write "
              f"({n_ckpt} checkpoint file(s) left on disk)")

        run([ckpt, out_resumed, "resume"])
        print("PASS: auto-resume completed the interrupted schedule")

        run([os.path.join(tmp, "ckpt2"), out_straight, "fresh"])

        a, b = np.load(out_resumed), np.load(out_straight)
        assert int(a["iteration"]) == int(b["iteration"]) == 24, (
            int(a["iteration"]), int(b["iteration"]))
        assert np.array_equal(a["params"], b["params"]), (
            "resumed params differ from the uninterrupted run")
        print("PASS: resumed run is bitwise-identical to the "
              "uninterrupted control (iteration 24)")


if __name__ == "__main__":
    main()
