"""Smoke: the bench scoreboard plane survives a wedged child.

Recreates the round-5 failure (a bench child that goes silent
mid-measurement) on demand with a `delay:` fault on `bench.child`,
then asserts the fail-safe path holds end to end:

* bench.py exits 0 anyway — a wedged child must not kill the artifact
* the artifact parses as JSON (the whole point: never `parsed: null`)
* the row is the in-process degraded fallback: `degraded: true`,
  `timeout: true`, a typed `"wedged"` failure string, and a real
  (reduced-config) measurement value > 0
* the registry snapshot is embedded with the bench families
  pre-registered — `bench_degraded_total` fired once and the never-hit
  statuses are present at 0, not absent
* the ledger got one schema-valid `status: "degraded"` row

Run: JAX_PLATFORMS=cpu python tests/smoke_scoreboard.py
Run by runtests.sh as a separate step (no test_ prefix on purpose).
"""
import json
import os
import signal
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from deeplearning4j_tpu.optimize import scoreboard  # noqa: E402

# Worst observed: ~6 s to wedge-kill the child + one reduced-config
# lenet_tiny compile in-process on a cold contended CPU rig.
HARD_TIMEOUT_S = 420

REQUIRED_FAMILIES = (
    'bench_rows_total{status="ok"}',
    'bench_rows_total{status="degraded"}',
    'bench_rows_total{status="wedged"}',
    'bench_rows_total{status="timeout"}',
    'bench_rows_total{status="failed"}',
    'bench_rows_total{status="dead_tunnel"}',
    "bench_degraded_total",
    "bench_regressions_total",
    "bench_baseline_corrupt_total",
)


def _alarm(signum, frame):
    print(f"SMOKE FAIL: scoreboard smoke exceeded {HARD_TIMEOUT_S}s "
          "hard timeout", flush=True)
    os._exit(2)


signal.signal(signal.SIGALRM, _alarm)
signal.alarm(HARD_TIMEOUT_S)


def main() -> int:
    failures = []
    with tempfile.TemporaryDirectory(prefix="dl4jtpu_smoke_sb_") as tmp:
        env = dict(os.environ)
        env.update(
            JAX_PLATFORMS="cpu",
            BENCH_REPEATS="1",
            # watchdog converts beat-then-silence to "wedged" in ~5 s
            BENCH_STALL_S="5",
            # beat 1 (start) passes; every later bench.child call wedges
            # for 600 s — life, then silence, the round-5 hang on demand
            DL4JTPU_FAULT_BENCH_CHILD="delay:2/1@600000",
            DL4JTPU_BENCH_PROBE="0",
            DL4JTPU_BENCH_LEDGER=os.path.join(tmp, "ledger.jsonl"),
            DL4JTPU_BENCH_BASELINE=os.path.join(tmp, "baseline.json"),
        )
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "lenet_tiny"],
            capture_output=True, text=True, env=env, cwd=REPO)

        if out.returncode != 0:
            failures.append(f"bench.py exited {out.returncode} "
                            f"(stderr tail: {out.stderr[-400:]!r})")
        row = None
        try:
            row = json.loads(out.stdout.strip().splitlines()[-1])
        except (ValueError, IndexError) as e:
            failures.append(f"artifact did not parse as JSON: {e} "
                            f"(stdout tail: {out.stdout[-400:]!r})")

        if row is not None:
            if row.get("degraded") is not True:
                failures.append(f"row.degraded is {row.get('degraded')!r},"
                                " wanted True")
            if row.get("timeout") is not True:
                failures.append(f"row.timeout is {row.get('timeout')!r},"
                                " wanted True")
            if "wedged" not in str(row.get("failure", "")):
                failures.append(f"row.failure {row.get('failure')!r} does"
                                " not name the wedge")
            if not (isinstance(row.get("value"), (int, float))
                    and row["value"] > 0):
                failures.append(f"row.value {row.get('value')!r} is not a"
                                " positive measurement")
            snap = row.get("metrics")
            if not isinstance(snap, dict):
                failures.append("row.metrics snapshot missing")
                snap = {}
            for fam in REQUIRED_FAMILIES:
                if fam not in snap:
                    failures.append(f"snapshot missing family {fam!r}")
            if snap.get("bench_degraded_total") != 1.0:
                failures.append(
                    "bench_degraded_total is "
                    f"{snap.get('bench_degraded_total')!r}, wanted 1.0")
            deg_key = 'bench_rows_total{status="degraded"}'
            if snap.get(deg_key) != 1.0:
                failures.append(f"{deg_key} is {snap.get(deg_key)!r}, "
                                "wanted 1.0")
            ok_key = 'bench_rows_total{status="ok"}'
            if snap.get(ok_key) != 0.0:
                failures.append(f"{ok_key} is {snap.get(ok_key)!r}, "
                                "wanted pre-registered 0.0")

        ledger_rows = scoreboard.read_ledger(
            os.path.join(tmp, "ledger.jsonl"))
        if len(ledger_rows) != 1:
            failures.append(f"ledger has {len(ledger_rows)} row(s), "
                            "wanted exactly 1")
        else:
            lrow = ledger_rows[0]
            if lrow.get("status") != "degraded":
                failures.append(f"ledger row status {lrow.get('status')!r},"
                                " wanted 'degraded'")
            problems = scoreboard.validate_row(lrow)
            if problems:
                failures.append(f"ledger row failed schema: {problems}")

    signal.alarm(0)
    if failures:
        print("SMOKE FAIL: bench scoreboard plane")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("SMOKE OK: wedged bench child -> schema-valid degraded "
          "artifact, exit 0, ledger row + registry snapshot intact")
    return 0


if __name__ == "__main__":
    sys.exit(main())
